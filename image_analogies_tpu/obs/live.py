"""Live telemetry plane (ISSUE 6 tentpole): scrapeable /metrics + /healthz.

Everything obs/ records is otherwise post-hoc (run-scoped JSONL read by
``ia report`` / ``ia trace`` after the run).  This module is the *live*
view: a lock-protected snapshot API over the in-process metrics registry
rendered as Prometheus text exposition (format 0.0.4), plus a tiny
loopback-only HTTP server exposing ``/metrics`` and ``/healthz``.

Three consumers share it:

- ``serve/http.py`` — the serving front end's ``GET /metrics`` and the
  enriched ``GET /healthz`` (queue depth, per-backend breaker state,
  worker liveness, inflight, uptime, devcache/HBM gauges, SLO burn).
- ``ia run/video/sweep --metrics-port N`` — the same exposition bound
  for the duration of a non-serve engine run (scrape the live registry
  mid-run instead of waiting for ``run_end``).
- ``ia metrics LOG [--port N]`` — post-hoc/sidecar mode: render the
  latest ``run_end`` snapshot of a run-log JSONL, once to stdout or
  re-read per scrape.

Contract (same as the rest of obs/): **no module-scope jax import**
(grep-locked) and a zero-cost disarmed path — with no active run,
:func:`snapshot_or_none` is one module-global read returning ``None``,
allocating nothing (asserted by test).
"""

from __future__ import annotations

import re
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from image_analogies_tpu.obs import metrics as _metrics

# Prometheus text exposition content type (format version 0.0.4).
CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_T0 = time.monotonic()  # process-level uptime anchor for default healthz

_EMPTY_SNAPSHOT: Dict[str, dict] = {"counters": {}, "gauges": {},
                                    "histograms": {}}


def snapshot_or_none() -> Optional[Dict[str, dict]]:
    """Lock-protected snapshot of the active registry, or ``None`` when
    observability is off.  The disabled path is one module-global read +
    branch — no dict, no lock, no allocation."""
    reg = _metrics.registry()
    if reg is None:
        return None
    return reg.snapshot()


# --- Prometheus text rendering ---------------------------------------------

_NAME_BAD = re.compile(r"[^a-zA-Z0-9_:]")


def prom_name(name: str) -> str:
    """Registry name -> valid Prometheus metric name.  Dots and other
    invalid characters become underscores; everything is namespaced under
    ``ia_`` so scraped metrics never collide with host exporters."""
    return "ia_" + _NAME_BAD.sub("_", name)


def _fmt(v: Any) -> str:
    f = float(v)
    if f != f or f in (float("inf"), float("-inf")):
        # never emit NaN/Inf samples: a single bad sample poisons the
        # whole scrape in strict parsers.  Empty-histogram min/max are
        # already normalized by Histogram.summary(); this is belt and
        # braces for any future gauge.
        return "0"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def render_prometheus(snap: Optional[Dict[str, dict]]) -> str:
    """Render a registry snapshot (or ``None``) as Prometheus text
    exposition.  Output is deterministic: sections in counter / gauge /
    histogram order, names sorted within each, one HELP + TYPE pair per
    metric.  The HELP line carries the original dotted registry name so
    operators (and the acceptance tests) can grep for ``serve.queue_depth``
    verbatim."""
    if snap is None:
        snap = _EMPTY_SNAPSHOT
    lines: List[str] = []

    for name in sorted(snap.get("counters", {})):
        pn = prom_name(name) + "_total"
        lines.append(f"# HELP {pn} counter {name}")
        lines.append(f"# TYPE {pn} counter")
        lines.append(f"{pn} {_fmt(snap['counters'][name])}")

    for name in sorted(snap.get("gauges", {})):
        pn = prom_name(name)
        lines.append(f"# HELP {pn} gauge {name}")
        lines.append(f"# TYPE {pn} gauge")
        lines.append(f"{pn} {_fmt(snap['gauges'][name])}")

    for name in sorted(snap.get("histograms", {})):
        summ = snap["histograms"][name]
        pn = prom_name(name)
        lines.append(f"# HELP {pn} histogram {name}")
        lines.append(f"# TYPE {pn} histogram")
        cum = 0
        # base-2 exponential buckets: key k holds values in [2^(k-1), 2^k)
        # (k=0 also absorbs v <= 0), so the bucket's le edge is 2^k.
        # An empty or single-sample histogram is well-defined here by
        # construction: no buckets -> just the +Inf line, _sum 0, _count 0.
        for k in sorted(int(x) for x in (summ.get("buckets") or {})):
            cum += int(summ["buckets"][str(k)])
            lines.append(f'{pn}_bucket{{le="{_fmt(float(2 ** k))}"}} {cum}')
        count = int(summ.get("count", 0))
        lines.append(f'{pn}_bucket{{le="+Inf"}} {count}')
        lines.append(f"{pn}_sum {_fmt(summ.get('sum', 0.0))}")
        lines.append(f"{pn}_count {count}")

    for name in sorted(snap.get("sketches") or {}):
        lines.extend(sketch_lines(name, snap["sketches"][name]))

    if not lines:
        lines.append("# no active run (observability disabled)")
    return "\n".join(lines) + "\n"


def sketch_lines(name: str, summ: Dict[str, Any],
                 label: str = "") -> List[str]:
    """Prometheus ``summary``-type exposition of one quantile-sketch
    summary: ``{quantile="0.999"}``-labeled samples plus _sum/_count.
    The ``_q`` suffix keeps the family distinct from the base-2
    histogram riding on the same registry name.  ``label`` (e.g.
    ``worker="w0"``) composes with the quantile label for the fleet
    view."""
    from image_analogies_tpu.obs import quantiles as _quantiles

    sk = _quantiles.QuantileSketch.from_summary(summ)
    pn = prom_name(name) + "_q"
    sep = "," if label else ""
    out: List[str] = []
    if not label:
        out.append(f"# HELP {pn} quantile sketch {name} "
                   f"(relative error {summ.get('alpha', '?')})")
        out.append(f"# TYPE {pn} summary")
    for q in _quantiles.EXPORT_QUANTILES:
        out.append(f'{pn}{{quantile="{_fmt(q)}"{sep}{label}}} '
                   f"{_fmt(sk.quantile(q))}")
    suffix = "{" + label + "}" if label else ""
    out.append(f"{pn}_sum{suffix} {_fmt(summ.get('sum', 0.0))}")
    out.append(f"{pn}_count{suffix} {int(summ.get('count', 0))}")
    return out


def metrics_text() -> str:
    """One-call convenience: exposition of the live registry."""
    return render_prometheus(snapshot_or_none())


# --- default healthz (non-serve runs) --------------------------------------


def default_health() -> Dict[str, Any]:
    """Generic liveness payload for non-serve expositions: is a run
    active, which run, how long has this process been up.  The serving
    front end replaces this with :meth:`serve.server.Server.health`."""
    from image_analogies_tpu.obs import ceilings as _ceilings
    from image_analogies_tpu.obs import trace as _trace

    return {
        "ok": True,
        "active_run": _metrics.registry() is not None,
        "run_id": _trace.current_run_id(),
        "uptime_s": round(time.monotonic() - _T0, 3),
        "vitals": _ceilings.read_proc_vitals(),
    }


# --- run-log (post-hoc / sidecar) snapshots --------------------------------


def snapshot_from_log(path: str) -> Optional[Dict[str, dict]]:
    """Latest ``run_end`` metrics snapshot found in a run-log JSONL, or
    ``None`` when no run has ended yet.  Re-read per scrape so a sidecar
    ``ia metrics --port`` serves fresh numbers as runs complete."""
    from image_analogies_tpu.obs import report as _report

    snap = None
    for rec in _report.load_records(path):
        if rec.get("event") == "run_end" and isinstance(rec.get("metrics"),
                                                        dict):
            snap = rec["metrics"]
    return snap


def health_from_log(path: str) -> Dict[str, Any]:
    from image_analogies_tpu.obs import report as _report

    records = _report.load_records(path)
    run_ids = []
    ended = set()
    for rec in records:
        rid = rec.get("run_id")
        if rid and rid not in run_ids:
            run_ids.append(rid)
        if rec.get("event") == "run_end" and rid:
            ended.add(rid)
    last = run_ids[-1] if run_ids else None
    return {
        "ok": bool(records),
        "records": len(records),
        "runs": len(run_ids),
        "last_run_id": last,
        "last_run_complete": last in ended if last else False,
    }


# --- loopback HTTP exposition ----------------------------------------------


def start_http_server(port: int,
                      snapshot_fn: Optional[Callable[[], Optional[dict]]]
                      = None,
                      health_fn: Optional[Callable[[], dict]] = None):
    """Bind a loopback-only exposition server on ``port`` (0 = ephemeral)
    and run it on a daemon thread.  Returns the ``ThreadingHTTPServer``;
    read the bound port from ``httpd.server_address[1]`` and stop it with
    :func:`stop_http_server`.

    The HTTP plumbing is imported lazily so importing ``obs.live`` stays
    cheap for callers that only render text."""
    import json
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    snap_fn = snapshot_fn or snapshot_or_none
    hz_fn = health_fn or default_health

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):  # noqa: A003 - silence stderr
            pass

        def _reply(self, code: int, body: bytes, ctype: str) -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802 - stdlib API
            import urllib.parse

            parts = urllib.parse.urlsplit(self.path)
            if parts.path == "/metrics":
                t0 = time.perf_counter()
                _metrics.inc("obs.scrape.metrics.total")
                try:
                    self._reply(200, render_prometheus(snap_fn()).encode(),
                                CONTENT_TYPE)
                except Exception:  # noqa: BLE001 - counted, then raised
                    _metrics.inc("obs.scrape.errors")
                    _metrics.inc("obs.scrape.metrics.errors")
                    raise
                finally:
                    _metrics.observe("obs.scrape.metrics.duration_ms",
                                     (time.perf_counter() - t0) * 1e3)
            elif parts.path == "/timeline":
                from image_analogies_tpu.obs import timeline as _timeline

                t0 = time.perf_counter()
                _metrics.inc("obs.scrape.timeline.total")
                try:
                    query = urllib.parse.parse_qs(parts.query)
                    window = (query.get("window") or [None])[0]
                    doc = _timeline.snapshot_json(
                        float(window) if window is not None else None)
                    self._reply(200, json.dumps(doc).encode(),
                                "application/json")
                except (KeyError, ValueError) as exc:
                    _metrics.inc("obs.scrape.errors")
                    _metrics.inc("obs.scrape.timeline.errors")
                    self._reply(400, json.dumps(
                        {"error": "bad_window",
                         "detail": str(exc)}).encode(),
                        "application/json")
                finally:
                    _metrics.observe("obs.scrape.timeline.duration_ms",
                                     (time.perf_counter() - t0) * 1e3)
            elif parts.path == "/tenants":
                from image_analogies_tpu.obs import ledger as _ledger

                t0 = time.perf_counter()
                _metrics.inc("obs.scrape.tenants.total")
                try:
                    self._reply(200,
                                json.dumps(_ledger.tenants_doc()).encode(),
                                "application/json")
                except Exception:  # noqa: BLE001 - counted, then raised
                    _metrics.inc("obs.scrape.errors")
                    _metrics.inc("obs.scrape.tenants.errors")
                    raise
                finally:
                    _metrics.observe("obs.scrape.tenants.duration_ms",
                                     (time.perf_counter() - t0) * 1e3)
            elif parts.path == "/archive/stats":
                from image_analogies_tpu.obs import archive as _archive

                t0 = time.perf_counter()
                _metrics.inc("obs.scrape.archive.total")
                try:
                    self._reply(200,
                                json.dumps(_archive.stats_doc()).encode(),
                                "application/json")
                except Exception:  # noqa: BLE001 - counted, then raised
                    _metrics.inc("obs.scrape.errors")
                    _metrics.inc("obs.scrape.archive.errors")
                    raise
                finally:
                    _metrics.observe("obs.scrape.archive.duration_ms",
                                     (time.perf_counter() - t0) * 1e3)
            elif parts.path == "/healthz":
                self._reply(200, json.dumps(hz_fn()).encode(),
                            "application/json")
            else:
                self._reply(404, b'{"error": "not_found"}',
                            "application/json")

    httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
    thread = threading.Thread(target=httpd.serve_forever,
                              name="ia-metrics-http", daemon=True)
    thread.start()
    httpd._ia_thread = thread  # kept for stop_http_server's join
    return httpd


def stop_http_server(httpd) -> None:
    httpd.shutdown()
    httpd.server_close()
    thread = getattr(httpd, "_ia_thread", None)
    if thread is not None:
        thread.join(timeout=5)
