"""Run-scoped observability (ISSUE 1 tentpole).

Three pieces, all process-local and dependency-free (no jax import):

- ``obs.metrics`` — a thread-safe registry of counters / gauges /
  histograms.  Instrumentation sites call the module-level helpers
  (``inc`` / ``add_gauge`` / ``observe``), which are a single bool check
  when no run is active — the engine's hot paths stay near-zero-cost
  with observability off (the 256^2 bench leg must not move).
- ``obs.trace`` — ``run_scope(params)`` opens a run (fresh ``run_id``,
  manifest record with config hash / backend / mesh / device kind / git
  rev, per-run metrics registry) and ``span(name, **attrs)`` emits
  nested wall-clock records; every JSONL record written through
  ``utils.logging.emit`` while a run is active is stamped with the
  ``run_id`` and a monotonically increasing ``seq``.
- ``obs.report`` — the ``ia report`` analyzer: reads a run-log JSONL
  and prints per-level timing (device vs host), counter totals
  (devcache hit rate, retries, kappa pick ratio), compile/HBM sections,
  and the run manifest; ``--json`` for the machine-readable dict.

Device-side layer (ISSUE 2 tentpole), imported lazily because it talks
to jax:

- ``obs.device`` — compile-aware shims around the jit/pjit entry points
  (``compile.count`` / ``compile.ms`` / ``compile.cache_hits`` /
  ``xla.flops`` / ``xla.bytes`` counters, per-program compile records)
  and per-level HBM watermarks (``hbm.peak_bytes.d<N>`` peak gauges).
- ``obs.export`` — the ``ia trace`` converter: run-log JSONL to
  Chrome/Perfetto trace.json (host / device / compile tracks).

Live telemetry plane (ISSUE 6 tentpole), jax-free like the core:

- ``obs.live`` — Prometheus text exposition over the live registry
  snapshot (``/metrics``) plus a loopback-only HTTP exposition server
  (``/metrics`` + ``/healthz``); used by serve/http.py, the
  ``--metrics-port`` engine flag, and ``ia metrics``.  Imported lazily
  by consumers (it pulls stdlib ``http.server`` on demand).
- ``obs.slo`` — rolling-window SLO attainment + fast/slow burn-rate
  tracking over deadline outcomes, exported as ``slo.*`` gauges and an
  ``slo`` section in ``ia report``.
- ``obs.trace.request_context`` — thread-ambient attrs (the serve
  request id) inherited by every span/record emitted inside the scope,
  so one request's records chain end to end in ``ia trace``.

Fleet-scoped plane (ISSUE 11 tentpole), jax-free like the core:

- ``obs.metrics.ObsScope`` — a bundled observability context (metrics
  registry + flight recorder + SLO slot + dump dir) resolved
  thread-ambiently by the one-liner helpers, so each fleet worker gets
  an ISOLATED registry while writes chain to the fleet parent and the
  call-site API stays unchanged.  ``scope_active`` pins a scope to the
  current thread; ``run_scope`` installs one process-wide.
- ``obs.fleet`` — label-only federation: merge N worker snapshots into
  one fleet view (counters sum, max-gauges max, histograms merge
  bucketwise) and render ``worker="<wid>"``-labeled Prometheus text;
  ``snapshot_from_exposition`` recovers a snapshot from a remote
  worker's scrape, so the merge is transport-agnostic.
- ``obs.recorder`` — per-scope flight recorder: a bounded ring of
  recent records, dumped as a SEALED blackbox JSON into the worker's
  journal dir on process death / breaker trip / watchdog timeout;
  ``ia blackbox <dir>`` renders the last seconds before a crash.
"""

from image_analogies_tpu.obs import metrics, trace  # noqa: F401
from image_analogies_tpu.obs.metrics import (  # noqa: F401
    ObsScope,
    current_scope,
    registry,
    scope_active,
    snapshot,
)
from image_analogies_tpu.obs.trace import (  # noqa: F401
    current_run_id,
    run_scope,
    span,
)
