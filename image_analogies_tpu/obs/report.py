"""`ia report` — turn a run-log JSONL into an answer.

Reads the records ``utils.logging.emit`` wrote (level stats, spans,
manifest, run_end metrics snapshot) and prints, per run:

- the run manifest (config hash, backend, strategy, mesh, device, git rev)
- a per-level timing breakdown: wall (from ``span`` records) vs device
  (the level stat's ``ms`` / ``enqueue_ms``) vs host (wall - device)
- counter totals: devcache hit rate + upload bytes, retries, psum-gather
  bytes, and the kappa coherence-vs-approx pick ratio
- the slowest spans

Works on both solo-run logs (``create_image_analogy``: one stat record
per level with device timing) and sharded-run logs (``_sharded_phase``:
per-frame records with no timing — wall comes from the mesh level spans,
coherence from the phase-end ``coherence_ratios`` summary).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Tuple


def load_records(path: str) -> List[Dict[str, Any]]:
    recs = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue  # tolerate truncated tail lines (preempted run)
            if isinstance(rec, dict):
                recs.append(rec)
    return recs


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024.0
    return f"{n:.1f} GiB"


def _is_level_stat(rec: Dict[str, Any]) -> bool:
    return ("level" in rec and "event" not in rec
            and ("db_rows" in rec or "pixels" in rec))


def analyze(records: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Aggregate one run's records (already filtered to a single run_id)."""
    manifest = next((r for r in records if r.get("event") == "run_manifest"),
                    None)
    run_end = next((r for r in records if r.get("event") == "run_end"), None)
    spans = [r for r in records if r.get("event") == "span"]
    stats = [r for r in records if _is_level_stat(r)]
    retries = [r for r in records if r.get("event") == "level_retry"]
    tune_resolved = [r for r in records if r.get("event") == "tune_resolved"]
    tune_errors = [r for r in records if r.get("event") in
                   ("tune_store_error", "tune_env_error")]
    coh_summaries = [r for r in records
                     if r.get("event") == "coherence_ratios"]

    # --- per-(phase, level) rows -----------------------------------------
    levels: Dict[Tuple[Optional[str], int], Dict[str, Any]] = {}

    def row(phase, level):
        key = (phase, level)
        if key not in levels:
            levels[key] = {"phase": phase, "level": level, "frames": 0,
                           "wall_ms": 0.0, "device_ms": 0.0, "pixels": 0,
                           "db_rows": 0, "coh_px": 0.0, "coh_known_px": 0}
        return levels[key]

    for st in stats:
        r = row(st.get("phase"), int(st["level"]))
        r["frames"] += 1
        r["pixels"] += int(st.get("pixels", 0))
        r["db_rows"] = max(r["db_rows"], int(st.get("db_rows", 0)))
        # device time: real compute under level_sync, enqueue otherwise
        r["device_ms"] += float(st.get("ms", st.get("enqueue_ms", 0.0)))
        if "total_ms" in st:
            r["wall_ms"] += float(st["total_ms"])
        if "coherence_ratio" in st and st.get("pixels"):
            r["coh_px"] += float(st["coherence_ratio"]) * int(st["pixels"])
            r["coh_known_px"] += int(st["pixels"])

    # sharded phase-end summaries carry the deferred coherence ratios the
    # streamed per-frame records omitted; join on (phase, level, frame)
    px_by_plf = {(st.get("phase"), int(st["level"]), st.get("frame")):
                 int(st.get("pixels", 0)) for st in stats}
    for summ in coh_summaries:
        phase = summ.get("phase")
        for key, ratio in (summ.get("ratios") or {}).items():
            try:
                lv_s, fr_s = key.split("_")
                lv, fr = int(lv_s[1:]), int(fr_s[1:])
            except (ValueError, IndexError):
                continue
            px = px_by_plf.get((phase, lv, fr))
            if px:
                r = row(phase, lv)
                r["coh_px"] += float(ratio) * px
                r["coh_known_px"] += px

    # level spans override the stat-side wall: they bracket the full host
    # iteration (features + scan + checkpoint io), and on the sharded path
    # they are the ONLY timing signal
    span_wall: Dict[Tuple[Optional[str], int], float] = {}
    for sp in spans:
        if sp.get("name") == "level" and "level" in sp:
            k = (sp.get("phase"), int(sp["level"]))
            span_wall[k] = span_wall.get(k, 0.0) + float(sp.get("wall_ms", 0))
    for k, wall in span_wall.items():
        row(k[0], k[1])["wall_ms"] = wall

    for r in levels.values():
        r["host_ms"] = max(r["wall_ms"] - r["device_ms"], 0.0) \
            if r["wall_ms"] else 0.0
        r["coherence_ratio"] = (r["coh_px"] / r["coh_known_px"]
                                if r["coh_known_px"] else None)

    # --- counters ---------------------------------------------------------
    counters: Dict[str, float] = {}
    if run_end:
        counters.update((run_end.get("metrics") or {}).get("counters", {}))
    # retries are visible even without the metrics toggle (failure.py
    # always emits the level_retry event)
    counters.setdefault("level_retry", 0)
    counters["level_retry"] = max(counters["level_retry"], len(retries))

    total_coh_px = sum(r["coh_px"] for r in levels.values())
    total_known_px = sum(r["coh_known_px"] for r in levels.values())

    hits = counters.get("devcache.hits", 0)
    misses = counters.get("devcache.misses", 0)

    # --- compile / XLA cost (obs.device shims) ----------------------------
    compiles = [r for r in records if r.get("event") == "compile"]
    compile_info: Optional[Dict[str, Any]] = None
    if compiles or counters.get("compile.count"):
        level_flops: Dict[int, float] = {}
        for cr in compiles:
            if "level" in cr and cr.get("flops"):
                lv = int(cr["level"])
                level_flops[lv] = level_flops.get(lv, 0) + float(cr["flops"])
        compile_info = {
            "count": int(counters.get("compile.count", len(compiles))),
            "cache_hits": int(counters.get("compile.cache_hits", 0)),
            "total_ms": float(counters.get(
                "compile.ms",
                sum(float(c.get("ms", 0.0)) for c in compiles))),
            "flops": float(counters.get("xla.flops", 0.0)),
            "bytes": float(counters.get("xla.bytes", 0.0)),
            "programs": [{k: c[k] for k in ("name", "ms", "flops", "bytes",
                                            "level", "phase", "ok")
                          if k in c} for c in compiles],
            "level_flops": level_flops,
        }

    # --- tuned-geometry provenance (tune/resolve.py records) --------------
    tune_info: Optional[Dict[str, Any]] = None
    if (tune_resolved or tune_errors
            or any(k.startswith("tune.") for k in counters)
            or (manifest and "tune_store" in manifest)):
        tune_info = {
            "store": (manifest or {}).get("tune_store"),
            "store_entries": (manifest or {}).get("tune_entries"),
            "store_hits": int(counters.get("tune.store_hits", 0)),
            "packaged": int(counters.get("tune.packaged", 0)),
            "fallbacks": int(counters.get("tune.fallbacks", 0)),
            "env_overrides": int(counters.get("tune.env_overrides", 0)),
            "errors": len(tune_errors),
            "configs": [{k: r[k] for k in
                         ("key", "tile_rows", "packed_tile_cap",
                          "packed_vmem_limit", "origin") if k in r}
                        for r in tune_resolved],
        }

    # --- serving section (serve_request records + serve.* counters) -------
    serve_reqs = [r for r in records if r.get("event") == "serve_request"]
    serve_info: Optional[Dict[str, Any]] = None
    if serve_reqs or any(k.startswith("serve.") for k in counters):
        done = [r for r in serve_reqs
                if r.get("status") in ("ok", "degraded")]
        lat = sorted(float(r.get("total_ms", 0.0)) for r in done)

        def pct(q):
            if not lat:
                return None
            return lat[min(len(lat) - 1,
                           int(round(q / 100.0 * (len(lat) - 1))))]

        batch_hist: Dict[int, int] = {}
        for r in done:
            bs = int(r.get("batch_size", 1))
            batch_hist[bs] = batch_hist.get(bs, 0) + 1
        accepted = int(counters.get("serve.accepted", len(serve_reqs)))
        rejected = int(counters.get("serve.rejected", 0))
        offered = accepted + rejected
        serve_info = {
            "accepted": accepted,
            "rejected": rejected,
            "reject_rate": (rejected / offered) if offered else 0.0,
            "completed": int(counters.get("serve.completed", len(done))),
            "degraded": int(counters.get(
                "serve.degraded",
                sum(1 for r in done if r.get("status") == "degraded"))),
            "timeouts": int(counters.get(
                "serve.timeouts",
                sum(1 for r in serve_reqs
                    if r.get("status") == "timeout"))),
            "errors": int(counters.get("serve.errors", 0)),
            "p50_ms": pct(50),
            "p95_ms": pct(95),
            "batch_size_hist": {str(k): v
                                for k, v in sorted(batch_hist.items())},
        }

    # --- tenant metering section (serve_cost records) ---------------------
    # One row per tenant (style == batcher exemplar sha1): request count,
    # dispatch-cost share, degrade/retry burden.  Built from the streamed
    # cost vectors so it works post-hoc on any journal-less run log.
    cost_recs = [r for r in records if r.get("event") == "serve_cost"]
    tenants_info: Optional[Dict[str, Any]] = None
    if cost_recs:
        by_tenant: Dict[str, Dict[str, Any]] = {}
        for cr in cost_recs:
            t = str(cr.get("tenant") or "?")
            row_t = by_tenant.setdefault(t, {
                "tenant": t, "requests": 0, "dispatch_ms": 0.0,
                "queue_ms": 0.0, "degraded": 0, "retries": 0,
                "wire_bytes": 0})
            row_t["requests"] += 1
            row_t["dispatch_ms"] += float(cr.get("dispatch_ms") or 0.0)
            row_t["queue_ms"] += float(cr.get("queue_ms") or 0.0)
            row_t["degraded"] += 1 if cr.get("degrade_levels") else 0
            row_t["retries"] += int(cr.get("retries") or 0)
            row_t["wire_bytes"] += int(cr.get("wire_bytes") or 0)
        total_cost_ms = sum(r["dispatch_ms"]
                            for r in by_tenant.values()) or 0.0
        rows_t = sorted(by_tenant.values(),
                        key=lambda r: (-r["dispatch_ms"], r["tenant"]))
        for r in rows_t:
            r["cost_share"] = (r["dispatch_ms"] / total_cost_ms
                               if total_cost_ms else 0.0)
        tenants_info = {"vectors": len(cost_recs),
                        "tenants": rows_t}

    # --- decision-attribution section (serve_decision + counters) ---------
    decision_recs = [r for r in records
                     if r.get("event") == "serve_decision"]
    decisions_info: Optional[Dict[str, Any]] = None
    if decision_recs or any(k.startswith("serve.decision.")
                            for k in counters):
        by_sv: Dict[str, int] = {}
        for dr in decision_recs:
            key = (f"{dr.get('site', '?')}:{dr.get('verdict', '?')}"
                   + (f"({dr['cause']})" if dr.get("cause") else ""))
            by_sv[key] = by_sv.get(key, 0) + 1
        by_verdict = {k.split("serve.decision.", 1)[1]: int(v)
                      for k, v in counters.items()
                      if k.startswith("serve.decision.")}
        decisions_info = {"records": len(decision_recs),
                          "by_site_verdict": by_sv,
                          "by_verdict": by_verdict}

    # --- catalog section (catalog.* counters + prefetch records) ----------
    # The exemplar catalog's tier ledger: per-tier hit/miss funnel
    # (HBM -> host -> disk -> cold build), quarantine + chaos-eviction
    # accounting, and the ring-placement prefetch summary.
    prefetch_recs = [r for r in records
                     if r.get("event") == "catalog_prefetch"]
    hists: Dict[str, Any] = {}
    if run_end:
        hists.update((run_end.get("metrics") or {}).get("histograms", {}))
    catalog_info: Optional[Dict[str, Any]] = None
    if prefetch_recs or any(k.startswith("catalog.") for k in counters):
        def _tier(name):
            h = int(counters.get(f"catalog.{name}.hits", 0))
            m = int(counters.get(f"catalog.{name}.misses", 0))
            return {"hits": h, "misses": m,
                    "hit_rate": (h / (h + m)) if (h + m) else None}

        cold = hists.get("catalog.cold_start_ms") or {}
        catalog_info = {
            "hbm": _tier("hbm"),
            "host": _tier("host"),
            "disk": _tier("disk"),
            "builds": int(counters.get("catalog.builds", 0)),
            "build_ms": {k: cold[k] for k in
                         ("count", "min", "max", "mean") if k in cold},
            "quarantined": int(counters.get("catalog.quarantined", 0)),
            "chaos_evictions": int(counters.get("catalog.chaos_evictions",
                                                0)),
            "host_evictions": int(counters.get("catalog.host.evictions",
                                               0)),
            "host_evicted_bytes": int(counters.get(
                "catalog.host.evicted_bytes", 0)),
            "disk_read_bytes": int(counters.get("catalog.disk.read_bytes",
                                                0)),
            "disk_write_bytes": int(counters.get("catalog.disk.write_bytes",
                                                 0)),
            "warmed": int(counters.get("catalog.warmed", 0)),
            "prefetch_styles": int(counters.get("catalog.prefetch.styles",
                                                0)),
            "prefetch_bytes": int(counters.get("catalog.prefetch.bytes",
                                               0)),
            "host_bytes": float(((run_end or {}).get("metrics") or {})
                                .get("gauges", {})
                                .get("catalog.host.bytes", 0.0)),
            # each fleet-join prefetch placement, in order
            "prefetch_events": [
                {k: r[k] for k in ("style", "worker", "entries", "bytes")
                 if k in r} for r in prefetch_recs],
        }

    # --- fleet section (router.* counters + router_* records) -------------
    handoff_recs = [r for r in records
                    if r.get("event") == "router_handoff"]
    router_info: Optional[Dict[str, Any]] = None
    if handoff_recs or any(k.startswith("router.") for k in counters):
        routed = {k.split("router.routed.", 1)[1]: int(v)
                  for k, v in counters.items()
                  if k.startswith("router.routed.")}
        codecs = {k.split("router.wire.", 1)[1]: int(v)
                  for k, v in counters.items()
                  if k.startswith("router.wire.")}
        router_info = {
            "requests": int(counters.get("router.requests", 0)),
            "routed": routed,
            "spills": int(counters.get("router.spills", 0)),
            "hop_faults": int(counters.get("router.hop_faults", 0)),
            "rejected": int(counters.get("router.rejected", 0)),
            "deaths": int(counters.get("router.deaths", 0)),
            "handoffs": int(counters.get("router.handoffs", 0)),
            "rechained": int(counters.get("router.rechained", 0)),
            "resubmitted": int(counters.get("router.resubmitted", 0)),
            "wire_bytes": int(counters.get("router.wire_bytes", 0)),
            "codecs": codecs,
            # each journal handoff, in order
            "handoff_events": [
                {k: r[k] for k in ("worker", "generation", "recovered")
                 if k in r} for r in handoff_recs],
        }

    # --- chaos section (chaos_inject records + chaos.* counters) ----------
    # The reconciliation ledger: injections on the left, the recovery
    # counters they caused on the right.  A drill (or an operator reading
    # a run log) checks the two sides account for each other.
    chaos_injects = [r for r in records if r.get("event") == "chaos_inject"]
    chaos_info: Optional[Dict[str, Any]] = None
    if chaos_injects or any(k.startswith("chaos.") for k in counters):
        by_site: Dict[str, int] = {}
        by_kind: Dict[str, int] = {}
        for name, v in counters.items():
            if name.startswith("chaos.site."):
                by_site[name.split("chaos.site.", 1)[1]] = int(v)
            elif name.startswith("chaos.injected."):
                by_kind[name.split("chaos.injected.", 1)[1]] = int(v)
        for cr in chaos_injects:  # records fill in when counters are off
            by_site.setdefault(str(cr.get("site")), 0)
            by_kind.setdefault(str(cr.get("kind")), 0)
        chaos_info = {
            "injected": int(counters.get("chaos.injected",
                                         len(chaos_injects))),
            "by_site": by_site,
            "by_kind": by_kind,
            "recovery": {
                "level_retry": int(counters.get("level_retry", 0)),
                "retry_exhausted": int(counters.get("retry.exhausted", 0)),
                "watchdog_timeouts": int(counters.get("watchdog.timeouts",
                                                      0)),
                "ckpt_quarantined": int(counters.get("ckpt.quarantined", 0)),
                "worker_crashes": int(counters.get("serve.worker_crashes",
                                                   0)),
                "requeued": int(counters.get("serve.requeued", 0)),
                "breaker_trips": int(counters.get("serve.breaker.trips", 0)),
            },
        }

    # --- soak section (soak/driver.py "soak_kill" records + the
    # in-replace autocompact counter): which workers the harness shot,
    # at which request, and how many corpse journals got offline-
    # compacted before their replacements opened them.
    soak_kills = [r for r in records if r.get("event") == "soak_kill"]
    soak_info: Optional[Dict[str, Any]] = None
    if soak_kills or counters.get("serve.journal.autocompact") \
            or counters.get("serve.journal.autocompact_refused"):
        soak_info = {
            "kills": [{k: r[k] for k in ("worker", "request") if k in r}
                      for r in soak_kills],
            "autocompacted": int(
                counters.get("serve.journal.autocompact", 0)),
            "autocompact_skipped": int(
                counters.get("serve.journal.autocompact_skipped", 0)),
            "autocompact_refused": int(
                counters.get("serve.journal.autocompact_refused", 0)),
        }

    # --- durability section (serve.journal.* counters + recovery records) -
    recoveries = [r for r in records if r.get("event") == "serve_recovery"]
    journal_info: Optional[Dict[str, Any]] = None
    if recoveries or any(k.startswith("serve.journal.") for k in counters):
        journal_info = {
            "admitted": int(counters.get("serve.journal.admitted", 0)),
            "dispatched": int(counters.get("serve.journal.dispatched", 0)),
            "done": int(counters.get("serve.journal.done", 0)),
            "rejected": int(counters.get("serve.journal.rejected", 0)),
            "poisoned": int(counters.get("serve.journal.poisoned", 0)),
            "replayed": int(counters.get("serve.journal.replayed", 0)),
            "deduped": int(counters.get("serve.journal.deduped", 0)),
            "quarantined": int(counters.get("serve.journal.quarantined", 0)),
            "poison_sheds": int(counters.get("serve.poisoned", 0)),
            "process_deaths": int(counters.get("serve.process_deaths", 0)),
            # flight-recorder seals (obs/recorder.py): how many black
            # boxes the death paths dumped during this run
            "blackbox_dumps": int(counters.get("obs.blackbox.dumps", 0)),
            # each restart's replay summary, in order
            "recoveries": [{k: r[k] for k in
                            ("entries", "replayed", "poisoned", "done",
                             "unrecoverable", "quarantined") if k in r}
                           for r in recoveries],
        }

    # --- per-device HBM peaks (run_end gauges + streamed hbm records) -----
    gauges: Dict[str, float] = {}
    if run_end:
        gauges.update((run_end.get("metrics") or {}).get("gauges", {}))
    hbm: Dict[str, float] = {
        name.split("hbm.peak_bytes.", 1)[1]: float(v)
        for name, v in gauges.items() if name.startswith("hbm.peak_bytes.")}
    for hr in (r for r in records if r.get("event") == "hbm"):
        for dev, v in (hr.get("peaks") or {}).items():
            hbm[dev] = max(hbm.get(dev, 0.0), float(v))

    # --- resource-ceiling section (obs/ceilings.py trend watchdogs) -------
    # Each ceiling_alarm record carries the robust (Theil-Sen) slope that
    # crossed its per-series growth threshold; the frozen run_end gauges
    # show where the process's vitals ended up.
    ceiling_recs = [r for r in records if r.get("event") == "ceiling_alarm"]
    ceilings_info: Optional[Dict[str, Any]] = None
    if ceiling_recs or any(k.startswith("obs.ceiling.") for k in counters):
        by_series = {k.split("obs.ceiling.", 1)[1]: int(v)
                     for k, v in counters.items()
                     if k.startswith("obs.ceiling.")
                     and k != "obs.ceiling.alarms"}
        ceilings_info = {
            "alarms": int(counters.get("obs.ceiling.alarms",
                                       len(ceiling_recs))),
            "by_series": by_series,
            "vitals": {k: gauges[k] for k in
                       ("proc.rss_bytes", "proc.open_fds", "proc.threads")
                       if gauges.get(k) is not None},
            # each alarm, in order
            "events": [{k: r[k] for k in
                        ("series", "slope_per_s", "threshold_per_s",
                         "value") if k in r} for r in ceiling_recs],
        }

    # --- pipeline-overlap section (driver pipeline.* gauges/counters) -----
    pipeline_info: Optional[Dict[str, Any]] = None
    if ("pipeline.host_gap_ms" in gauges
            or any(k.startswith("pipeline.") for k in counters)):
        gap = gauges.get("pipeline.host_gap_ms")
        prep = gauges.get("pipeline.prep_ms")
        hidden = gauges.get("pipeline.host_hidden_ms")
        pipeline_info = {
            # host time between successive level dispatches — the window
            # prefetch tries to hide; recorded even on sequential runs
            "host_gap_ms": gap,
            "prep_ms": prep,
            "wait_ms": gauges.get("pipeline.wait_ms"),
            "host_hidden_ms": hidden,
            "levels_prepped": int(counters.get("pipeline.levels_prepped",
                                               0)),
            "donated_levels": int(counters.get("pipeline.donated_levels",
                                               0)),
            "prefetch_errors": int(counters.get("pipeline.prefetch_errors",
                                                0)),
            # fraction of the prefetch worker's host time that the device
            # program absorbed (1.0 = fully overlapped)
            "hidden_fraction": (hidden / prep
                                if hidden is not None and prep else None),
        }

    # --- SLO section (obs/slo.py counters + run_end gauges) ---------------
    slo_info: Optional[Dict[str, Any]] = None
    if "slo.deadlined" in counters or "slo.target" in gauges:
        deadlined = int(counters.get("slo.deadlined", 0))
        violations = int(counters.get("slo.violations", 0))
        slo_info = {
            "target": gauges.get("slo.target"),
            "deadlined": deadlined,
            "violations": violations,
            # lifetime attainment from counters; the rolling-window view
            # lives in the gauges below (frozen at run_end)
            "attainment": ((deadlined - violations) / deadlined
                           if deadlined else None),
            "burn_rate_fast": gauges.get("slo.burn_rate.fast"),
            "burn_rate_slow": gauges.get("slo.burn_rate.slow"),
        }

    # --- batched-engine section (batch.* counters + lane records) ---------
    lane_recs = [r for r in records if r.get("event") == "serve_batch_lane"]
    batch_info: Optional[Dict[str, Any]] = None
    if lane_recs or any(k.startswith("batch.") for k in counters):
        fallbacks = {k.split("batch.fallback_sequential.", 1)[1]: int(v)
                     for k, v in counters.items()
                     if k.startswith("batch.fallback_sequential.")}
        batch_info = {
            "launches": int(counters.get("batch.launches", 0)),
            "lanes": int(counters.get("batch.lanes", 0)),
            "lane_faults": int(counters.get("batch.lane_faults", 0)),
            # finest-level dead-row fraction of the last admitted launch
            # (frozen at run_end); 0 when every member filled its bucket
            "pad_waste_frac": gauges.get("batch.pad_waste_frac"),
            "fallbacks": fallbacks,
        }

    # --- ANN section (ann.* counters + gate/prefilter records) ------------
    # The two-stage matcher's ledger: the parity gate's verdicts, each
    # level's prefilter engagement with its basis source, sealed-artifact
    # integrity (quarantines + rebuilds), and the exact-fallback count
    # that accounts for every request the matcher declined.
    gate_recs = [r for r in records if r.get("event") == "ann_gate"]
    engage_recs = [r for r in records if r.get("event") == "ann_prefilter"]
    ann_info: Optional[Dict[str, Any]] = None
    if (gate_recs or engage_recs
            or any(k.startswith("ann.") for k in counters)):
        ann_info = {
            "prefilter_used": int(counters.get("ann.prefilter_used", 0)),
            "fallback_exact": int(counters.get("ann.fallback_exact", 0)),
            "gate_ok": int(counters.get("ann.gate_ok", 0)),
            "disabled_unexplained": int(counters.get(
                "ann.disabled_unexplained", 0)),
            "artifact_hits": int(counters.get("ann.artifact_hits", 0)),
            "artifacts_built": int(counters.get("ann.artifacts_built", 0)),
            "artifacts_rebuilt": int(counters.get(
                "ann.artifacts_rebuilt", 0)),
            "projection_built": int(counters.get(
                "ann.projection_built", 0)),
            "quarantined": int(counters.get("ann.quarantined", 0)),
            "chaos_corruptions": int(counters.get(
                "ann.chaos_corruptions", 0)),
            "artifact_write_bytes": int(counters.get(
                "ann.artifact_write_bytes", 0)),
            "top_m": gauges.get("ann.top_m"),
            "proj_dims": gauges.get("ann.proj_dims"),
            # each gate verdict, in order (one per device class+strategy)
            "gates": [{k: r[k] for k in
                       ("device", "strategy", "ok", "mismatches",
                        "unexplained") if k in r} for r in gate_recs],
            # each level's prefilter engagement, in order
            "engagements": [{k: r[k] for k in
                             ("level", "strategy", "source", "top_m",
                              "proj_dims", "db_rows") if k in r}
                            for r in engage_recs],
        }

    # --- cross-hop trace section (ambient trace ids on records) -----------
    # Every record stamped inside a request_context carries the trace id
    # the HTTP hop adopted (or the router minted); grouping by it shows
    # each request's whole journey — http -> router -> worker -> engine —
    # even when the hops wrote to two isolated worker registries.
    traced = [r for r in records if isinstance(r.get("trace"), str)
              and r.get("trace")]
    traces_info: Optional[List[Dict[str, Any]]] = None
    if traced:
        by_trace: Dict[str, List[Dict[str, Any]]] = {}
        for r in traced:
            by_trace.setdefault(r["trace"], []).append(r)
        traces_info = []
        for tid in by_trace:  # insertion order == first-seen order
            recs = by_trace[tid]
            traces_info.append({
                "trace": tid,
                "records": len(recs),
                "spans": sum(1 for r in recs if r.get("event") == "span"),
                "events": sorted({str(r.get("event") or r.get("name")
                                      or "record") for r in recs}),
                "workers": sorted({str(r["worker"]) for r in recs
                                   if r.get("worker")}),
                "requests": sorted({str(r["request"]) for r in recs
                                    if r.get("request")}),
            })

    return {
        "manifest": manifest,
        "run_end": run_end,
        "levels": [levels[k] for k in sorted(
            levels, key=lambda k: (str(k[0] or ""), -k[1]))],
        "counters": counters,
        "retries": len(retries),
        "kappa_pick_ratio": (total_coh_px / total_known_px
                             if total_known_px else None),
        "devcache_hit_rate": (hits / (hits + misses)
                              if (hits + misses) else None),
        "compile": compile_info,
        "tune": tune_info,
        "pipeline": pipeline_info,
        "serve": serve_info,
        "tenants": tenants_info,
        "decisions": decisions_info,
        "batch": batch_info,
        "ann": ann_info,
        "catalog": catalog_info,
        "router": router_info,
        "slo": slo_info,
        "ceilings": ceilings_info,
        "traces": traces_info,
        "journal": journal_info,
        "chaos": chaos_info,
        "soak": soak_info,
        "hbm": hbm or None,
        "spans": spans,
        "n_records": len(records),
    }


def render(an: Dict[str, Any], run_id: Optional[str] = None) -> str:
    out: List[str] = []
    w = out.append

    w(f"run {run_id or '(unstamped)'} — {an['n_records']} records")
    man = an["manifest"]
    if man:
        keys = ("config_hash", "backend", "strategy", "mesh", "levels",
                "device_kind", "device_count", "platform", "git_rev",
                "jax_version", "metrics")
        w("  manifest:")
        for k in keys:
            if k in man and man[k] is not None:
                w(f"    {k:<13} {man[k]}")

    if an["levels"]:
        w("  per-level timing (ms):")
        w(f"    {'phase':<8} {'lvl':>3} {'frames':>6} {'wall':>10} "
          f"{'device':>10} {'host':>10} {'pixels':>10} {'coh%':>6}")
        tot_wall = tot_dev = 0.0
        for r in an["levels"]:
            coh = (f"{100 * r['coherence_ratio']:.1f}"
                   if r["coherence_ratio"] is not None else "-")
            w(f"    {str(r['phase'] or '-'):<8} {r['level']:>3} "
              f"{r['frames']:>6} {r['wall_ms']:>10.1f} "
              f"{r['device_ms']:>10.1f} {r['host_ms']:>10.1f} "
              f"{r['pixels']:>10} {coh:>6}")
            tot_wall += r["wall_ms"]
            tot_dev += r["device_ms"]
        w(f"    {'total':<8} {'':>3} {'':>6} {tot_wall:>10.1f} "
          f"{tot_dev:>10.1f} {max(tot_wall - tot_dev, 0.0):>10.1f}")

    w("  counters:")
    c = an["counters"]
    if an["devcache_hit_rate"] is not None:
        w(f"    devcache      {int(c.get('devcache.hits', 0))} hits / "
          f"{int(c.get('devcache.misses', 0))} misses "
          f"(hit rate {100 * an['devcache_hit_rate']:.1f}%), "
          f"uploaded {_fmt_bytes(c.get('devcache.upload_bytes', 0))}")
    w(f"    retries       {an['retries']}")
    if an["kappa_pick_ratio"] is not None:
        w(f"    kappa picks   {100 * an['kappa_pick_ratio']:.1f}% coherence "
          f"/ {100 * (1 - an['kappa_pick_ratio']):.1f}% approx")
    if c.get("mesh.level_steps"):
        w(f"    mesh steps    {int(c['mesh.level_steps'])}, "
          f"psum-gather ~{_fmt_bytes(c.get('mesh.psum_gather_bytes', 0))}")
    if c.get("fetch.bytes"):
        w(f"    fetched       {_fmt_bytes(c['fetch.bytes'])}")
    shown = {"devcache.hits", "devcache.misses", "devcache.upload_bytes",
             "level_retry", "mesh.level_steps", "mesh.psum_gather_bytes",
             "fetch.bytes", "kappa.coherence_px", "kappa.total_px",
             "compile.count", "compile.ms", "compile.cache_hits",
             "xla.flops", "xla.bytes", "tune.store_hits", "tune.fallbacks",
             "tune.env_overrides", "tune.packaged"}
    # serve.*/chaos.* and the recovery counters render in their own
    # serving/chaos sections below
    rest = {k: v for k, v in c.items()
            if k not in shown and v
            and not k.startswith(("serve.", "chaos.", "watchdog.",
                                  "ckpt.", "retry.", "pipeline.",
                                  "router.", "batch.", "catalog.",
                                  "ann.", "obs.ceiling."))}
    for k in sorted(rest):
        w(f"    {k:<13} {rest[k]:g}")

    comp = an.get("compile")
    if comp:
        w("  compile:")
        w(f"    programs      {comp['count']} compiled / "
          f"{comp['cache_hits']} cache hits, total {comp['total_ms']:.1f} ms")
        if comp["flops"] or comp["bytes"]:
            w(f"    xla cost      {comp['flops']:.4g} flops executed, "
              f"{_fmt_bytes(comp['bytes'])} accessed")
        # achieved TFLOPs where BOTH a cost estimate and a device time
        # exist for the level (compile events carry one execution's flops;
        # the solo path runs each level program once per frame)
        dev_ms = {r["level"]: r["device_ms"] for r in an["levels"]
                  if r.get("device_ms")}
        for lv in sorted(comp["level_flops"], reverse=True):
            ms = dev_ms.get(lv)
            if ms:
                tf = comp["level_flops"][lv] / (ms * 1e9)
                w(f"    L{lv} achieved   ~{tf:.4g} TFLOP/s "
                  f"({comp['level_flops'][lv]:.3g} flops est / "
                  f"{ms:.1f} ms device)")

    tune = an.get("tune")
    if tune:
        w("  tune:")
        if tune.get("store"):
            w(f"    store         {tune['store']} "
              f"({tune.get('store_entries', 0)} entries)")
        w(f"    resolutions   {tune['store_hits']} store / "
          f"{tune.get('packaged', 0)} packaged / "
          f"{tune['fallbacks']} default / {tune['env_overrides']} env")
        if tune["errors"]:
            w(f"    errors        {tune['errors']} "
              "(corrupt store / bad env — defaults used)")
        for cfg in tune["configs"]:
            origins = ",".join(sorted(set(
                (cfg.get("origin") or {}).values())))
            w(f"    {cfg.get('key', '?'):<36} "
              f"tile_rows={cfg.get('tile_rows')} "
              f"cap={cfg.get('packed_tile_cap')} [{origins}]")

    pl = an.get("pipeline")
    if pl:
        w("  pipeline:")
        gap = pl.get("host_gap_ms")
        if gap is not None:
            w(f"    host gap      {gap:.1f} ms between level dispatches")
        if pl.get("prep_ms") is not None:
            hid = pl.get("host_hidden_ms") or 0.0
            frac = pl.get("hidden_fraction")
            w(f"    overlap       {pl['levels_prepped']} levels prepped, "
              f"{pl['prep_ms']:.1f} ms prep / {hid:.1f} ms hidden under "
              f"device"
              + (f" ({100 * frac:.0f}%)" if frac is not None else ""))
            w(f"    join wait     {pl.get('wait_ms', 0.0):.1f} ms")
        if pl.get("donated_levels"):
            w(f"    donation      {pl['donated_levels']} levels donated "
              "their chained B' buffer")
        if pl.get("prefetch_errors"):
            w(f"    prefetch errs {pl['prefetch_errors']} (swallowed — "
              "main path rebuilt cold)")

    srv = an.get("serve")
    if srv:
        w("  serving:")
        w(f"    admission     {srv['accepted']} accepted / "
          f"{srv['rejected']} rejected "
          f"(reject rate {100 * srv['reject_rate']:.1f}%)")
        w(f"    outcomes      {srv['completed']} completed, "
          f"{srv['degraded']} degraded, {srv['timeouts']} timeout, "
          f"{srv['errors']} error")
        if srv["p50_ms"] is not None:
            w(f"    latency       p50 {srv['p50_ms']:.1f} ms / "
              f"p95 {srv['p95_ms']:.1f} ms")
        if srv["batch_size_hist"]:
            hist = ", ".join(f"{k}x{v}" for k, v in
                             srv["batch_size_hist"].items())
            w(f"    batch sizes   {hist}  (size x count)")

    tn = an.get("tenants")
    if tn:
        w("  tenants:")
        w(f"    cost vectors  {tn['vectors']} recorded")
        for r in tn["tenants"][:12]:
            w(f"    {str(r['tenant'])[:12]:<13} {r['requests']:>5} reqs  "
              f"{100 * r['cost_share']:>5.1f}% cost  "
              f"{r['dispatch_ms']:>8.1f} ms dispatch  "
              f"{r['degraded']} degraded / {r['retries']} retries")
        if len(tn["tenants"]) > 12:
            w(f"    ... {len(tn['tenants']) - 12} more tenants")

    dec = an.get("decisions")
    if dec:
        w("  decisions:")
        verdicts = ", ".join(f"{k}x{v}" for k, v in
                             sorted(dec["by_verdict"].items()))
        w(f"    verdicts      {verdicts or '-'}  (verdict x count)")
        for key in sorted(dec["by_site_verdict"]):
            w(f"    {key:<36} {dec['by_site_verdict'][key]}")

    be = an.get("batch")
    if be:
        w("  batched engine:")
        launches, lanes = be["launches"], be["lanes"]
        w(f"    launches      {launches} device launches / {lanes} lanes"
          + (f" (mean {lanes / launches:.1f} lanes/launch)"
             if launches else ""))
        if be["pad_waste_frac"] is not None:
            w(f"    pad waste     {100 * be['pad_waste_frac']:.1f}% dead "
              "rows at the finest level")
        if be["lane_faults"]:
            w(f"    lane faults   {be['lane_faults']} isolated "
              "(surviving lanes completed)")
        if be["fallbacks"]:
            fb = ", ".join(f"{k}x{v}" for k, v in
                           sorted(be["fallbacks"].items()))
            w(f"    fallbacks     {fb}  (reason x count)")

    cat = an.get("catalog")
    if cat:
        w("  catalog:")

        def _tier_line(label, t):
            rate = (f" (hit rate {100 * t['hit_rate']:.1f}%)"
                    if t["hit_rate"] is not None else "")
            w(f"    {label:<13} {t['hits']} hits / {t['misses']} misses"
              + rate)

        _tier_line("hbm tier", cat["hbm"])
        _tier_line("host tier", cat["host"])
        _tier_line("disk tier", cat["disk"])
        bm = cat["build_ms"]
        w(f"    cold builds   {cat['builds']}"
          + (f" ({bm['mean']:.1f} ms mean / {bm['max']:.1f} ms max)"
             if bm.get("count") else ""))
        if cat["host_bytes"] or cat["host_evictions"]:
            w(f"    host tier     {_fmt_bytes(cat['host_bytes'])} resident, "
              f"{cat['host_evictions']} evictions "
              f"({_fmt_bytes(cat['host_evicted_bytes'])})")
        if cat["disk_read_bytes"] or cat["disk_write_bytes"]:
            w(f"    disk io       {_fmt_bytes(cat['disk_read_bytes'])} "
              f"read / {_fmt_bytes(cat['disk_write_bytes'])} written")
        if cat["quarantined"] or cat["chaos_evictions"]:
            w(f"    integrity     {cat['quarantined']} entries quarantined, "
              f"{cat['chaos_evictions']} chaos tier evictions")
        if cat["warmed"] or cat["prefetch_styles"]:
            w(f"    prefetch      {cat['warmed']} entries warmed, "
              f"{cat['prefetch_styles']} styles placed "
              f"({_fmt_bytes(cat['prefetch_bytes'])})")
        for pf in cat["prefetch_events"]:
            w(f"    placed        {pf.get('style', '?')} -> "
              f"{pf.get('worker', '?')} ({pf.get('entries', 0)} entries, "
              f"{_fmt_bytes(pf.get('bytes', 0))})")

    ann = an.get("ann")
    if ann:
        w("  ann matcher:")
        knobs = ""
        if ann["top_m"] is not None:
            knobs = (f" (top_m={int(ann['top_m'])}, "
                     f"proj_dims={int(ann['proj_dims'] or 0)})")
        w(f"    two-stage     {ann['prefilter_used']} levels prefiltered "
          f"/ {ann['fallback_exact']} exact fallbacks{knobs}")
        if ann["gate_ok"] or ann["disabled_unexplained"]:
            w(f"    parity gate   {ann['gate_ok']} ok / "
              f"{ann['disabled_unexplained']} refused "
              "(unexplained divergence)")
        sealed = ann["artifacts_built"] + ann["artifacts_rebuilt"]
        w(f"    bases         {ann['artifact_hits']} artifact hits / "
          f"{ann['projection_built']} device builds / {sealed} sealed "
          f"({_fmt_bytes(ann['artifact_write_bytes'])})")
        if ann["quarantined"] or ann["chaos_corruptions"]:
            w(f"    integrity     {ann['quarantined']} artifacts "
              f"quarantined, {ann['chaos_corruptions']} chaos corruptions")
        for g in ann["gates"]:
            w(f"    gate          {g.get('device', '?')} "
              f"{'ok' if g.get('ok') else 'REFUSED'} "
              f"(mismatches={g.get('mismatches', '?')}, "
              f"unexplained={g.get('unexplained', '?')})")

    rt = an.get("router")
    if rt:
        w("  fleet:")
        routed = ", ".join(f"{k}x{v}" for k, v in
                           sorted(rt["routed"].items()))
        w(f"    routing       {rt['requests']} requests -> "
          f"{routed or '-'}  (worker x count)")
        w(f"    resilience    {rt['spills']} spills, "
          f"{rt['hop_faults']} hop faults, {rt['rejected']} rejected")
        if rt["deaths"] or rt["handoffs"]:
            w(f"    handoff       {rt['deaths']} deaths -> "
              f"{rt['handoffs']} journal handoffs, "
              f"{rt['rechained']} futures rechained, "
              f"{rt['resubmitted']} resubmitted")
        for i, ho in enumerate(rt["handoff_events"]):
            rcv = ho.get("recovered") or {}
            w(f"    handoff {i:<5} {ho.get('worker', '?')} "
              f"gen {ho.get('generation', '?')}: "
              f"entries={rcv.get('entries', 0)} "
              f"replayed={rcv.get('replayed', 0)} "
              f"done={rcv.get('done', 0)} "
              f"poisoned={rcv.get('poisoned', 0)}")
        if rt["codecs"]:
            codecs = ", ".join(f"{k}x{v}" for k, v in
                               sorted(rt["codecs"].items()))
            w(f"    wire          {codecs} "
              f"({_fmt_bytes(rt['wire_bytes'])} framed)")

    slo = an.get("slo")
    if slo:
        w("  slo:")
        target = slo.get("target")
        attain = slo.get("attainment")
        if target is not None:
            w(f"    target        {100 * target:.2f}%")
        w(f"    deadlined     {slo['deadlined']} requests, "
          f"{slo['violations']} violations"
          + (f" (attainment {100 * attain:.2f}%)"
             if attain is not None else ""))
        bf, bs = slo.get("burn_rate_fast"), slo.get("burn_rate_slow")
        if bf is not None or bs is not None:
            w(f"    burn rate     fast {bf if bf is not None else '-'} / "
              f"slow {bs if bs is not None else '-'}  "
              "(1.0 = exactly on budget)")

    ce = an.get("ceilings")
    if ce:
        w("  ceilings:")
        series = ", ".join(f"{k}x{v}" for k, v in
                           sorted(ce["by_series"].items()))
        w(f"    alarms        {ce['alarms']}  ({series or '-'})")
        vit = ce["vitals"]
        if vit:
            parts = []
            if vit.get("proc.rss_bytes") is not None:
                parts.append(f"rss {_fmt_bytes(vit['proc.rss_bytes'])}")
            if vit.get("proc.open_fds") is not None:
                parts.append(f"{int(vit['proc.open_fds'])} fds")
            if vit.get("proc.threads") is not None:
                parts.append(f"{int(vit['proc.threads'])} threads")
            w(f"    vitals        {', '.join(parts)}")
        for ev in ce["events"]:
            w(f"    alarm         {ev.get('series', '?')}: "
              f"+{_fmt_bytes(ev.get('slope_per_s', 0))}/s over the "
              f"{_fmt_bytes(ev.get('threshold_per_s', 0))}/s ceiling "
              f"(at {_fmt_bytes(ev.get('value', 0))})")

    trs = an.get("traces")
    if trs:
        w("  traces:")
        for t in trs:
            w(f"    {t['trace']:<16} {t['records']} records / "
              f"{t['spans']} spans"
              f"  workers={','.join(t['workers']) or '-'}"
              f"  requests={','.join(t['requests']) or '-'}")

    jn = an.get("journal")
    if jn:
        w("  durability:")
        w(f"    journal       {jn['admitted']} admitted -> "
          f"{jn['done']} done, {jn['rejected']} rejected, "
          f"{jn['poisoned']} poisoned "
          f"({jn['dispatched']} dispatch attempts)")
        w(f"    exactly-once  {jn['deduped']} duplicate submissions "
          f"answered from the journal, {jn['poison_sheds']} poison sheds")
        if (jn["replayed"] or jn["process_deaths"] or jn["quarantined"]
                or jn["recoveries"]):
            w(f"    recovery      {jn['replayed']} replayed across "
              f"{len(jn['recoveries'])} restart(s), "
              f"{jn['process_deaths']} process deaths, "
              f"{jn['quarantined']} journal files quarantined")
        if jn.get("blackbox_dumps"):
            w(f"    blackbox      {jn['blackbox_dumps']} flight-recorder "
              f"dump(s) sealed (ia blackbox <journal-dir>)")
        for i, rcv in enumerate(jn["recoveries"]):
            w(f"    restart {i:<5} entries={rcv.get('entries', 0)} "
              f"replayed={rcv.get('replayed', 0)} "
              f"done={rcv.get('done', 0)} "
              f"poisoned={rcv.get('poisoned', 0)} "
              f"unrecoverable={rcv.get('unrecoverable', 0)}")

    cha = an.get("chaos")
    if cha:
        w("  chaos:")
        kinds = ", ".join(f"{k}x{v}" for k, v in
                          sorted(cha["by_kind"].items()))
        sites = ", ".join(f"{k}x{v}" for k, v in
                          sorted(cha["by_site"].items()))
        w(f"    injected      {cha['injected']}  ({kinds or '-'})")
        if sites:
            w(f"    sites         {sites}")
        rec = cha["recovery"]
        w(f"    recovery      {rec['level_retry']} retries "
          f"({rec['retry_exhausted']} exhausted), "
          f"{rec['watchdog_timeouts']} watchdog timeouts, "
          f"{rec['ckpt_quarantined']} ckpt quarantined")
        if rec["worker_crashes"] or rec["requeued"] or rec["breaker_trips"]:
            w(f"    containment   {rec['worker_crashes']} worker crashes, "
              f"{rec['requeued']} requeued, "
              f"{rec['breaker_trips']} breaker trips")

    soak = an.get("soak")
    if soak:
        w("  soak:")
        shots = ", ".join(
            f"{k.get('worker', '?')}@{k.get('request', '?')}"
            for k in soak["kills"])
        w(f"    kills         {len(soak['kills'])}  ({shots or '-'})")
        w(f"    autocompact   {soak['autocompacted']} corpse journal(s) "
          f"compacted in-replace, "
          f"{soak.get('autocompact_skipped', 0)} skipped "
          f"(single-segment), {soak['autocompact_refused']} refused")

    hbm = an.get("hbm")
    if hbm:
        w("  hbm peak:")
        for dev in sorted(hbm):
            w(f"    {dev:<13} {_fmt_bytes(hbm[dev])}")

    other = [sp for sp in an["spans"] if sp.get("name") != "level"]
    if other:
        agg: Dict[str, List[float]] = {}
        for sp in other:
            agg.setdefault(sp["name"], []).append(
                float(sp.get("wall_ms", 0)))
        w("  spans:")
        for name in sorted(agg, key=lambda n: -sum(agg[n])):
            v = agg[name]
            w(f"    {name:<20} n={len(v):<4} total {sum(v):>9.1f} ms")
    return "\n".join(out)


def _by_run(records: List[Dict[str, Any]]) \
        -> Dict[Optional[str], List[Dict[str, Any]]]:
    by_run: Dict[Optional[str], List[Dict[str, Any]]] = {}
    for rec in records:
        by_run.setdefault(rec.get("run_id"), []).append(rec)
    return by_run


def report(path: str) -> str:
    """Analyze a run-log JSONL; one section per run_id found in it."""
    records = load_records(path)
    if not records:
        return f"{path}: no records"
    sections = []
    by_run = _by_run(records)
    for run_id in by_run:  # insertion order == file order
        sections.append(render(analyze(by_run[run_id]), run_id))
    return "\n\n".join(sections)


def report_json(path: str) -> str:
    """Machine-readable `ia report --json`: the analyze() dict per run
    (manifest, levels, counters, compile/HBM sections), so bench/CI can
    diff runs without scraping the text renderer."""
    records = load_records(path)
    runs = []
    by_run = _by_run(records)
    for run_id in by_run:
        an = analyze(by_run[run_id])
        an["run_id"] = run_id
        runs.append(an)
    return json.dumps({"path": path, "runs": runs}, indent=2,
                      sort_keys=True, default=str)
