"""Device/compiler observability (ISSUE 2 tentpole).

:func:`instrument` wraps one jit/pjit entry point in a
:class:`JitShim` — a compile-aware cache keyed by the program signature
(pytree structure + per-leaf shape/dtype, i.e. the same information
jit's own dispatch cache keys on, including the static aux data of
registered pytrees like ``TpuLevelDB``).  On the first call of a key the
shim lowers and compiles ahead-of-time (``fn.lower(...).compile()``),
records the compile wall-time and — where the compiled artifact exposes
``cost_analysis()`` — the program's estimated FLOPs and bytes-accessed,
then caches the executable.  Subsequent calls of the same key count as
cache hits and dispatch the cached executable directly.  Counters flow
into the PR-1 metrics registry: ``compile.count``, ``compile.ms``,
``compile.cache_hits``, ``xla.flops``, ``xla.bytes`` (the xla.* totals
accumulate per EXECUTION, so they estimate work actually dispatched).
One ``{"event": "compile", ...}`` record is emitted per program, stamped
with the enclosing span's level/phase/frame so `ia report` can derive
achieved-TFLOPs per level.

:func:`record_hbm` samples ``device.memory_stats()`` into per-device
peak gauges (``hbm.peak_bytes.d<N>``) — backends that return None (CPU)
are tolerated silently.

PR-1 invariant: with no active run the shim's ``__call__`` is a single
module-bool check and a positional passthrough — no clock read, no
allocation in obs/ frames (covered by the zero-alloc disabled-path
test) — and ``record_hbm`` returns after the same bool check.  jax is
imported lazily and only on the active path; importing this module does
not force backend init.
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Any, Dict, Optional, Tuple

from image_analogies_tpu.obs import metrics as _metrics
from image_analogies_tpu.obs import trace as _trace
from image_analogies_tpu.utils import logging as _logging


def _leaf_sig(leaf: Any) -> Any:
    """Hashable signature of one pytree leaf: (shape, dtype) for array
    likes, the value itself for hashable scalars/statics, repr otherwise."""
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is not None and dtype is not None:
        return ("arr", tuple(shape), str(dtype))
    try:
        hash(leaf)
    except TypeError:
        return ("repr", repr(leaf))
    return leaf


def program_key(args: tuple, kwargs: Optional[dict]) -> tuple:
    """The (tree structure, leaf shapes/dtypes/statics) program key — the
    same facts jit's dispatch cache keys on, so a shim cache hit is a jit
    cache hit and a shim miss is a recompile."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs or {}))
    return (treedef, tuple(_leaf_sig(leaf) for leaf in leaves))


class JitShim:
    """Compile-aware wrapper around one jitted callable.

    ``static_argnums`` names the positions jit treats as static: the AOT
    executable is called with them stripped (a ``Compiled`` object takes
    only the dynamic args).  Any failure to lower/compile/dispatch falls
    back to the raw jitted callable — observability must never change
    what runs.
    """

    # __weakref__ so the shim can itself be re-wrapped by jax.jit (jit
    # keeps a weakref to its callable)
    __slots__ = ("fn", "name", "static_argnums", "_programs", "_lock",
                 "__weakref__")

    def __init__(self, fn: Any, name: str,
                 static_argnums: Tuple[int, ...] = ()):
        self.fn = fn
        self.name = name
        self.static_argnums = frozenset(static_argnums)
        # program key -> (compiled_or_None, cost_or_None); None compiled
        # means "AOT unusable for this key, call the raw fn"
        self._programs: Dict[tuple, Tuple[Any, Optional[Dict[str, float]]]] \
            = {}
        self._lock = threading.Lock()

    def __getattr__(self, item: str) -> Any:
        # delegate .lower / .clear_cache / _cache_size etc. to the jit fn
        return getattr(self.fn, item)

    def __call__(self, *args, **kwargs):
        if not _metrics._ACTIVE:
            if kwargs:
                return self.fn(*args, **kwargs)
            return self.fn(*args)
        return self._observed_call(args, kwargs)

    # --- active path -----------------------------------------------------

    def _dynamic_args(self, args: tuple) -> tuple:
        if not self.static_argnums:
            return args
        return tuple(a for i, a in enumerate(args)
                     if i not in self.static_argnums)

    def _observed_call(self, args: tuple, kwargs: dict):
        try:
            key = program_key(args, kwargs)
        except Exception:
            return self.fn(*args, **kwargs)
        with self._lock:
            entry = self._programs.get(key)
        if entry is None:
            entry = self._compile(key, args, kwargs)
        else:
            _metrics.inc("compile.cache_hits")
        compiled, cost = entry
        if cost is not None:
            if cost["flops"]:
                _metrics.inc("xla.flops", cost["flops"])
            if cost["bytes"]:
                _metrics.inc("xla.bytes", cost["bytes"])
        if compiled is not None and not kwargs:
            try:
                return compiled(*self._dynamic_args(args))
            except Exception:
                # aval/pytree drift (e.g. weak_type) — retire the AOT
                # executable for this key, keep the cost accounting
                with self._lock:
                    self._programs[key] = (None, cost)
        return self.fn(*args, **kwargs)

    def _compile(self, key: tuple, args: tuple, kwargs: dict):
        compiled = cost = None
        t0 = time.perf_counter()
        try:
            compiled = self.fn.lower(*args, **kwargs).compile()
        except Exception:
            compiled = None
        dt_ms = (time.perf_counter() - t0) * 1e3
        if compiled is not None:
            try:
                ca = compiled.cost_analysis()
                if isinstance(ca, (list, tuple)):
                    ca = ca[0] if ca else {}
                cost = {"flops": max(float(ca.get("flops", 0.0)), 0.0),
                        "bytes": max(float(ca.get("bytes accessed", 0.0)),
                                     0.0)}
            except Exception:
                cost = None
        _metrics.inc("compile.count")
        _metrics.inc("compile.ms", dt_ms)
        rec: Dict[str, Any] = {"event": "compile", "name": self.name,
                               "ms": round(dt_ms, 3),
                               "ok": compiled is not None}
        if cost is not None:
            rec["flops"] = cost["flops"]
            rec["bytes"] = cost["bytes"]
        attrs = _trace.current_span_attrs()
        if attrs:
            for k in ("level", "phase", "frame"):
                if k in attrs:
                    rec[k] = attrs[k]
        ctx = _trace._CURRENT
        _logging.emit(rec, ctx.log_path if ctx is not None else None)
        entry = (compiled, cost)
        with self._lock:
            self._programs[key] = entry
        return entry


def instrument(fn: Any, name: str,
               static_argnums: Tuple[int, ...] = ()) -> JitShim:
    """Wrap a jit/pjit entry point in a compile-aware shim."""
    return JitShim(fn, name, static_argnums)


def record_hbm(level: Optional[int] = None,
               log_path: Optional[str] = None) -> None:
    """Fold per-device HBM watermarks into ``hbm.peak_bytes.d<N>`` peak
    gauges and (when a log path is set) one ``hbm`` record.  Only peeks
    at an already-initialized jax runtime — never forces backend init —
    and tolerates backends whose ``memory_stats()`` is None (CPU)."""
    if not _metrics._ACTIVE:
        return
    jax = sys.modules.get("jax")
    if jax is None:
        return
    try:
        bridge = sys.modules.get("jax._src.xla_bridge")
        if bridge is None or not getattr(bridge, "_backends", None):
            return
        devs = jax.local_devices()
    except Exception:
        return
    peaks: Dict[str, int] = {}
    for d in devs:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue  # CPU and friends: no allocator stats — fine
        peak = stats.get("peak_bytes_in_use", stats.get("bytes_in_use"))
        if peak is None:
            continue
        _metrics.max_gauge(f"hbm.peak_bytes.d{d.id}", float(peak))
        peaks[f"d{d.id}"] = int(peak)
    if peaks and log_path:
        rec: Dict[str, Any] = {"event": "hbm", "peaks": peaks}
        if level is not None:
            rec["level"] = level
        _logging.emit(rec, log_path)
