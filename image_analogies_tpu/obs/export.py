"""`ia trace` — run-log JSONL to Chrome/Perfetto trace.json.

Maps the run log's record kinds onto the Chrome Trace Event Format so a
north-star run can be opened in ``chrome://tracing`` / Perfetto:

- ``span`` records become ``ph=X`` complete events on the HOST track.
  Spans are emitted at exit carrying ``wall_ms`` and an exit ``ts``, so
  the event start is ``ts - wall_ms/1e3``; nesting falls out of the
  interval containment (a child span closes before its parent).
- level stat records (``ms`` / ``enqueue_ms``) become ``ph=X`` events on
  the DEVICE track — real device compute under level_sync, enqueue cost
  otherwise (the record says which by field name).
- ``compile`` records (obs.device) become ``ph=X`` events on the
  COMPILE track, args carrying the XLA cost estimate.
- everything else (manifest, run_end, retries, run_join, hbm, coherence
  summaries) becomes a ``ph=i`` instant on the host track.

One Chrome ``pid`` per run_id; tids 1/2/3 = host/device/compile, named
via ``ph=M`` metadata events (which carry ``ts``/``dur`` 0 so every
event in the file uniformly has ph/ts/pid/tid and dur-or-instant).
Timestamps are microseconds relative to the earliest event start.

Cross-hop traces: a record carrying a ``trace`` attr (stamped by the
ambient request context — HTTP front end, router, worker, engine spans
all share one id via X-IA-Trace / the IAT1 wire frame) is re-homed onto
a per-trace track (tids from 16 up, named ``trace <id>``), so one
request's whole journey — even across two isolated worker registries —
renders as a single horizontal track instead of being scattered over
the host/serve/device lanes.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Tuple

from image_analogies_tpu.obs.report import _is_level_stat, load_records

HOST_TID = 1
DEVICE_TID = 2
COMPILE_TID = 3
SERVE_TID = 4
CHAOS_TID = 5

_TID_NAMES = {HOST_TID: "host", DEVICE_TID: "device", COMPILE_TID: "compile",
              SERVE_TID: "serve", CHAOS_TID: "chaos"}

# Records stamped with a trace id get their own per-trace track; the
# base leaves room below for future fixed lanes without renumbering.
TRACE_TID_BASE = 16

# bookkeeping fields that don't belong in an event's args payload
_DROP_ARGS = ("ts",)


def _classify(rec: Dict[str, Any]) -> Tuple[str, int, str, Optional[float]]:
    """(ph, tid, name, dur_ms) of one record."""
    ev = rec.get("event")
    if ev == "span":
        tid = (SERVE_TID if rec.get("name") in ("serve_batch",
                                                "serve_dispatch",
                                                "serve_warmup")
               else HOST_TID)
        return "X", tid, str(rec.get("name", "span")), \
            float(rec.get("wall_ms", 0.0))
    if ev == "compile":
        return "X", COMPILE_TID, f"compile {rec.get('name', '?')}", \
            float(rec.get("ms", 0.0))
    if ev == "serve_request":
        # emitted at completion with total_ms = enqueue->done, so the
        # ph=X interval spans the request's whole lifetime on the serve
        # track; queue_ms/dispatch_ms ride in args for inspection
        return ("X", SERVE_TID,
                f"req {rec.get('request', '?')} "
                f"{rec.get('status', '?')}",
                float(rec.get("total_ms", 0.0)))
    if ev in ("serve_admit", "serve_degrade_decision"):
        # request-chain instants on the serve track: together with the
        # queue_ms/dispatch_ms-bearing serve_request interval these make
        # one request's critical path readable end to end (admit ->
        # queue wait -> batch -> dispatch -> degrade decision), all
        # joined by the shared `request` id in args.
        verb = "admit" if ev == "serve_admit" else "degrade"
        return "i", SERVE_TID, f"{verb} r{rec.get('request', '?')}", None
    if ev == "serve_batch_lane":
        # batched-engine lane instants on the serve track: which lane of
        # the shared launch answered (or faulted) which request
        return ("i", SERVE_TID,
                f"lane {rec.get('lane', '?')} r{rec.get('request', '?')} "
                f"{rec.get('status', '?')}", None)
    if ev in ("serve_replay", "serve_recovery", "serve_dedupe"):
        # durability-plane instants on the serve track: journal replay
        # actions, the recovery summary, and dedupe short-circuits sit
        # next to the request intervals they stand in for
        if ev == "serve_replay":
            name = f"replay {rec.get('action', '?')} {rec.get('idem', '?')}"
        elif ev == "serve_dedupe":
            name = f"dedupe {rec.get('idem', '?')}"
        else:
            name = (f"recovery replayed={rec.get('replayed', 0)} "
                    f"done={rec.get('done', 0)}")
        return "i", SERVE_TID, name, None
    if ev == "serve_decision":
        # decision-attribution instants on the serve track: every
        # control-plane verdict (degrade, shed, spill, poison, dedupe,
        # re-chain) that shaped a request's fate, with site + cause in
        # args.  Trace-stamped ones re-home to their per-trace track, so
        # a request's verdicts line up under its own request chain.
        name = (f"{rec.get('site', '?')} {rec.get('verdict', '?')}"
                + (f" ({rec['cause']})" if rec.get("cause") else ""))
        return "i", SERVE_TID, name, None
    if ev == "serve_cost":
        # cost-vector instants close each request's chain on the serve
        # track: tenant + queue/dispatch split + lanes in args
        return ("i", SERVE_TID,
                f"cost {str(rec.get('tenant', '?'))[:8]} "
                f"{rec.get('dispatch_ms', 0)}ms", None)
    if ev in ("router_route", "router_spill", "router_rechain",
              "router_resubmit"):
        # routing-plane instants share the serve track: a request's hop
        # (or spillover walk) sits next to the serve interval it fed
        if ev == "router_route":
            name = f"route {rec.get('idem', '?')} -> {rec.get('worker', '?')}"
        elif ev == "router_spill":
            name = (f"spill {rec.get('idem', '?')} "
                    f"{rec.get('home', '?')} -> {rec.get('to', '?')}")
        else:
            verb = "rechain" if ev == "router_rechain" else "resubmit"
            name = f"{verb} {rec.get('idem', '?')}"
        return "i", SERVE_TID, name, None
    if ev in ("router_death", "router_handoff"):
        # fleet lifecycle instants on the fault track, next to the
        # process death that caused them
        if ev == "router_death":
            name = f"worker death {rec.get('worker', '?')}"
        else:
            name = (f"journal handoff {rec.get('worker', '?')} "
                    f"gen {rec.get('generation', '?')}")
        return "i", CHAOS_TID, name, None
    if ev in ("ann_gate", "ann_prefilter"):
        # two-stage matcher instants on the host track: the parity
        # gate's verdict and each level's prefilter engagement (with its
        # basis source and slab size in args)
        if ev == "ann_gate":
            name = (f"ann gate {'ok' if rec.get('ok') else 'refused'} "
                    f"{rec.get('device', '?')}")
        else:
            name = (f"ann prefilter L{rec.get('level', '?')} "
                    f"{rec.get('source', '?')} m={rec.get('top_m', '?')}")
        return "i", HOST_TID, name, None
    if ev in ("chaos_inject", "ckpt_quarantined", "journal_quarantined",
              "ann_quarantined", "watchdog_timeout",
              "retry_exhausted", "serve_worker_crash", "serve_process_death",
              "breaker_open",
              "breaker_half_open", "breaker_closed", "blackbox_dump"):
        # fault-plane instants on their own track: injections line up
        # visually against the retries/quarantines/crashes they caused
        if ev == "chaos_inject":
            name = f"inject {rec.get('kind', '?')} @{rec.get('site', '?')}"
        elif ev == "blackbox_dump":
            # the flight-recorder seal sits NEXT to the fault that
            # triggered it on the same track
            name = f"blackbox {rec.get('reason', '?')}"
        else:
            name = str(ev)
        return "i", CHAOS_TID, name, None
    if ev is None and _is_level_stat(rec):
        dur = rec.get("ms", rec.get("enqueue_ms", 0.0))
        name = f"L{rec['level']}"
        if "frame" in rec:
            name += f" f{rec['frame']}"
        name += " device" if "ms" in rec else " enqueue"
        return "X", DEVICE_TID, name, float(dur)
    return "i", HOST_TID, str(ev or "record"), None


def to_chrome_trace(records: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Convert run-log records into a Chrome trace dict."""
    pids: Dict[Optional[str], int] = {}

    def pid_of(rec: Dict[str, Any]) -> int:
        rid = rec.get("run_id")
        if rid not in pids:
            pids[rid] = len(pids) + 1
        return pids[rid]

    # pass 1: classify + find the earliest start so ts stays small
    trace_tids: Dict[str, int] = {}
    rows = []
    base = None
    for rec in records:
        ts = rec.get("ts")
        if not isinstance(ts, (int, float)):
            continue
        ph, tid, name, dur_ms = _classify(rec)
        trace_id = rec.get("trace")
        if isinstance(trace_id, str) and trace_id:
            # a traced record leaves its kind-lane for the request's own
            # track — the whole hop chain reads as one horizontal story
            if trace_id not in trace_tids:
                trace_tids[trace_id] = TRACE_TID_BASE + len(trace_tids)
            tid = trace_tids[trace_id]
        start_s = float(ts) - (dur_ms or 0.0) / 1e3 if ph == "X" \
            else float(ts)
        if base is None or start_s < base:
            base = start_s
        rows.append((rec, ph, tid, name, dur_ms, start_s))
    base = base or 0.0

    events: List[Dict[str, Any]] = []
    trace_tracks = set()  # (pid, tid, trace_id) needing thread_name meta
    for rec, ph, tid, name, dur_ms, start_s in rows:
        args = {k: v for k, v in rec.items() if k not in _DROP_ARGS}
        pid = pid_of(rec)
        if tid >= TRACE_TID_BASE:
            trace_tracks.add((pid, tid, str(rec.get("trace"))))
        event: Dict[str, Any] = {
            "ph": ph,
            "ts": round((start_s - base) * 1e6, 1),  # µs
            "pid": pid,
            "tid": tid,
            "name": name,
            "args": args,
        }
        if ph == "X":
            event["dur"] = round((dur_ms or 0.0) * 1e3, 1)  # µs
        else:
            event["s"] = "t"  # thread-scoped instant
        events.append(event)

    events.sort(key=lambda e: (e["pid"], e["ts"]))

    meta: List[Dict[str, Any]] = []
    for rid, pid in pids.items():
        meta.append({"ph": "M", "name": "process_name", "ts": 0, "dur": 0,
                     "pid": pid, "tid": 0,
                     "args": {"name": f"run {rid or '(unstamped)'}"}})
        for tid, tname in _TID_NAMES.items():
            meta.append({"ph": "M", "name": "thread_name", "ts": 0,
                         "dur": 0, "pid": pid, "tid": tid,
                         "args": {"name": tname}})
    for pid, tid, trace_id in sorted(trace_tracks):
        meta.append({"ph": "M", "name": "thread_name", "ts": 0, "dur": 0,
                     "pid": pid, "tid": tid,
                     "args": {"name": f"trace {trace_id}"}})
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


def export_trace(log_path: str, out_path: str) -> Dict[str, int]:
    """Read a run-log JSONL, write Chrome trace JSON, return counts."""
    records = load_records(log_path)
    trace = to_chrome_trace(records)
    with open(out_path, "w") as f:
        json.dump(trace, f)
    return {"records": len(records), "events": len(trace["traceEvents"])}
