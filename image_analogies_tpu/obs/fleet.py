"""Federated fleet metrics: N worker snapshots -> one labeled view.

The merge is TRANSPORT-AGNOSTIC: it consumes the plain snapshot schema
(``MetricsRegistry.snapshot()``) — whether a snapshot came from an
in-process ``ObsScope`` or was recovered from a remote worker's
``/metrics`` exposition via :func:`snapshot_from_exposition` makes no
difference, so a future subprocess/socket worker federates by scrape
with zero new code here.

Federation is LABEL-ONLY: every per-worker sample is re-emitted exactly
as the worker reported it, under a ``worker="<wid>"`` label; the
unlabeled merged sample is a pure roll-up computed from those same
values (counters sum, max-gauges max, histograms merge bucketwise).
No worker's sample value is ever mutated, scaled, or reinterpreted —
the labeled series and the merged series are byte-consistent by
construction because both render through obs.live's formatter.

Merge rules per section:

- counters: SUM across workers.
- gauges: SUM, except max-gauge families (peak watermarks, uptime,
  breaker state, slo.* health gauges — see ``_MAX_GAUGE_MARKERS``)
  which take the MAX (summing two HBM peaks invents memory no device
  has; summing breaker states invents a state no breaker is in).
- histograms: counts/sums add, min/max extremize, base-2 buckets add
  key-wise — merging N workers' latency histograms is exact, not an
  approximation, because every worker uses the same bucket edges.

Jax-free like the rest of the obs core.
"""

from __future__ import annotations

import math
import re
from typing import Any, Dict, List, Optional, Tuple

from image_analogies_tpu.obs import live as _live
from image_analogies_tpu.obs import quantiles as _quantiles

# Gauge families merged by MAX instead of SUM.  Substring match on the
# dotted registry name: peak watermarks and state-like gauges are
# "highest wins"; everything else (queue depths, byte totals) sums.
_MAX_GAUGE_MARKERS = ("peak", "uptime", "breaker.state", "slo.")


def is_max_gauge(name: str) -> bool:
    return any(m in name for m in _MAX_GAUGE_MARKERS)


def _empty_hist() -> Dict[str, Any]:
    return {"count": 0, "sum": 0.0, "min": math.inf, "max": -math.inf,
            "buckets": {}}


def merge_histograms(summaries: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Merge N ``Histogram.summary()`` dicts into one (same schema)."""
    acc = _empty_hist()
    for s in summaries:
        count = int(s.get("count", 0))
        if not count:
            continue
        acc["count"] += count
        acc["sum"] += float(s.get("sum", 0.0))
        acc["min"] = min(acc["min"], float(s.get("min", 0.0)))
        acc["max"] = max(acc["max"], float(s.get("max", 0.0)))
        for k, v in (s.get("buckets") or {}).items():
            acc["buckets"][str(k)] = acc["buckets"].get(str(k), 0) + int(v)
    if not acc["count"]:
        return {"count": 0, "sum": 0.0, "min": 0.0, "max": 0.0, "mean": 0.0}
    acc["mean"] = acc["sum"] / acc["count"]
    acc["buckets"] = {k: acc["buckets"][k]
                      for k in sorted(acc["buckets"], key=int)}
    return acc


def merge_snapshots(by_worker: Dict[str, Dict[str, dict]]
                    ) -> Dict[str, dict]:
    """Roll N worker snapshots into one fleet snapshot."""
    counters: Dict[str, float] = {}
    gauges: Dict[str, float] = {}
    hists: Dict[str, List[Dict[str, Any]]] = {}
    for _wid, snap in sorted(by_worker.items()):
        for name, v in (snap.get("counters") or {}).items():
            counters[name] = counters.get(name, 0) + v
        for name, v in (snap.get("gauges") or {}).items():
            if name in gauges and is_max_gauge(name):
                gauges[name] = max(gauges[name], v)
            else:
                gauges[name] = gauges.get(name, 0) + v if name in gauges \
                    else v
    for _wid, snap in sorted(by_worker.items()):
        for name, summ in (snap.get("histograms") or {}).items():
            hists.setdefault(name, []).append(summ)
    sketches: Dict[str, List[Dict[str, Any]]] = {}
    for _wid, snap in sorted(by_worker.items()):
        for name, summ in (snap.get("sketches") or {}).items():
            sketches.setdefault(name, []).append(summ)
    out = {
        "counters": counters,
        "gauges": gauges,
        "histograms": {name: merge_histograms(ss)
                       for name, ss in hists.items()},
    }
    if sketches:
        # merge-closed by construction (bucket counts add on a shared
        # grid), so the fleet sketch equals the whole-stream sketch.
        out["sketches"] = {name: _quantiles.merge_summaries(ss)
                           for name, ss in sketches.items()}
    return out


# --- tenant federation -------------------------------------------------------

def merge_tenant_docs(docs: List[Dict[str, Any]],
                      k: Optional[int] = None) -> Dict[str, Any]:
    """Federate per-worker ``/tenants`` documents into one fleet-level
    top-K.  Thin re-export of :func:`obs.tenants.merge_docs` so the
    fleet-federation surface lives beside the metrics merge; the sketch
    math (mergeable space-saving, honest error intervals) is documented
    on the tenants module."""
    from image_analogies_tpu.obs import tenants as _tenants

    return _tenants.merge_docs(docs, k=k)


# --- labeled exposition -----------------------------------------------------

def render_fleet(by_worker: Dict[str, Dict[str, dict]],
                 extra: Optional[Tuple[str, Dict[str, dict]]] = None) -> str:
    """Prometheus text of the fleet: for every metric family, the MERGED
    unlabeled sample followed by one ``{worker="<wid>"}`` sample per
    worker, all through obs.live's formatter so the labeled values sum
    byte-consistently to the merged one.

    ``extra`` is an optional ``(label, snapshot)`` whose families are
    appended (labeled, NOT merged) only where they do not collide with a
    worker family — the fleet's own routing-plane counters surface this
    way without double counting (the run scope's registry already
    contains every worker's chained writes).
    """
    merged = merge_snapshots(by_worker)
    wids = sorted(by_worker)
    lines: List[str] = []

    def val(snap: Dict[str, dict], section: str, name: str):
        return (snap.get(section) or {}).get(name)

    for name in sorted(merged["counters"]):
        pn = _live.prom_name(name) + "_total"
        lines.append(f"# HELP {pn} counter {name}")
        lines.append(f"# TYPE {pn} counter")
        lines.append(f"{pn} {_live._fmt(merged['counters'][name])}")
        for wid in wids:
            v = val(by_worker[wid], "counters", name)
            if v is not None:
                lines.append(f'{pn}{{worker="{wid}"}} {_live._fmt(v)}')

    for name in sorted(merged["gauges"]):
        pn = _live.prom_name(name)
        lines.append(f"# HELP {pn} gauge {name}")
        lines.append(f"# TYPE {pn} gauge")
        lines.append(f"{pn} {_live._fmt(merged['gauges'][name])}")
        for wid in wids:
            v = val(by_worker[wid], "gauges", name)
            if v is not None:
                lines.append(f'{pn}{{worker="{wid}"}} {_live._fmt(v)}')

    for name in sorted(merged["histograms"]):
        pn = _live.prom_name(name)
        lines.append(f"# HELP {pn} histogram {name}")
        lines.append(f"# TYPE {pn} histogram")
        lines.extend(_hist_lines(pn, merged["histograms"][name], ""))
        for wid in wids:
            summ = val(by_worker[wid], "histograms", name)
            if summ is not None:
                lines.extend(_hist_lines(pn, summ, f'worker="{wid}"'))

    for name in sorted(merged.get("sketches") or {}):
        lines.extend(_live.sketch_lines(name, merged["sketches"][name]))
        for wid in wids:
            summ = val(by_worker[wid], "sketches", name)
            if summ is not None:
                lines.extend(_live.sketch_lines(name, summ,
                                                f'worker="{wid}"'))

    if extra is not None:
        label, snap = extra
        taken = (set(merged["counters"]) | set(merged["gauges"])
                 | set(merged["histograms"]))
        only = {
            "counters": {k: v for k, v in (snap.get("counters") or {})
                         .items() if k not in taken},
            "gauges": {k: v for k, v in (snap.get("gauges") or {})
                       .items() if k not in taken},
            "histograms": {k: v for k, v in (snap.get("histograms") or {})
                           .items() if k not in taken},
        }
        for name in sorted(only["counters"]):
            pn = _live.prom_name(name) + "_total"
            lines.append(f"# HELP {pn} counter {name}")
            lines.append(f"# TYPE {pn} counter")
            lines.append(f'{pn}{{worker="{label}"}} '
                         f"{_live._fmt(only['counters'][name])}")
        for name in sorted(only["gauges"]):
            pn = _live.prom_name(name)
            lines.append(f"# HELP {pn} gauge {name}")
            lines.append(f"# TYPE {pn} gauge")
            lines.append(f'{pn}{{worker="{label}"}} '
                         f"{_live._fmt(only['gauges'][name])}")
        for name in sorted(only["histograms"]):
            pn = _live.prom_name(name)
            lines.append(f"# HELP {pn} histogram {name}")
            lines.append(f"# TYPE {pn} histogram")
            lines.extend(_hist_lines(pn, only["histograms"][name],
                                     f'worker="{label}"'))

    if not lines:
        lines.append("# empty fleet (no worker scopes)")
    return "\n".join(lines) + "\n"


def _hist_lines(pn: str, summ: Dict[str, Any], label: str) -> List[str]:
    """One histogram family's sample lines, optionally worker-labeled
    (the ``le`` label composes with it)."""
    out: List[str] = []
    cum = 0
    for k in sorted(int(x) for x in (summ.get("buckets") or {})):
        cum += int(summ["buckets"][str(k)])
        le = _live._fmt(float(2 ** k))
        lab = f'le="{le}"' + (f",{label}" if label else "")
        out.append(f"{pn}_bucket{{{lab}}} {cum}")
    count = int(summ.get("count", 0))
    inf_lab = 'le="+Inf"' + (f",{label}" if label else "")
    suffix = f"{{{label}}}" if label else ""
    out.append(f"{pn}_bucket{{{inf_lab}}} {count}")
    out.append(f"{pn}_sum{suffix} {_live._fmt(summ.get('sum', 0.0))}")
    out.append(f"{pn}_count{suffix} {count}")
    return out


# --- scrape-side recovery ---------------------------------------------------

_HELP_RE = re.compile(r"^# HELP (\S+) (counter|gauge|histogram) (.+)$")
_SAMPLE_RE = re.compile(r"^(\S+?)(?:\{([^}]*)\})? (\S+)$")
_LE_RE = re.compile(r'le="([^"]+)"')


def snapshot_from_exposition(text: str) -> Dict[str, dict]:
    """Recover a registry snapshot from obs.live's Prometheus text.

    This is the remote half of transport-agnostic federation: scrape a
    worker's ``/metrics``, recover its snapshot, feed it to
    :func:`merge_snapshots` exactly like an in-process scope's.  The
    HELP line carries the original dotted registry name, so recovery is
    lossless for counters and gauges; histograms rebuild their base-2
    buckets from the cumulative samples (min/max/mean are not exposed
    by the text format — min degrades to 0 and max to the top occupied
    bucket edge, which the merge rules tolerate).  Labeled samples
    (an already-federated view) are skipped: federation composes by
    re-scraping workers, not by double-merging roll-ups.
    """
    kinds: Dict[str, Tuple[str, str]] = {}  # prom name -> (kind, dotted)
    for line in text.splitlines():
        m = _HELP_RE.match(line)
        if m:
            kinds[m.group(1)] = (m.group(2), m.group(3))

    snap: Dict[str, dict] = {"counters": {}, "gauges": {}, "histograms": {}}
    hstate: Dict[str, Dict[str, Any]] = {}
    for line in text.splitlines():
        if line.startswith("#") or not line.strip():
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            continue
        pn, labels, raw = m.group(1), m.group(2) or "", m.group(3)
        if "worker=" in labels:
            continue
        try:
            value = float(raw)
        except ValueError:
            continue
        base, suffix = pn, ""
        for suf in ("_bucket", "_sum", "_count"):
            if pn.endswith(suf) and pn[:-len(suf)] in kinds \
                    and kinds[pn[:-len(suf)]][0] == "histogram":
                base, suffix = pn[:-len(suf)], suf
                break
        if suffix:
            kind, dotted = kinds[base]
            st = hstate.setdefault(dotted, {"cum": [], "sum": 0.0,
                                            "count": 0})
            if suffix == "_bucket":
                le = _LE_RE.search(labels)
                if le and le.group(1) != "+Inf":
                    st["cum"].append((float(le.group(1)), value))
            elif suffix == "_sum":
                st["sum"] = value
            else:
                st["count"] = int(value)
            continue
        if pn not in kinds and pn.endswith("_total"):
            # counters expose as <name>_total but HELP is keyed on the
            # full sample name already; this branch is unreachable for
            # our own renderer and exists for foreign expositions
            continue
        kind_dotted = kinds.get(pn)
        if kind_dotted is None:
            continue
        kind, dotted = kind_dotted
        if kind == "counter":
            snap["counters"][dotted] = value
        elif kind == "gauge":
            snap["gauges"][dotted] = value

    for dotted, st in hstate.items():
        buckets: Dict[str, int] = {}
        prev = 0.0
        top_edge = 0.0
        for edge, cum in sorted(st["cum"]):
            n = int(cum - prev)
            prev = cum
            if n > 0:
                k = int(round(math.log2(edge))) if edge > 0 else 0
                buckets[str(k)] = buckets.get(str(k), 0) + n
                top_edge = edge
        count = st["count"]
        if count:
            snap["histograms"][dotted] = {
                "count": count, "sum": st["sum"],
                "min": 0.0, "max": top_edge,
                "mean": st["sum"] / count, "buckets": buckets}
        else:
            snap["histograms"][dotted] = {"count": 0, "sum": 0.0,
                                          "min": 0.0, "max": 0.0,
                                          "mean": 0.0}
    return snap
