"""Process-local, thread-safe metrics registry.

A :class:`MetricsRegistry` holds counters, gauges, and histograms keyed
by name.  One registry is installed per run by ``obs.trace.run_scope``;
instrumentation sites in the engine call the module-level helpers
(:func:`inc`, :func:`add_gauge`, :func:`set_gauge`, :func:`observe`),
which check a single module bool before touching the registry — with no
active run the cost is one attribute load + branch per call site, so
bench numbers do not move when observability is off.

No jax / numpy imports here: the registry must be importable from any
layer (utils, parallel, backends) without creating cycles or forcing
device init.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, List, Optional


class Histogram:
    """Fixed power-of-two bucket histogram (base-2 exponential).

    Tracks count / sum / min / max plus counts per bucket
    ``[2^k, 2^(k+1))``.  Good enough for ms and byte distributions
    without requiring a quantile sketch dependency.
    """

    __slots__ = ("count", "total", "min", "max", "buckets")

    def __init__(self):
        self.count = 0
        self.total = 0.0
        self.min = math.inf
        self.max = -math.inf
        self.buckets: Dict[int, int] = {}

    def observe(self, value: float) -> None:
        self.count += 1
        self.total += value
        if value < self.min:
            self.min = value
        if value > self.max:
            self.max = value
        k = max(0, math.frexp(value)[1]) if value > 0 else 0
        self.buckets[k] = self.buckets.get(k, 0) + 1

    def percentile(self, q: float) -> float:
        """Estimated q-th percentile from the base-2 buckets: the upper
        edge of the bucket holding that rank, clamped to the observed
        max.  Coarse by construction (buckets are octaves) but monotone
        and dependency-free — good enough for serving-latency p50/p95."""
        if not self.count:
            return 0.0
        rank = max(1, math.ceil(q / 100.0 * self.count))
        cum = 0
        for k in sorted(self.buckets):
            cum += self.buckets[k]
            if cum >= rank:
                edge = float(2 ** k) if k > 0 else 0.0
                return min(edge, self.max)
        return self.max

    def cumulative_buckets(self) -> List[tuple]:
        """Prometheus-style cumulative buckets: ``[(le_edge, cum), ...]``
        ascending by edge, where ``le_edge`` is the bucket's upper bound
        (``2**k``; the k=0 bucket also absorbs values <= 0 so its edge is
        1.0).  Well-defined on every histogram state: empty -> ``[]``,
        single-sample -> one pair — never an exception, never NaN."""
        out: List[tuple] = []
        cum = 0
        for k in sorted(self.buckets):
            cum += self.buckets[k]
            out.append((float(2 ** k), cum))
        return out

    def summary(self) -> Dict[str, float]:
        if not self.count:
            return {"count": 0, "sum": 0.0, "min": 0.0, "max": 0.0,
                    "mean": 0.0}
        # buckets ride along (run_end snapshots feed `ia report`'s
        # batch-size histogram); the empty-histogram summary keeps its
        # legacy shape.
        return {"count": self.count, "sum": self.total, "min": self.min,
                "max": self.max, "mean": self.total / self.count,
                "buckets": {str(k): v
                            for k, v in sorted(self.buckets.items())}}


class MetricsRegistry:
    """Thread-safe named counters / gauges / histograms."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, float] = {}
        self._histograms: Dict[str, Histogram] = {}

    def inc(self, name: str, value: float = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + value

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    def add_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = self._gauges.get(name, 0) + value

    def max_gauge(self, name: str, value: float) -> None:
        """Peak watermark: keep the maximum ever observed (HBM peaks)."""
        with self._lock:
            cur = self._gauges.get(name)
            if cur is None or value > cur:
                self._gauges[name] = value

    def observe(self, name: str, value: float) -> None:
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = Histogram()
            h.observe(value)

    def counter(self, name: str) -> float:
        with self._lock:
            return self._counters.get(name, 0)

    def snapshot(self) -> Dict[str, dict]:
        """Plain-dict dump, safe to json-serialize into a run record."""
        with self._lock:
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "histograms": {k: h.summary()
                               for k, h in self._histograms.items()},
            }


# --- module-level fast path -------------------------------------------------
#
# _ACTIVE is flipped by obs.trace when a run installs/uninstalls a
# registry.  Hot-path call sites read one module global and branch; the
# lock is only ever taken when a run asked for metrics.

_ACTIVE = False
_REGISTRY: Optional[MetricsRegistry] = None
_STACK: List[MetricsRegistry] = []


def _install(reg: MetricsRegistry) -> None:
    global _ACTIVE, _REGISTRY
    _STACK.append(reg)
    _REGISTRY = reg
    _ACTIVE = True


def _uninstall(reg: MetricsRegistry) -> None:
    global _ACTIVE, _REGISTRY
    if reg in _STACK:
        _STACK.remove(reg)
    _REGISTRY = _STACK[-1] if _STACK else None
    _ACTIVE = _REGISTRY is not None


def registry() -> Optional[MetricsRegistry]:
    return _REGISTRY


def inc(name: str, value: float = 1) -> None:
    if _ACTIVE:
        _REGISTRY.inc(name, value)


def set_gauge(name: str, value: float) -> None:
    if _ACTIVE:
        _REGISTRY.set_gauge(name, value)


def add_gauge(name: str, value: float) -> None:
    if _ACTIVE:
        _REGISTRY.add_gauge(name, value)


def max_gauge(name: str, value: float) -> None:
    if _ACTIVE:
        _REGISTRY.max_gauge(name, value)


def observe(name: str, value: float) -> None:
    if _ACTIVE:
        _REGISTRY.observe(name, value)


def snapshot() -> Dict[str, dict]:
    return _REGISTRY.snapshot() if _REGISTRY is not None else {
        "counters": {}, "gauges": {}, "histograms": {}}
