"""Scoped, thread-safe metrics registries.

A :class:`MetricsRegistry` holds counters, gauges, and histograms keyed
by name.  Registries travel inside an :class:`ObsScope` — the unit of
observability identity (registry + flight recorder + SLO tracker +
dump dir) — and scopes resolve THREAD-AMBIENTLY, the same pattern as
``obs.trace.request_context``: a per-thread scope stack first, then the
process-default scope installed by ``obs.trace.run_scope``, then None.
Instrumentation sites everywhere call the module-level helpers
(:func:`inc`, :func:`add_gauge`, :func:`set_gauge`, :func:`observe`),
which check a single module bool before resolving — with no scope
active anywhere the cost is one attribute load + branch per call site,
so bench numbers do not move when observability is off.

A scope may chain to a ``parent``: writes land in the scope's own
registry AND every ancestor's.  That is how fleet workers get isolated
per-worker registries (each worker thread pushes its scope) while the
enclosing run's registry still sees the whole-fleet totals that drills
and ``run_end`` snapshots assert on.  Reads (``registry()``,
``snapshot()``) never chain — they see exactly the resolved scope.

No jax / numpy imports here: the registry must be importable from any
layer (utils, parallel, backends) without creating cycles or forcing
device init.
"""

from __future__ import annotations

import contextlib
import itertools
import math
import threading
from typing import Dict, List, Optional

from image_analogies_tpu.obs import quantiles as _quantiles
from image_analogies_tpu.obs import recorder as _recorder


class Histogram:
    """Fixed power-of-two bucket histogram (base-2 exponential).

    Tracks count / sum / min / max plus counts per bucket
    ``[2^k, 2^(k+1))``.  Good enough for ms and byte distributions
    without requiring a quantile sketch dependency.
    """

    __slots__ = ("count", "total", "min", "max", "buckets")

    def __init__(self):
        self.count = 0
        self.total = 0.0
        self.min = math.inf
        self.max = -math.inf
        self.buckets: Dict[int, int] = {}

    def observe(self, value: float) -> None:
        self.count += 1
        self.total += value
        if value < self.min:
            self.min = value
        if value > self.max:
            self.max = value
        k = max(0, math.frexp(value)[1]) if value > 0 else 0
        self.buckets[k] = self.buckets.get(k, 0) + 1

    def percentile(self, q: float) -> float:
        """Estimated q-th percentile from the base-2 buckets: the upper
        edge of the bucket holding that rank, clamped to the observed
        max.  Coarse by construction (buckets are octaves) but monotone
        and dependency-free — good enough for serving-latency p50/p95."""
        if not self.count:
            return 0.0
        rank = max(1, math.ceil(q / 100.0 * self.count))
        cum = 0
        for k in sorted(self.buckets):
            cum += self.buckets[k]
            if cum >= rank:
                edge = float(2 ** k) if k > 0 else 0.0
                return min(edge, self.max)
        return self.max

    def cumulative_buckets(self) -> List[tuple]:
        """Prometheus-style cumulative buckets: ``[(le_edge, cum), ...]``
        ascending by edge, where ``le_edge`` is the bucket's upper bound
        (``2**k``; the k=0 bucket also absorbs values <= 0 so its edge is
        1.0).  Well-defined on every histogram state: empty -> ``[]``,
        single-sample -> one pair — never an exception, never NaN."""
        out: List[tuple] = []
        cum = 0
        for k in sorted(self.buckets):
            cum += self.buckets[k]
            out.append((float(2 ** k), cum))
        return out

    def summary(self) -> Dict[str, float]:
        if not self.count:
            return {"count": 0, "sum": 0.0, "min": 0.0, "max": 0.0,
                    "mean": 0.0}
        # buckets ride along (run_end snapshots feed `ia report`'s
        # batch-size histogram); the empty-histogram summary keeps its
        # legacy shape.
        return {"count": self.count, "sum": self.total, "min": self.min,
                "max": self.max, "mean": self.total / self.count,
                "buckets": {str(k): v
                            for k, v in sorted(self.buckets.items())}}

    def merge(self, other: "Histogram") -> None:
        """Fold ``other`` into this histogram so the result equals a
        single histogram fed the union of both sample sets (count, sum,
        min, max, and every bucket are all exactly additive — percentile
        estimates therefore agree too).  Merging an EMPTY other must be
        a no-op: its min/max sentinels (inf/-inf) would otherwise poison
        the extremes of a non-empty target."""
        if not other.count:
            return
        if other.min < self.min:
            self.min = other.min
        if other.max > self.max:
            self.max = other.max
        self.count += other.count
        self.total += other.total
        for k, v in other.buckets.items():
            self.buckets[k] = self.buckets.get(k, 0) + v

    @classmethod
    def from_summary(cls, summ: Dict) -> "Histogram":
        """Rebuild a mergeable histogram from a :meth:`summary` dict —
        the timeline downsampler merges window aggregates that crossed a
        snapshot boundary as plain dicts.  Tolerates the legacy
        empty-summary shape (no ``buckets`` key)."""
        h = cls()
        count = int(summ.get("count", 0) or 0)
        if not count:
            return h
        h.count = count
        h.total = float(summ.get("sum", 0.0))
        h.min = float(summ.get("min", 0.0))
        h.max = float(summ.get("max", 0.0))
        h.buckets = {int(k): int(v)
                     for k, v in (summ.get("buckets") or {}).items()}
        return h


# Series (by name suffix) that also feed a relative-error quantile
# sketch next to their base-2 histogram — the honest-tail rider for
# p99.9/p99.99.  Latency is the tail that matters; everything else
# keeps the cheap histogram only.
SKETCH_SUFFIXES = ("latency_ms",)


class MetricsRegistry:
    """Thread-safe named counters / gauges / histograms, plus a
    DDSketch-style quantile sketch riding beside the histogram on
    latency series (see :data:`SKETCH_SUFFIXES`)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, float] = {}
        self._histograms: Dict[str, Histogram] = {}
        self._sketches: Dict[str, "_quantiles.QuantileSketch"] = {}

    def inc(self, name: str, value: float = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + value

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    def add_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = self._gauges.get(name, 0) + value

    def max_gauge(self, name: str, value: float) -> None:
        """Peak watermark: keep the maximum ever observed (HBM peaks)."""
        with self._lock:
            cur = self._gauges.get(name)
            if cur is None or value > cur:
                self._gauges[name] = value

    def observe(self, name: str, value: float) -> None:
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = Histogram()
            h.observe(value)
            if name.endswith(SKETCH_SUFFIXES):
                sk = self._sketches.get(name)
                if sk is None:
                    sk = self._sketches[name] = _quantiles.QuantileSketch()
                sk.observe(value)

    def counter(self, name: str) -> float:
        with self._lock:
            return self._counters.get(name, 0)

    def snapshot(self) -> Dict[str, dict]:
        """Plain-dict dump, safe to json-serialize into a run record.
        The ``sketches`` key appears only once a latency series exists,
        so pre-sketch snapshot shapes (golden tests, archived run logs)
        stay byte-stable."""
        with self._lock:
            snap = {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "histograms": {k: h.summary()
                               for k, h in self._histograms.items()},
            }
            if self._sketches:
                snap["sketches"] = {k: sk.summary()
                                    for k, sk in self._sketches.items()}
            return snap


# --- scoped observability contexts ------------------------------------------

_SCOPE_IDS = itertools.count(1)


class ObsScope:
    """One observability identity: a registry plus the trace sink
    (flight-recorder ring) and slots for the SLO tracker and black-box
    dump directory that travel with it.

    ``parent`` chains writes upward (worker scope -> fleet/run scope):
    metric WRITES through this scope land in every registry on the
    chain, so isolation (reads see only this worker) and aggregate
    invariants (the run's registry sums all workers) hold at once.
    Reads never chain.
    """

    __slots__ = ("scope_id", "registry", "parent", "recorder", "slo",
                 "dump_dir")

    def __init__(self, scope_id: Optional[str] = None,
                 parent: Optional["ObsScope"] = None,
                 recorder_capacity: int = _recorder.DEFAULT_CAPACITY):
        self.scope_id = scope_id or f"scope{next(_SCOPE_IDS)}"
        self.registry = MetricsRegistry()
        self.parent = parent
        self.recorder = _recorder.FlightRecorder(recorder_capacity)
        self.slo = None  # obs.slo.SloTracker, attached by the owner
        self.dump_dir: Optional[str] = None  # black-box dump target

    def inc(self, name: str, value: float = 1) -> None:
        s: Optional[ObsScope] = self
        while s is not None:
            s.registry.inc(name, value)
            s = s.parent

    def set_gauge(self, name: str, value: float) -> None:
        s: Optional[ObsScope] = self
        while s is not None:
            s.registry.set_gauge(name, value)
            s = s.parent

    def add_gauge(self, name: str, value: float) -> None:
        s: Optional[ObsScope] = self
        while s is not None:
            s.registry.add_gauge(name, value)
            s = s.parent

    def max_gauge(self, name: str, value: float) -> None:
        s: Optional[ObsScope] = self
        while s is not None:
            s.registry.max_gauge(name, value)
            s = s.parent

    def observe(self, name: str, value: float) -> None:
        s: Optional[ObsScope] = self
        while s is not None:
            s.registry.observe(name, value)
            s = s.parent


# --- module-level fast path + scope resolution ------------------------------
#
# _ACTIVE is true while ANY scope is installed anywhere (process default
# or any thread's stack).  Hot-path call sites read one module global
# and branch; resolution walks thread-local -> process default only when
# some run asked for metrics.

_ACTIVE = False
_ACTIVE_COUNT = 0
_ACTIVE_LOCK = threading.Lock()
_PROCESS: List[ObsScope] = []  # process-default stack (run_scope installs)
_TLS = threading.local()  # per-thread scope stack (fleet worker threads)


def _activate() -> None:
    global _ACTIVE, _ACTIVE_COUNT
    with _ACTIVE_LOCK:
        _ACTIVE_COUNT += 1
        _ACTIVE = True


def _deactivate() -> None:
    global _ACTIVE, _ACTIVE_COUNT
    with _ACTIVE_LOCK:
        _ACTIVE_COUNT = max(_ACTIVE_COUNT - 1, 0)
        _ACTIVE = _ACTIVE_COUNT > 0


def current_scope() -> Optional[ObsScope]:
    """Thread-ambient scope resolution: this thread's innermost pushed
    scope, else the process-default scope, else None.  The disabled path
    is one module-global read + branch — no allocation."""
    if not _ACTIVE:
        return None
    stack = getattr(_TLS, "stack", None)
    if stack:
        return stack[-1]
    return _PROCESS[-1] if _PROCESS else None


def push_scope(scope: ObsScope) -> None:
    stack = getattr(_TLS, "stack", None)
    if stack is None:
        stack = _TLS.stack = []
    stack.append(scope)
    _activate()


def pop_scope(scope: ObsScope) -> None:
    stack = getattr(_TLS, "stack", None)
    if stack:
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is scope:
                del stack[i]
                break
    _deactivate()


@contextlib.contextmanager
def scope_active(scope: Optional[ObsScope]):
    """Make ``scope`` the current thread's ambient scope for the block.
    ``scope_active(None)`` is a transparent no-op, so call sites that
    may or may not own a scope (standalone Server vs fleet worker)
    never branch."""
    if scope is None:
        yield None
        return
    push_scope(scope)
    try:
        yield scope
    finally:
        pop_scope(scope)


def install_process_scope(scope: ObsScope) -> None:
    """Install the process-default scope (obs.trace.run_scope does this
    once per top-level run) — the fallback every thread without its own
    pushed scope resolves to."""
    _PROCESS.append(scope)
    _activate()


def uninstall_process_scope(scope: ObsScope) -> None:
    for i in range(len(_PROCESS) - 1, -1, -1):
        if _PROCESS[i] is scope:
            del _PROCESS[i]
            break
    _deactivate()


def registry() -> Optional[MetricsRegistry]:
    # _ACTIVE is re-checked HERE (not just inside current_scope) so the
    # disabled path never pushes another frame — the zero-alloc contract
    # (test_obs_live's tracemalloc lock) is depth-sensitive: a nested
    # call can force a fresh interpreter datastack chunk.
    if not _ACTIVE:
        return None
    s = current_scope()
    return s.registry if s is not None else None


def inc(name: str, value: float = 1) -> None:
    if _ACTIVE:
        s = current_scope()
        if s is not None:
            s.inc(name, value)


def set_gauge(name: str, value: float) -> None:
    if _ACTIVE:
        s = current_scope()
        if s is not None:
            s.set_gauge(name, value)


def add_gauge(name: str, value: float) -> None:
    if _ACTIVE:
        s = current_scope()
        if s is not None:
            s.add_gauge(name, value)


def max_gauge(name: str, value: float) -> None:
    if _ACTIVE:
        s = current_scope()
        if s is not None:
            s.max_gauge(name, value)


def observe(name: str, value: float) -> None:
    if _ACTIVE:
        s = current_scope()
        if s is not None:
            s.observe(name, value)


def snapshot() -> Dict[str, dict]:
    s = current_scope() if _ACTIVE else None
    return s.registry.snapshot() if s is not None else {
        "counters": {}, "gauges": {}, "histograms": {}}
