"""Resource-ceiling trend watchdogs: catch the leak before the OOM.

A soak does not fail at the moment the leak starts; it fails hours
later when RSS crosses the cgroup limit or the journal fills the disk.
This module watches the slow-moving resource series — process RSS,
devcache bytes, journal segment bytes, archive disk usage — and raises
``obs.ceiling.*`` alarms while the trend is still a trend.

Mechanics:

- :func:`read_proc_vitals` reads RSS / open fds / thread count from
  ``/proc`` (no new deps) with a graceful fallback off-Linux
  (``resource.getrusage`` for RSS, ``threading.active_count`` for
  threads, fds unknown).  ``/healthz`` and the watchdog share this one
  source.
- :class:`TrendWatchdog` keeps a bounded window of (t, value) points
  per series and estimates slope with THEIL-SEN (median of pairwise
  slopes) — robust to the sawtooth a GC or compaction puts on top of a
  real leak, where least squares would chase every spike.
- An alarm fires when the robust slope exceeds the series' threshold
  over a full window: counters ``obs.ceiling.alarms`` +
  ``obs.ceiling.<series>`` through the ambient scope, a trace record,
  a ``decision`` record through obs/ledger.emit_decision (so `ia why`
  can attribute a later shed to the detected leak), and an ``anomaly``
  record into the telemetry archive.  Re-alarms are rate-limited per
  series (``cooldown_s``).

Jax-free (grep-locked in tests/test_obs_live.py); stdlib only.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from image_analogies_tpu.obs import metrics as _metrics
from image_analogies_tpu.obs import trace as _trace

DEFAULT_WINDOW = 32        # points per series
DEFAULT_MIN_POINTS = 8     # alarm needs at least a window's worth
DEFAULT_COOLDOWN_S = 60.0  # one alarm per series per cooldown
# Default slope thresholds, bytes/second sustained.  Conservative: a
# steady +1 MiB/s RSS climb exhausts a 16 GiB box in ~4.5 hours — well
# inside soak territory but far above sampling noise.
DEFAULT_THRESHOLDS = {
    "proc.rss_bytes": 1 << 20,
    "devcache.bytes": 1 << 20,
    "journal.bytes": 256 << 10,
    "archive.bytes": 256 << 10,
}


def read_proc_vitals() -> Dict[str, Any]:
    """Process vitals from ``/proc`` (Linux) or best-effort fallbacks.
    Always returns the full key set; unknown values are None."""
    vitals: Dict[str, Any] = {"pid": os.getpid(), "rss_bytes": None,
                              "open_fds": None, "threads": None}
    try:
        with open("/proc/self/statm") as f:
            fields = f.read().split()
        vitals["rss_bytes"] = int(fields[1]) * os.sysconf("SC_PAGE_SIZE")
    except (OSError, IndexError, ValueError):
        try:  # non-Linux fallback: peak, not current — better than None
            import resource
            ru = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
            # linux reports KiB, macOS bytes; off-/proc we assume KiB
            vitals["rss_bytes"] = int(ru) * 1024
        except Exception:
            pass
    try:
        vitals["open_fds"] = len(os.listdir("/proc/self/fd"))
    except OSError:
        pass
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("Threads:"):
                    vitals["threads"] = int(line.split()[1])
                    break
    except (OSError, IndexError, ValueError):
        pass
    if vitals["threads"] is None:
        vitals["threads"] = threading.active_count()
    return vitals


def theil_sen_slope(points: List[Tuple[float, float]]) -> float:
    """Median of all pairwise slopes — the robust trend estimate.
    O(n^2) pairs on a <=32-point window is trivial."""
    slopes: List[float] = []
    n = len(points)
    for i in range(n):
        t0, v0 = points[i]
        for j in range(i + 1, n):
            t1, v1 = points[j]
            if t1 != t0:
                slopes.append((v1 - v0) / (t1 - t0))
    if not slopes:
        return 0.0
    slopes.sort()
    m = len(slopes)
    mid = m // 2
    return slopes[mid] if m % 2 else 0.5 * (slopes[mid - 1] + slopes[mid])


class TrendWatchdog:
    """One watched series: bounded window + Theil-Sen slope + alarm
    hysteresis."""

    __slots__ = ("series", "threshold", "min_points", "cooldown_s",
                 "points", "last_alarm", "alarms")

    def __init__(self, series: str, threshold: float,
                 window: int = DEFAULT_WINDOW,
                 min_points: int = DEFAULT_MIN_POINTS,
                 cooldown_s: float = DEFAULT_COOLDOWN_S):
        self.series = series
        self.threshold = float(threshold)
        self.min_points = int(min_points)
        self.cooldown_s = float(cooldown_s)
        self.points: deque = deque(maxlen=int(window))
        self.last_alarm: Optional[float] = None
        self.alarms = 0

    def observe(self, t: float, v: float) -> None:
        self.points.append((float(t), float(v)))

    def evaluate(self, now: float, mutate: bool = True) -> Dict[str, Any]:
        """Verdict for the current window.  ``mutate=False`` (the
        ``report`` path) never consumes the cooldown, so a read-only
        peek cannot swallow the alarm the next sample tick owes."""
        pts = list(self.points)
        slope = theil_sen_slope(pts)
        verdict: Dict[str, Any] = {
            "series": self.series, "n": len(pts),
            "slope_per_s": round(slope, 3),
            "threshold_per_s": self.threshold,
            "value": pts[-1][1] if pts else None,
            "alarms": self.alarms, "alarm": False,
        }
        if len(pts) < self.min_points or slope <= self.threshold:
            return verdict
        if self.last_alarm is not None \
                and now - self.last_alarm < self.cooldown_s:
            verdict["suppressed"] = True
            return verdict
        if mutate:
            self.last_alarm = now
            self.alarms += 1
            verdict["alarms"] = self.alarms
        verdict["alarm"] = True
        return verdict


class CeilingMonitor:
    """The watchdog pack: feeds every configured series per tick and
    funnels alarms into counters / traces / decisions / the archive."""

    def __init__(self, thresholds: Optional[Dict[str, float]] = None,
                 window: int = DEFAULT_WINDOW,
                 min_points: int = DEFAULT_MIN_POINTS,
                 cooldown_s: float = DEFAULT_COOLDOWN_S,
                 clock: Callable[[], float] = time.monotonic,
                 decision_log: Any = None):
        self._lock = threading.Lock()
        self._clock = clock
        self.decision_log = decision_log  # fleet DecisionLog, optional
        self._dogs: Dict[str, TrendWatchdog] = {}
        for series, thr in (thresholds or DEFAULT_THRESHOLDS).items():
            self._dogs[series] = TrendWatchdog(
                series, thr, window=window, min_points=min_points,
                cooldown_s=cooldown_s)

    def sample(self, extra: Optional[Dict[str, float]] = None,
               now: Optional[float] = None) -> List[Dict[str, Any]]:
        """One tick: gather vitals + ambient gauges + ``extra`` series
        values, evaluate every watchdog, emit alarms.  Returns the
        alarms raised this tick."""
        from image_analogies_tpu.obs import archive as _archive
        from image_analogies_tpu.obs import ledger as _ledger

        if now is None:
            now = self._clock()
        values: Dict[str, float] = {}
        vitals = read_proc_vitals()
        if vitals.get("rss_bytes") is not None:
            values["proc.rss_bytes"] = float(vitals["rss_bytes"])
            _metrics.set_gauge("proc.rss_bytes", float(vitals["rss_bytes"]))
        if vitals.get("open_fds") is not None:
            _metrics.set_gauge("proc.open_fds", float(vitals["open_fds"]))
        if vitals.get("threads") is not None:
            _metrics.set_gauge("proc.threads", float(vitals["threads"]))
        reg = _metrics.registry()
        if reg is not None:
            gauges = reg.snapshot().get("gauges") or {}
            if "devcache.bytes" in gauges:
                values["devcache.bytes"] = float(gauges["devcache.bytes"])
        ar = _archive.current()
        if ar is not None:
            values["archive.bytes"] = float(ar.stats().get("bytes") or 0)
        for k, v in (extra or {}).items():
            if v is not None:
                values[k] = float(v)
        alarms: List[Dict[str, Any]] = []
        with self._lock:
            for series, v in values.items():
                dog = self._dogs.get(series)
                if dog is None:
                    continue
                dog.observe(now, v)
                verdict = dog.evaluate(now)
                if verdict["alarm"]:
                    alarms.append(verdict)
        for verdict in alarms:
            series = verdict["series"]
            _metrics.inc("obs.ceiling.alarms")
            _metrics.inc(f"obs.ceiling.{series}")
            _trace.emit_record({"event": "ceiling_alarm", **{
                k: verdict[k] for k in ("series", "slope_per_s",
                                        "threshold_per_s", "value")}})
            _ledger.emit_decision(
                "ceilings", "alarm", cause=f"{series}_trend",
                slope_per_s=verdict["slope_per_s"],
                threshold_per_s=verdict["threshold_per_s"])
            if self.decision_log is not None:
                try:
                    self.decision_log.record(
                        None, "ceilings", "alarm",
                        cause=f"{series}_trend",
                        slope_per_s=verdict["slope_per_s"])
                except Exception:
                    pass
            _archive.record("anomaly", {"series": series,
                                        "kind": "ceiling",
                                        "slope_per_s":
                                        verdict["slope_per_s"]})
        return alarms

    def report(self) -> Dict[str, Any]:
        """The ``ceilings`` section for ``ia report`` / ``/healthz``."""
        with self._lock:
            now = self._clock()
            out = {}
            for series, dog in self._dogs.items():
                v = dog.evaluate(now, mutate=False)
                v.pop("suppressed", None)
                out[series] = v
        return out


# --- module-level armed plane ------------------------------------------------

_ARMED = False
_ARM_LOCK = threading.Lock()
_ARM_COUNT = 0
_MONITOR: Optional[CeilingMonitor] = None


def arm(monitor: Optional[CeilingMonitor] = None,
        **kwargs: Any) -> CeilingMonitor:
    """Install (or join) the process ceilings monitor.  Arming registers
    a timeline-sampler feeder so a standalone ``ia serve --http``
    samples vitals without extra wiring; the fleet health loop calls
    :func:`sample` itself (with journal bytes in ``extra``)."""
    from image_analogies_tpu.obs import timeline as _timeline

    global _ARMED, _ARM_COUNT, _MONITOR
    with _ARM_LOCK:
        if _MONITOR is None:
            _MONITOR = monitor if monitor is not None \
                else CeilingMonitor(**kwargs)
        _ARM_COUNT += 1
        _ARMED = True
        _timeline.register_feeder(_feed)
        return _MONITOR


def disarm() -> None:
    from image_analogies_tpu.obs import timeline as _timeline

    global _ARMED, _ARM_COUNT, _MONITOR
    with _ARM_LOCK:
        _ARM_COUNT = max(_ARM_COUNT - 1, 0)
        if _ARM_COUNT == 0:
            _MONITOR = None
            _ARMED = False
            _timeline.unregister_feeder(_feed)


def current() -> Optional[CeilingMonitor]:
    return _MONITOR if _ARMED else None


def sample(extra: Optional[Dict[str, float]] = None) -> None:
    """Producer fast path: one bool check when disarmed."""
    if not _ARMED:
        return
    mon = _MONITOR
    if mon is not None:
        mon.sample(extra=extra)


def _feed() -> None:
    sample()


def report_doc() -> Optional[Dict[str, Any]]:
    mon = _MONITOR if _ARMED else None
    return None if mon is None else mon.report()


def selftest(seed: int = 11, n: int = 24,
             slope_bytes_per_s: float = 4 << 20) -> Dict[str, Any]:
    """Seeded leak-detection drill, scaled down for tier-1: a synthetic
    monotonic RSS trend (slope well over threshold, with noise) must
    alarm within the window budget (``min_points`` ticks); a flat noisy
    series must not.  Deterministic: injected clock, seeded noise."""
    import random

    rng = random.Random(seed)
    dog = TrendWatchdog("proc.rss_bytes",
                        DEFAULT_THRESHOLDS["proc.rss_bytes"],
                        cooldown_s=0.0)
    flat = TrendWatchdog("proc.rss_bytes",
                         DEFAULT_THRESHOLDS["proc.rss_bytes"],
                         cooldown_s=0.0)
    base = 512 << 20
    first_alarm: Optional[int] = None
    flat_alarms = 0
    for i in range(n):
        t = float(i)
        noise = rng.uniform(-64 << 10, 64 << 10)
        dog.observe(t, base + slope_bytes_per_s * i + noise)
        flat.observe(t, base + noise)
        if dog.evaluate(t)["alarm"] and first_alarm is None:
            first_alarm = i
        if flat.evaluate(t)["alarm"]:
            flat_alarms += 1
    return {"seed": seed, "n": n,
            "first_alarm_tick": first_alarm,
            "budget_ticks": DEFAULT_MIN_POINTS,
            "flat_alarms": flat_alarms,
            "ok": first_alarm is not None
            and first_alarm <= DEFAULT_MIN_POINTS
            and flat_alarms == 0}
