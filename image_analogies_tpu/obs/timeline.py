"""Windowed time-series over the scoped metrics plane.

The cumulative registries (obs/metrics.py) answer "what happened over
this run"; this module answers "what is happening NOW and how did it
change over the last minute".  A :class:`Timeline` is a fixed-memory
ring store of per-window aggregates over named series:

- **counter** series hold the delta of a cumulative counter per window
  (a worker replacement resets its registry; a sample smaller than the
  previous one is treated as a fresh generation, not a negative delta);
- **gauge** series hold the last sampled value of the window;
- **hist** series hold a mergeable :class:`~image_analogies_tpu.obs.
  metrics.Histogram` of the window's new samples (the cumulative
  summary diff), so p50/p95 are per-window, not lifetime.

Windows cascade through downsampling tiers (1s -> 10s -> 60s by
default): when a tier-i window closes it is folded — counters add,
gauges keep the last value, histograms :meth:`Histogram.merge` — into
the tier-i+1 window covering its start, and each tier is a bounded
deque, so total memory is fixed regardless of uptime.

An EWMA/MAD z-score detector runs over closed tier-0 latency and
queue-depth windows; outliers bump ``obs.anomaly.*`` counters through
the ambient scope and surface as an :func:`advisory` hint the degrade
ladder (or an operator watching ``ia top``) may consume.

Producers feed a timeline explicitly: the fleet health daemon samples
each worker's registry snapshot per poll (worker-labeled series, e.g.
``w0:serve.completed``), and :meth:`Timeline.start_sampler` runs a
background thread for single-server deployments.  Consumers read
:meth:`range` / :meth:`to_json` (the ``/timeline`` HTTP endpoint) and
the pure :func:`cockpit_rows` / :func:`render_cockpit` renderers that
``ia top`` draws.

The module-level plane is DISARMED by default and zero-cost while so:
:func:`sample_ambient` / :func:`sample_snapshot` read one module bool
and return — no allocation, no lock — the same contract (and the same
tracemalloc lock in tests) as the disabled metrics registry.  The clock
is injectable for deterministic tests.

No jax / numpy imports here (grep-locked like live.py): the timeline
must be importable from any layer without forcing device init.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from image_analogies_tpu.obs import metrics as _metrics
from image_analogies_tpu.obs import quantiles as _quantiles

# (window_seconds, ring_capacity) per tier, coarsening left to right:
# 2 minutes of 1s, 15 minutes of 10s, 1 hour of 60s — fixed memory.
DEFAULT_TIERS: Tuple[Tuple[float, int], ...] = (
    (1.0, 120), (10.0, 90), (60.0, 60))

# EWMA/MAD z-score detector defaults (tier-0 closed windows).
Z_THRESHOLD = 4.0
EWMA_ALPHA = 0.3
WARMUP_WINDOWS = 8
MAX_HINTS = 64
_MAD_SCALE = 1.4826  # MAD -> sigma under normality


def _anomaly_series(name: str) -> bool:
    return name.endswith("latency_ms") or name.endswith("queue_depth")


class _Window:
    """One aggregation window: ``series`` maps name -> float (counter
    delta / gauge last-value) or Histogram (windowed samples)."""

    __slots__ = ("start", "series", "closed")

    def __init__(self, start: float):
        self.start = start
        self.series: Dict[str, Any] = {}
        self.closed = False  # folded into the next tier already


class _Tier:
    __slots__ = ("window_s", "windows")

    def __init__(self, window_s: float, capacity: int):
        self.window_s = window_s
        self.windows: deque = deque(maxlen=capacity)

    def window_at(self, start: float) -> _Window:
        """The window whose start is ``start``, appended if absent.
        Folds arrive in closing order, so the target is always the
        newest window or a brand-new one."""
        if self.windows and self.windows[-1].start == start:
            return self.windows[-1]
        w = _Window(start)
        self.windows.append(w)
        return w


class Timeline:
    """Fixed-memory windowed store with downsampling tiers and an
    anomaly detector.  Thread-safe; the clock is injectable."""

    def __init__(self, tiers: Tuple[Tuple[float, int], ...] = DEFAULT_TIERS,
                 clock: Callable[[], float] = time.monotonic,
                 z_threshold: float = Z_THRESHOLD,
                 warmup: int = WARMUP_WINDOWS,
                 alpha: float = EWMA_ALPHA):
        if not tiers:
            raise ValueError("timeline needs at least one tier")
        self._lock = threading.Lock()
        self._tiers = [_Tier(ws, cap) for ws, cap in tiers]
        self._clock = clock
        self._z = float(z_threshold)
        self._warmup = int(warmup)
        self._alpha = float(alpha)
        # Per-series cumulative baselines (counter last value / histogram
        # last summary / sketch last summary) so each sample contributes
        # only its delta.
        self._cum: Dict[str, float] = {}
        self._cum_h: Dict[str, Dict] = {}
        self._cum_q: Dict[str, Dict] = {}
        self._kinds: Dict[str, str] = {}
        # Last sample wall time per series key, so baselines from dead
        # worker generations can be pruned instead of pinned forever.
        self._last_seen: Dict[str, float] = {}
        self._next_prune = 0.0
        self.series_pruned = 0
        # EWMA state per anomaly-watched series: [mean, mad, n_windows].
        self._ewma: Dict[str, List[float]] = {}
        self._hints: deque = deque(maxlen=MAX_HINTS)
        self._sampler: Optional[threading.Thread] = None
        self._sampler_stop = threading.Event()

    # --- ingest --------------------------------------------------------------

    def sample_snapshot(self, snap: Dict[str, dict],
                        worker: Optional[str] = None,
                        now: Optional[float] = None) -> None:
        """Fold one registry snapshot (``MetricsRegistry.snapshot()``
        shape) into the current tier-0 window.  ``worker`` labels every
        series ``worker:name`` so N isolated registries coexist in one
        timeline; fleet-level snapshots pass no worker."""
        if now is None:
            now = self._clock()
        prefix = f"{worker}:" if worker else ""
        with self._lock:
            self._advance_locked(now)
            t0 = self._tiers[0]
            win = t0.window_at(math.floor(now / t0.window_s) * t0.window_s)
            for name, v in (snap.get("counters") or {}).items():
                key = prefix + name
                prev = self._cum.get(key, 0.0)
                # v < prev: the source registry restarted (worker
                # replacement) — the whole value is this window's delta.
                delta = v - prev if v >= prev else v
                self._cum[key] = v
                self._kinds[key] = "counter"
                if delta:
                    win.series[key] = win.series.get(key, 0.0) + delta
            for name, v in (snap.get("gauges") or {}).items():
                key = prefix + name
                self._kinds[key] = "gauge"
                win.series[key] = v
            for name, summ in (snap.get("histograms") or {}).items():
                key = prefix + name
                self._kinds[key] = "hist"
                delta_h = self._hist_delta_locked(key, summ)
                if delta_h.count:
                    cur = win.series.get(key)
                    if cur is None:
                        win.series[key] = delta_h
                    else:
                        cur.merge(delta_h)
            for name, summ in (snap.get("sketches") or {}).items():
                # distinct key: the same registry name also carries the
                # base-2 histogram; ".q" keeps the kinds from colliding.
                key = prefix + name + ".q"
                self._kinds[key] = "sketch"
                prev = self._cum_q.get(key)
                delta = _quantiles.delta_summary(summ, prev)
                if delta is None:  # count regressed: fresh generation
                    delta = dict(summ)
                self._cum_q[key] = summ
                if int(delta.get("count", 0)) > 0:
                    cur = win.series.get(key)
                    win.series[key] = delta if cur is None else \
                        _quantiles.merge_summaries([cur, delta])
            stamp = now
            for name in (snap.get("counters") or {}):
                self._last_seen[prefix + name] = stamp
            for name in (snap.get("gauges") or {}):
                self._last_seen[prefix + name] = stamp
            for name in (snap.get("histograms") or {}):
                self._last_seen[prefix + name] = stamp
            for name in (snap.get("sketches") or {}):
                self._last_seen[prefix + name + ".q"] = stamp
            self._prune_locked(stamp)

    def _hist_delta_locked(self, key: str, summ: Dict) -> _metrics.Histogram:
        """New samples since the last snapshot of ``key``, as a
        mergeable histogram.  Window min/max are approximated by the
        cumulative extremes (the summary does not carry per-sample
        order); a count regression means a fresh source generation."""
        prev = self._cum_h.get(key)
        self._cum_h[key] = summ
        cur_n = int(summ.get("count", 0) or 0)
        if prev is None or cur_n < int(prev.get("count", 0) or 0):
            return _metrics.Histogram.from_summary(summ)
        h = _metrics.Histogram()
        n = cur_n - int(prev.get("count", 0) or 0)
        if n <= 0:
            return h
        h.count = n
        h.total = float(summ.get("sum", 0.0)) - float(prev.get("sum", 0.0))
        h.min = float(summ.get("min", 0.0))
        h.max = float(summ.get("max", 0.0))
        pb = prev.get("buckets") or {}
        for k, v in (summ.get("buckets") or {}).items():
            d = int(v) - int(pb.get(k, 0))
            if d > 0:
                h.buckets[int(k)] = d
        return h

    def _prune_locked(self, now: float) -> None:
        """Drop per-series baselines (cum / cum_h / cum_q / kinds /
        ewma) idle for more than two full tier-0 retentions.  A SIGKILLed
        worker's ``w<N>:`` series stop arriving the moment its scrape
        dies; without this, every generation's baselines stay pinned for
        the life of the fleet.  Ring windows age the *values* out on
        their own; this reclaims the dictionaries."""
        t0 = self._tiers[0]
        retention = t0.window_s * (t0.windows.maxlen or 1)
        if now < self._next_prune:
            return
        self._next_prune = now + retention
        horizon = now - 2.0 * retention
        stale = [k for k, ts in self._last_seen.items() if ts < horizon]
        for k in stale:
            self._last_seen.pop(k, None)
            self._cum.pop(k, None)
            self._cum_h.pop(k, None)
            self._cum_q.pop(k, None)
            self._kinds.pop(k, None)
            self._ewma.pop(k, None)
        if stale:
            self.series_pruned += len(stale)
            _metrics.inc("timeline.series_pruned", len(stale))

    # --- window lifecycle ----------------------------------------------------

    def _advance_locked(self, now: float) -> None:
        """Close every window whose span has passed, folding it into
        the next tier.  Ascending tier order: a tier-0 closure may land
        in a tier-1 window that this same advance is about to close."""
        for i, tier in enumerate(self._tiers):
            cur_start = math.floor(now / tier.window_s) * tier.window_s
            nxt = self._tiers[i + 1] if i + 1 < len(self._tiers) else None
            for w in tier.windows:
                if w.start >= cur_start:
                    break
                if w.closed:
                    continue
                # deque entries older than cur_start and not yet folded
                self._close_locked(i, w, nxt)

    def _close_locked(self, tier_i: int, w: _Window,
                      nxt: Optional[_Tier]) -> None:
        w.closed = True
        if tier_i == 0:
            self._detect_locked(w)
        if nxt is None:
            return
        target = nxt.window_at(
            math.floor(w.start / nxt.window_s) * nxt.window_s)
        for key, v in w.series.items():
            kind = self._kinds.get(key, "gauge")
            if kind == "counter":
                target.series[key] = target.series.get(key, 0.0) + v
            elif kind == "hist":
                cur = target.series.get(key)
                if cur is None:
                    h = _metrics.Histogram()
                    h.merge(v)
                    target.series[key] = h
                else:
                    cur.merge(v)
            elif kind == "sketch":
                cur = target.series.get(key)
                target.series[key] = dict(v) if cur is None else \
                    _quantiles.merge_summaries([cur, v])
            else:  # gauge: last value wins (windows close in time order)
                target.series[key] = v

    # --- anomaly detection ---------------------------------------------------

    def _detect_locked(self, w: _Window) -> None:
        for key, v in w.series.items():
            if not _anomaly_series(key):
                continue
            x = v.total / v.count if isinstance(v, _metrics.Histogram) \
                else float(v)
            state = self._ewma.get(key)
            if state is None:
                self._ewma[key] = [x, 0.0, 1.0]
                continue
            mean, mad, n = state
            dev = abs(x - mean)
            sigma = mad * _MAD_SCALE
            if n >= self._warmup and sigma > 1e-9:
                z = dev / sigma
                if z > self._z:
                    self._hints.append({
                        "series": key, "window_start": w.start,
                        "value": round(x, 3), "baseline": round(mean, 3),
                        "z": round(z, 2)})
                    _metrics.inc("obs.anomaly.total")
                    _metrics.inc(f"obs.anomaly.{key}")
                    # An outlier must not drag the baseline toward
                    # itself — skip the EWMA update for this window.
                    continue
            a = self._alpha
            state[0] = (1 - a) * mean + a * x
            state[1] = (1 - a) * mad + a * dev
            state[2] = n + 1

    # --- queries -------------------------------------------------------------

    def _tier_for(self, window_s: Optional[float]) -> _Tier:
        if window_s is None:
            return self._tiers[0]
        for tier in self._tiers:
            if tier.window_s == float(window_s):
                return tier
        raise KeyError(f"no timeline tier with window_s={window_s}; "
                       f"have {[t.window_s for t in self._tiers]}")

    @staticmethod
    def _point_value(v: Any) -> Any:
        if isinstance(v, _metrics.Histogram):
            return {"count": v.count, "sum": round(v.total, 3),
                    "mean": round(v.total / v.count, 3) if v.count else 0.0,
                    "p50": round(v.percentile(50), 3),
                    "p95": round(v.percentile(95), 3),
                    "max": round(v.max, 3) if v.count else 0.0}
        if isinstance(v, dict) and "bins" in v and "alpha" in v:
            sk = _quantiles.QuantileSketch.from_summary(v)
            out = {"count": sk.count,
                   "max": round(sk.max, 3) if sk.count else 0.0}
            out.update(sk.quantiles_doc())
            return out
        return v

    def range(self, series: str, window_s: Optional[float] = None
              ) -> List[Tuple[float, Any]]:
        """``[(window_start, value), ...]`` ascending for one series at
        one tier (default: the finest).  Histogram values come back as
        summary dicts with per-window p50/p95."""
        tier = self._tier_for(window_s)
        with self._lock:
            self._advance_locked(self._clock())
            return [(w.start, self._point_value(w.series[series]))
                    for w in tier.windows if series in w.series]

    def to_json(self, window_s: Optional[float] = None) -> Dict[str, Any]:
        """The ``/timeline`` document: every series at one tier, plus
        tier geometry and recent anomaly hints."""
        tier = self._tier_for(window_s)
        with self._lock:
            now = self._clock()
            self._advance_locked(now)
            series: Dict[str, Any] = {}
            for w in tier.windows:
                for key, v in w.series.items():
                    ent = series.setdefault(
                        key, {"kind": self._kinds.get(key, "gauge"),
                              "points": []})
                    ent["points"].append([w.start, self._point_value(v)])
            return {
                "armed": True,
                "now": round(now, 3),
                "window_s": tier.window_s,
                "tiers": [{"window_s": t.window_s,
                           "capacity": t.windows.maxlen,
                           "windows": len(t.windows)}
                          for t in self._tiers],
                "series": series,
                "anomalies": list(self._hints),
            }

    def advisory(self) -> Optional[Dict[str, Any]]:
        """The newest anomaly hint within the last two tier-0 windows —
        the degrade ladder's one-line view — or None when healthy."""
        with self._lock:
            if not self._hints:
                return None
            hint = self._hints[-1]
            horizon = self._clock() - 2 * self._tiers[0].window_s
            if hint["window_start"] < horizon:
                return None
            return dict(hint, degrade_hint=True)

    # --- background sampler --------------------------------------------------

    def start_sampler(self, interval_s: float = 1.0,
                      snap_fn: Optional[Callable[[], Dict]] = None,
                      worker: Optional[str] = None) -> None:
        """Background thread sampling ``snap_fn()`` (default: the
        ambient scope's snapshot) every ``interval_s``.  Single-server
        deployments use this; the fleet health daemon samples each
        worker itself."""
        if self._sampler is not None:
            return
        fn = snap_fn or _metrics.snapshot
        self._sampler_stop.clear()

        def _loop():
            while not self._sampler_stop.wait(interval_s):
                try:
                    self.sample_snapshot(fn(), worker=worker)
                except Exception:
                    _metrics.inc("obs.timeline.sampler_errors")
                for feeder in list(_FEEDERS):
                    try:
                        feeder()
                    except Exception:
                        _metrics.inc("obs.timeline.sampler_errors")

        self._sampler = threading.Thread(
            target=_loop, name="ia-timeline-sampler", daemon=True)
        self._sampler.start()

    def stop_sampler(self) -> None:
        if self._sampler is None:
            return
        self._sampler_stop.set()
        self._sampler.join(timeout=5.0)
        self._sampler = None


# --- sampler feeders ---------------------------------------------------------
#
# Other armed planes (obs/ledger.py's per-tenant series) register a
# zero-arg feeder here; a running sampler calls each after its own
# sample, so tenant-labeled series ride whichever sampler exists
# (standalone `ia serve --http` — the fleet health loop feeds directly).

_FEEDERS: List[Callable[[], None]] = []


def register_feeder(fn: Callable[[], None]) -> None:
    if fn not in _FEEDERS:
        _FEEDERS.append(fn)


def unregister_feeder(fn: Callable[[], None]) -> None:
    try:
        _FEEDERS.remove(fn)
    except ValueError:
        pass


# --- module-level armed plane ------------------------------------------------
#
# Mirrors the metrics registry's module fast path: _ARMED is one bool,
# and every producer-side helper checks it FIRST and returns — the
# disarmed path allocates nothing (tracemalloc-locked in tests).

_ARMED = False
_ARM_LOCK = threading.Lock()
_ARM_COUNT = 0
_TIMELINE: Optional[Timeline] = None


def arm(timeline: Optional[Timeline] = None, **kwargs: Any) -> Timeline:
    """Install (or join) the process timeline.  Re-arming nests: the
    fleet arms for its lifetime while `ia serve --http` arms for the
    server's; the plane disarms when the last owner leaves."""
    global _ARMED, _ARM_COUNT, _TIMELINE
    with _ARM_LOCK:
        if _TIMELINE is None:
            _TIMELINE = timeline if timeline is not None \
                else Timeline(**kwargs)
        _ARM_COUNT += 1
        _ARMED = True
        return _TIMELINE


def disarm() -> None:
    global _ARMED, _ARM_COUNT, _TIMELINE
    with _ARM_LOCK:
        _ARM_COUNT = max(_ARM_COUNT - 1, 0)
        if _ARM_COUNT == 0:
            t = _TIMELINE
            _TIMELINE = None
            _ARMED = False
            if t is not None:
                t.stop_sampler()


def current() -> Optional[Timeline]:
    return _TIMELINE if _ARMED else None


def sample_snapshot(snap: Dict[str, dict],
                    worker: Optional[str] = None) -> None:
    """Producer fast path: one bool check when disarmed."""
    if not _ARMED:
        return
    t = _TIMELINE
    if t is not None:
        t.sample_snapshot(snap, worker=worker)


def sample_ambient() -> None:
    """Sample the ambient scope's registry into the armed timeline;
    zero-cost when disarmed or no scope is active."""
    if not _ARMED:
        return
    t = _TIMELINE
    if t is not None:
        reg = _metrics.registry()
        if reg is not None:
            t.sample_snapshot(reg.snapshot())


def snapshot_json(window_s: Optional[float] = None) -> Dict[str, Any]:
    t = _TIMELINE if _ARMED else None
    if t is None:
        return {"armed": False, "series": {}, "anomalies": []}
    return t.to_json(window_s)


def advisory() -> Optional[Dict[str, Any]]:
    if not _ARMED:
        return None
    t = _TIMELINE
    return t.advisory() if t is not None else None


# --- cockpit rendering (pure; `ia top` and tests share it) -------------------

_BREAKER_NAMES = {0: "closed", 1: "half", 2: "OPEN"}


def _last_point(ent: Optional[Dict]) -> Optional[Tuple[float, Any]]:
    if not ent or not ent["points"]:
        return None
    start, v = ent["points"][-1]
    return start, v


def cockpit_rows(doc: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Digest a ``/timeline`` document into one row per worker label
    (plus a fleet-level '-' row when unlabeled series exist): QPS from
    the completed-counter delta, p50/p95 from the windowed latency
    histogram, queue depth / breaker / HBM peak from gauges, anomaly
    count from the hints that name the worker."""
    window_s = float(doc.get("window_s") or 1.0)
    series = doc.get("series") or {}
    workers: Dict[str, Dict[str, Any]] = {}

    def row(worker: str) -> Dict[str, Any]:
        return workers.setdefault(worker, {
            "worker": worker, "qps": 0.0, "p50": None, "p95": None,
            "p999": None, "queue": None, "breaker": "", "hbm": None,
            "anomalies": 0})

    for key, ent in series.items():
        worker, _, name = key.rpartition(":")
        worker = worker or "-"
        last = _last_point(ent)
        if last is None:
            continue
        _, v = last
        if name == "serve.completed":
            row(worker)["qps"] = round(float(v) / window_s, 2)
        elif name == "serve.latency_ms" and isinstance(v, dict):
            row(worker)["p50"] = v.get("p50")
            row(worker)["p95"] = v.get("p95")
        elif name == "serve.latency_ms.q" and isinstance(v, dict):
            row(worker)["p999"] = v.get("p999")
        elif name == "serve.queue_depth":
            row(worker)["queue"] = v
        elif name.startswith("serve.breaker.state."):
            state = _BREAKER_NAMES.get(int(v), str(v))
            r = row(worker)
            r["breaker"] = state if not r["breaker"] \
                else f"{r['breaker']},{state}"
        elif name.startswith("hbm.peak_bytes"):
            r = row(worker)
            r["hbm"] = max(float(v), r["hbm"] or 0.0)
    for hint in doc.get("anomalies") or []:
        worker, _, _ = str(hint.get("series", "")).rpartition(":")
        worker = worker or "-"
        if worker in workers:
            workers[worker]["anomalies"] += 1
    return [workers[k] for k in sorted(workers)]


def render_cockpit(doc: Dict[str, Any]) -> str:
    """One terminal frame of the ``ia top`` cockpit."""
    rows = cockpit_rows(doc)
    hdr = (f"{'WORKER':<10} {'QPS':>8} {'P50ms':>8} {'P95ms':>8} "
           f"{'P999ms':>8} {'QUEUE':>6} {'BREAKER':>12} {'HBM':>10} "
           f"{'ANOM':>5}")
    lines = [f"ia top — window {doc.get('window_s', '?')}s, "
             f"{len(doc.get('series') or {})} series"
             + ("" if doc.get("armed", True) else "  [timeline disarmed]"),
             hdr, "-" * len(hdr)]

    def fmt(v, spec="{:.1f}"):
        return "-" if v is None else spec.format(v)

    def fmt_hbm(v):
        if v is None:
            return "-"
        return f"{v / (1 << 20):.1f}M" if v >= 1 << 20 else f"{v:.0f}"

    for r in rows:
        lines.append(
            f"{r['worker']:<10} {r['qps']:>8.2f} {fmt(r['p50']):>8} "
            f"{fmt(r['p95']):>8} {fmt(r.get('p999')):>8} "
            f"{fmt(r['queue'], '{:.0f}'):>6} "
            f"{(r['breaker'] or '-'):>12} {fmt_hbm(r['hbm']):>10} "
            f"{r['anomalies']:>5d}")
    if not rows:
        lines.append("(no series yet)")
    for hint in (doc.get("anomalies") or [])[-3:]:
        lines.append(f"! anomaly {hint.get('series')}: "
                     f"value {hint.get('value')} vs baseline "
                     f"{hint.get('baseline')} (z={hint.get('z')})")
    return "\n".join(lines)
