"""Tenant-scoped metering plane: per-request cost vectors + decisions.

Three planes in one module, all host-side and jax-free (grep-locked):

**Cost ledger.**  serve/worker.py assembles one *cost vector* per
completed dispatch — queue wait, device/dispatch ms, batch lanes shared,
degrade steps, retries, ANN/catalog engagement, wire bytes — stamped
with the tenant key (the batcher exemplar sha1: style == tenant) and the
trace id.  Vectors land in a bounded in-memory deque (:class:`Ledger`)
and, when a request journal is armed, as sealed ``cost`` records beside
the request's own transitions (serve/journal.py), so `ia why` can read
them back offline.

**Heavy hitters.**  Each vector feeds the fixed-memory
:class:`~image_analogies_tpu.obs.tenants.TenantTracker` (space-saving
top-K), whose document is the ``/tenants`` endpoint contract::

    {"armed": true, "capacity": N, "recorded": n, "uptime_s": s,
     "k": K, "tracked": t, "offered": n,
     "tenants": [{"tenant", "count", "count_error", "requests",
                  "degraded", "retries", "errors", "lanes",
                  "wire_bytes", "dispatch_ms", "queue_ms",
                  "cost_share", "p50_ms", "p95_ms", "qps",
                  "latency": <histogram summary>}, ...]}

:func:`sample_timeline` mirrors the tracked tenants into the PR 14
timeline store as ``tenant:<sha1[:8]>``-labeled series (cumulative
counters + latency histograms, so the timeline's delta logic and
per-worker anomaly detector fire per-tenant with no changes).

**Decision attribution.**  :func:`emit_decision` is the single funnel
for control-plane verdicts (degrade, shed, spill, poison, dedupe,
handoff re-chain, ...): it bumps ``serve.decision.<verdict>`` and emits
a ``serve_decision`` trace record carrying cause + site + trace id.
Journal-side persistence is the caller's job (journal.record_decision /
DecisionLog) so this module stays import-light on the request path.

Armed/disarmed module plane mirrors obs/timeline.py: one bool check
when disarmed, zero allocations (tracemalloc-locked in tests), arm()
nests across owners.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Dict, List, Optional

from image_analogies_tpu.obs import metrics as _metrics
from image_analogies_tpu.obs import timeline as _timeline
from image_analogies_tpu.obs import trace as _trace
from image_analogies_tpu.obs.tenants import TenantTracker


class Ledger:
    """Bounded in-memory cost-vector store + tenant tracker."""

    def __init__(self, capacity: int = 512, tenant_k: int = 16):
        self.capacity = max(1, int(capacity))
        self._lock = threading.Lock()
        self._vecs: collections.deque = collections.deque(
            maxlen=self.capacity)
        self._recorded = 0
        self._t0 = time.monotonic()
        self.tenants = TenantTracker(tenant_k)

    def record(self, vec: Dict[str, Any]) -> None:
        with self._lock:
            self._vecs.append(vec)
            self._recorded += 1
        tenant = vec.get("tenant")
        if tenant:
            self.tenants.observe(
                str(tenant),
                latency_ms=float(vec.get("total_ms") or 0.0),
                queue_ms=float(vec.get("queue_ms") or 0.0),
                dispatch_ms=float(vec.get("dispatch_ms") or 0.0),
                lanes=int(vec.get("lanes") or 1),
                degraded=bool(vec.get("degrade_levels")),
                retries=int(vec.get("retries") or 0),
                wire_bytes=int(vec.get("wire_bytes") or 0),
                error=vec.get("status") not in (None, "ok"))

    def recent(self, n: Optional[int] = None) -> List[Dict[str, Any]]:
        with self._lock:
            vecs = list(self._vecs)
        return vecs if n is None else vecs[-n:]

    def tenants_doc(self) -> Dict[str, Any]:
        doc = self.tenants.snapshot()
        uptime = max(time.monotonic() - self._t0, 1e-9)
        for row in doc["tenants"]:
            row["qps"] = round(row["requests"] / uptime, 4)
        with self._lock:
            recorded = self._recorded
        doc.update(armed=True, capacity=self.capacity,
                   recorded=recorded, uptime_s=round(uptime, 3))
        return doc


# --- module-level armed plane ------------------------------------------------
#
# Mirrors obs/timeline.py: _ARMED is one bool, every producer helper
# checks it FIRST — the disarmed path allocates nothing (tracemalloc-
# locked in tests/test_ledger.py).  arm() nests across owners.

_ARMED = False
_ARM_LOCK = threading.Lock()
_ARM_COUNT = 0
_LEDGER: Optional[Ledger] = None


def arm(ledger: Optional[Ledger] = None, **kwargs: Any) -> Ledger:
    """Install (or join) the process ledger; registers the timeline
    feeder so a running sampler mirrors per-tenant series."""
    global _ARMED, _ARM_COUNT, _LEDGER
    with _ARM_LOCK:
        if _LEDGER is None:
            _LEDGER = ledger if ledger is not None else Ledger(**kwargs)
            _timeline.register_feeder(sample_timeline)
        _ARM_COUNT += 1
        _ARMED = True
        return _LEDGER


def disarm() -> None:
    global _ARMED, _ARM_COUNT, _LEDGER
    with _ARM_LOCK:
        _ARM_COUNT = max(_ARM_COUNT - 1, 0)
        if _ARM_COUNT == 0:
            _LEDGER = None
            _ARMED = False
            _timeline.unregister_feeder(sample_timeline)


def armed() -> bool:
    return _ARMED


def current() -> Optional[Ledger]:
    return _LEDGER if _ARMED else None


def record(vec: Dict[str, Any]) -> None:
    """Producer fast path: one bool check when disarmed."""
    if not _ARMED:
        return
    led = _LEDGER
    if led is not None:
        led.record(vec)


def tenants_doc() -> Dict[str, Any]:
    led = _LEDGER if _ARMED else None
    if led is None:
        return {"armed": False, "k": 0, "tracked": 0, "offered": 0,
                "recorded": 0, "tenants": []}
    return led.tenants_doc()


def record_throttle(tenant: str) -> None:
    """Producer fast path for a quota refusal: one bool check when
    disarmed, else a sketch offer + per-tenant THROTTLE increment."""
    if not _ARMED:
        return
    led = _LEDGER
    if led is not None and tenant:
        led.tenants.throttle(str(tenant))


def sample_timeline() -> None:
    """Mirror tracked tenants into the armed timeline store as
    ``tenant:<sha1[:8]>``-labeled series.  Counters/histograms are
    cumulative; the timeline's delta + generation-reset logic windows
    them exactly like ``w<N>:`` worker series, so `ia top` and the
    anomaly detector get a per-tenant view for free."""
    if not _ARMED:
        return
    led = _LEDGER
    tl = _timeline.current()
    if led is None or tl is None:
        return
    for row in led.tenants.snapshot()["tenants"]:
        label = f"tenant:{str(row['tenant'])[:8]}"
        snap = {
            "counters": {
                "serve.completed": row["requests"],
                "serve.errors": row["errors"],
                "serve.degraded": row["degraded"],
            },
            "gauges": {},
            "histograms": {"serve.latency_ms": row["latency"]},
        }
        tl.sample_snapshot(snap, worker=label)


def emit_decision(site: str, verdict: str, cause: Optional[str] = None,
                  idem: Optional[str] = None, **extra: Any) -> None:
    """The decision-attribution funnel: every control-plane verdict that
    shapes a request's fate goes through here (counter + trace record).
    Callers with a journal additionally persist a sealed ``decision``
    line (journal.record_decision / DecisionLog.record) for `ia why`."""
    _metrics.inc(f"serve.decision.{verdict}")
    rec = {"event": "serve_decision", "site": site, "verdict": verdict}
    if cause is not None:
        rec["cause"] = cause
    if idem is not None:
        rec["idem"] = idem
    if extra:
        rec.update(extra)
    _trace.emit_record(rec)
    from image_analogies_tpu.obs import archive as _archive
    _archive.record("decision", rec)


# --- rendering (`ia top --tenants` and tests share it) -----------------------

def render_tenants(doc: Dict[str, Any], title: str = "tenants") -> str:
    """Pure text rendering of a ``/tenants`` document."""
    doc = doc or {}
    lines = []
    armed = bool(doc.get("armed", False))
    header = (f"ia top — {title}  "
              f"[k={doc.get('k', 0)} tracked={doc.get('tracked', 0)} "
              f"offered={doc.get('offered', 0)} "
              f"recorded={doc.get('recorded', 0)}]")
    lines.append(header)
    if not armed and not doc.get("tenants"):
        lines.append("  (ledger disarmed — start serving with the "
                     "metering plane on)")
        return "\n".join(lines) + "\n"
    lines.append(f"  {'TENANT':<14}{'REQS':>7}{'QPS':>10}{'P95MS':>9}"
                 f"{'COST%':>7}{'DEGR':>6}{'RETRY':>6}{'THROT':>6}"
                 f"{'ERR':>5}{'±ERR':>6}")
    for row in doc.get("tenants", []):
        lines.append(
            f"  {str(row.get('tenant', '?'))[:12]:<14}"
            f"{row.get('requests', 0):>7}"
            f"{row.get('qps', 0.0):>10.2f}"
            f"{row.get('p95_ms', 0.0):>9.1f}"
            f"{100.0 * (row.get('cost_share') or 0.0):>6.1f}%"
            f"{row.get('degraded', 0):>6}"
            f"{row.get('retries', 0):>6}"
            f"{row.get('throttled', 0):>6}"
            f"{row.get('errors', 0):>5}"
            f"{row.get('count_error', 0.0):>6.0f}")
    if not doc.get("tenants"):
        lines.append("  (no tenants observed yet)")
    return "\n".join(lines) + "\n"
