"""Fixed-memory per-tenant accounting: space-saving heavy hitters.

A *tenant* in the serving plane is a style — the batcher's exemplar
digest (``sha1(a, ap)[:12]``), already the routing key every request
carries.  Per-tenant QoS (ROADMAP item 2: "one viral style must degrade
itself, not the fleet") needs per-tenant rates and costs, but the tenant
cardinality is unbounded: a pod-scale frontend can present millions of
distinct styles.  Exact per-key dicts would grow without bound, so this
module implements the space-saving sketch (Metwally, Agrawal, El Abbadi
2005): top-K frequency tracking in O(K) memory regardless of stream
cardinality, with a per-key overcount bound (``error``) that makes every
reported count an honest interval ``[count - error, count]``.

:class:`TenantTracker` pairs the sketch with bounded per-tenant
aggregates (requests, dispatch/queue ms, degrades, retries, a latency
histogram) for the currently-tracked keys only — eviction from the
sketch drops the aggregates too, so memory stays O(K) by construction
(locked by tests/test_ledger.py under a 10k-style synthetic load).

Sketches are mergeable (:func:`merge_docs`): worker-local documents
federate across the PR 11 path into one fleet-level top-K whose counts
stay within the union's error bounds.

jax-free by design (grep-locked): this is host-side bookkeeping on the
request path.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

from image_analogies_tpu.obs.metrics import Histogram


class SpaceSaving:
    """Top-K counts over an unbounded key stream in O(K) memory.

    ``offer(key)`` either increments a tracked key, fills a free slot,
    or evicts the minimum-count key and inherits its count as the new
    key's ``error`` (the classic space-saving replacement rule).  Any
    key with true frequency > N/K is guaranteed to be tracked."""

    def __init__(self, k: int):
        self.k = max(1, int(k))
        self.offered = 0
        self._counts: Dict[str, float] = {}
        self._errors: Dict[str, float] = {}

    def __len__(self) -> int:
        return len(self._counts)

    def offer(self, key: str, weight: float = 1.0) -> Optional[str]:
        """Count one occurrence of *key*; returns the evicted key when
        tracking *key* displaced another, else None."""
        self.offered += 1
        counts = self._counts
        if key in counts:
            counts[key] += weight
            return None
        if len(counts) < self.k:
            counts[key] = weight
            self._errors[key] = 0.0
            return None
        victim = min(counts, key=counts.get)
        floor = counts.pop(victim)
        self._errors.pop(victim, None)
        counts[key] = floor + weight
        self._errors[key] = floor
        return victim

    def items(self) -> List[Tuple[str, float, float]]:
        """``(key, count, error)`` sorted by count desc.  True frequency
        of each key lies in ``[count - error, count]``."""
        return sorted(
            ((k, c, self._errors.get(k, 0.0))
             for k, c in self._counts.items()),
            key=lambda t: (-t[1], t[0]))

    def merge(self, other: "SpaceSaving") -> None:
        """Fold *other* into this sketch.  Shared keys sum counts and
        errors; foreign keys enter with their remote error plus this
        sketch's current floor (they may have been evicted here), then
        the union is re-trimmed to K — the standard mergeable-summary
        construction, so the federated top-K stays an honest interval."""
        if not len(other):
            self.offered += other.offered
            return
        floor = (min(self._counts.values())
                 if len(self._counts) >= self.k else 0.0)
        for key, count, err in other.items():
            if key in self._counts:
                self._counts[key] += count
                self._errors[key] = self._errors.get(key, 0.0) + err
            else:
                self._counts[key] = floor + count
                self._errors[key] = floor + err
        self.offered += other.offered
        while len(self._counts) > self.k:
            victim = min(self._counts, key=self._counts.get)
            self._counts.pop(victim)
            self._errors.pop(victim, None)


def _blank_stats() -> Dict[str, Any]:
    return {"requests": 0, "errors": 0, "degraded": 0, "retries": 0,
            "throttled": 0, "dispatch_ms": 0.0, "queue_ms": 0.0,
            "lanes": 0, "wire_bytes": 0, "latency": Histogram()}


class TenantTracker:
    """Space-saving sketch + bounded per-tenant aggregates.

    Thread-safe; every structure is bounded by K, so arming this on the
    hot path costs a dict probe and a few float adds per request."""

    def __init__(self, k: int = 16):
        self.k = max(1, int(k))
        self._lock = threading.Lock()
        self._ss = SpaceSaving(self.k)
        self._stats: Dict[str, Dict[str, Any]] = {}

    def observe(self, tenant: str, *, latency_ms: float = 0.0,
                queue_ms: float = 0.0, dispatch_ms: float = 0.0,
                lanes: int = 1, degraded: bool = False, retries: int = 0,
                wire_bytes: int = 0, error: bool = False) -> None:
        with self._lock:
            evicted = self._ss.offer(tenant)
            if evicted is not None:
                self._stats.pop(evicted, None)
            st = self._stats.get(tenant)
            if st is None:
                st = self._stats[tenant] = _blank_stats()
            st["requests"] += 1
            st["errors"] += 1 if error else 0
            st["degraded"] += 1 if degraded else 0
            st["retries"] += retries
            st["dispatch_ms"] += dispatch_ms
            st["queue_ms"] += queue_ms
            st["lanes"] += lanes
            st["wire_bytes"] += wire_bytes
            st["latency"].observe(latency_ms)

    def throttle(self, tenant: str) -> None:
        """Record one quota refusal for *tenant*.  A throttle is NOT a
        request observation (no latency, no cost) — but it does count
        toward the sketch, so a tenant seen only through refusals still
        shows up in the top-K with its THROTTLE tally."""
        with self._lock:
            evicted = self._ss.offer(tenant)
            if evicted is not None:
                self._stats.pop(evicted, None)
            st = self._stats.get(tenant)
            if st is None:
                st = self._stats[tenant] = _blank_stats()
            st["throttled"] += 1

    def merge(self, other: "TenantTracker") -> None:
        with other._lock:
            ss_copy, stats_copy = _copy_locked(other)
        with self._lock:
            self._ss.merge(ss_copy)
            tracked = set(self._ss._counts)
            for tenant, st in stats_copy.items():
                if tenant not in tracked:
                    continue
                mine = self._stats.get(tenant)
                if mine is None:
                    self._stats[tenant] = st
                    continue
                for f in ("requests", "errors", "degraded", "retries",
                          "throttled", "lanes", "wire_bytes"):
                    mine[f] += st.get(f, 0)
                for f in ("dispatch_ms", "queue_ms"):
                    mine[f] += st[f]
                mine["latency"].merge(st["latency"])
            for tenant in list(self._stats):
                if tenant not in tracked:
                    self._stats.pop(tenant)

    def snapshot(self) -> Dict[str, Any]:
        """JSON-safe document: the ``tenants`` list of the ``/tenants``
        contract (see obs/ledger.py for the full envelope)."""
        with self._lock:
            items = self._ss.items()
            total_cost = sum(st["dispatch_ms"]
                             for st in self._stats.values()) or 0.0
            rows = []
            for tenant, count, err in items:
                st = self._stats.get(tenant) or _blank_stats()
                hist = st["latency"]
                rows.append({
                    "tenant": tenant,
                    "count": count,
                    "count_error": err,
                    "requests": st["requests"],
                    "errors": st["errors"],
                    "degraded": st["degraded"],
                    "retries": st["retries"],
                    "throttled": st.get("throttled", 0),
                    "lanes": st["lanes"],
                    "wire_bytes": st["wire_bytes"],
                    "dispatch_ms": round(st["dispatch_ms"], 3),
                    "queue_ms": round(st["queue_ms"], 3),
                    "cost_share": round(st["dispatch_ms"] / total_cost, 4)
                    if total_cost else 0.0,
                    "p50_ms": round(hist.percentile(50), 3),
                    "p95_ms": round(hist.percentile(95), 3),
                    "latency": hist.summary(),
                })
            return {"k": self.k, "tracked": len(items),
                    "offered": self._ss.offered, "tenants": rows}


def _copy_locked(t: TenantTracker):
    """Deep-enough copies of *t*'s sketch + stats (caller holds t._lock)."""
    ss = SpaceSaving(t._ss.k)
    ss.offered = t._ss.offered
    ss._counts = dict(t._ss._counts)
    ss._errors = dict(t._ss._errors)
    stats = {}
    for tenant, st in t._stats.items():
        cp = {f: st[f] for f in st if f != "latency"}
        h = Histogram()
        h.merge(st["latency"])
        cp["latency"] = h
        stats[tenant] = cp
    return ss, stats


def merge_docs(docs: List[Dict[str, Any]],
               k: Optional[int] = None) -> Dict[str, Any]:
    """Federate per-worker ``snapshot()`` documents into one fleet-level
    top-K (the obs/fleet.py path).  Counts for shared tenants are summed;
    the merged list is re-trimmed to K by count."""
    docs = [d for d in docs if d and d.get("tenants") is not None]
    if not docs:
        return {"k": k or 0, "tracked": 0, "offered": 0, "tenants": []}
    kk = int(k or max(int(d.get("k") or 1) for d in docs))
    merged: Dict[str, Dict[str, Any]] = {}
    offered = 0
    for doc in docs:
        offered += int(doc.get("offered") or 0)
        for row in doc.get("tenants", []):
            t = row.get("tenant")
            cur = merged.get(t)
            if cur is None:
                cur = merged[t] = {**row,
                                   "latency": dict(row.get("latency")
                                                   or {})}
                continue
            for f in ("count", "count_error", "requests", "errors",
                      "degraded", "retries", "throttled", "lanes",
                      "wire_bytes", "dispatch_ms", "queue_ms"):
                cur[f] = (cur.get(f) or 0) + (row.get(f) or 0)
            h = Histogram.from_summary(cur.get("latency") or {})
            h.merge(Histogram.from_summary(row.get("latency") or {}))
            cur["latency"] = h.summary()
            cur["p50_ms"] = round(h.percentile(50), 3)
            cur["p95_ms"] = round(h.percentile(95), 3)
    rows = sorted(merged.values(),
                  key=lambda r: (-(r.get("count") or 0),
                                 r.get("tenant") or ""))[:kk]
    total_cost = sum(r.get("dispatch_ms") or 0.0 for r in rows) or 0.0
    for r in rows:
        r["cost_share"] = (round((r.get("dispatch_ms") or 0.0)
                                 / total_cost, 4) if total_cost else 0.0)
        r["dispatch_ms"] = round(r.get("dispatch_ms") or 0.0, 3)
        r["queue_ms"] = round(r.get("queue_ms") or 0.0, 3)
    return {"k": kk, "tracked": len(rows), "offered": offered,
            "tenants": rows}
