"""SLO attainment + burn-rate tracking over deadline outcomes.

A :class:`SloTracker` watches the stream of *deadlined* request outcomes
(met / missed) and maintains the two standard SRE views:

- **attainment** — fraction of deadlined requests that met their
  deadline over the rolling slow window; compared against a configurable
  target (default 99%).
- **burn rate** — observed violation rate divided by the error budget
  (``1 - target``), over a fast window (paging signal: "we are burning
  budget 14x too fast") and a slow window (ticket signal).  Burn 1.0
  means exactly on budget; >1 means the budget will be exhausted early.

Everything is exported as gauges through the ordinary obs metrics
helpers (``slo.target``, ``slo.attainment``, ``slo.burn_rate.fast``,
``slo.burn_rate.slow``) plus counters ``slo.deadlined`` /
``slo.violations``, so the live /metrics exposition, ``ia report``'s
``slo`` section, and /healthz all read the same numbers.  The helpers
resolve thread-ambiently (obs/metrics.py): a fleet worker's tracker
writes into that worker's own :class:`~.metrics.ObsScope` (which also
carries the tracker as ``scope.slo``), so per-worker ``/metrics`` show
per-worker burn while the fleet roll-up takes the MAX across workers
(``slo.`` is a max-gauge family in obs/fleet.py — averaging away one
worker's page-worthy burn rate would defeat the signal).

Contract (shared with the rest of obs/): **no module-scope jax import**
(grep-locked) and near-zero cost when observability is disabled — the
gauge/counter helpers are one-branch no-ops without an active run, and
the tracker itself is plain-Python deque arithmetic.  The clock is
injectable for deterministic tests.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Optional

from image_analogies_tpu.obs import metrics as _metrics


class SloTracker:
    """Rolling-window SLO bookkeeping over deadline outcomes.

    Thread-safe: ``record`` is called from every serve worker thread.
    """

    def __init__(self,
                 target: float = 0.99,
                 fast_window_s: float = 60.0,
                 slow_window_s: float = 600.0,
                 clock: Callable[[], float] = time.monotonic) -> None:
        if not 0.0 < target < 1.0:
            raise ValueError(f"slo target must be in (0, 1), got {target}")
        if fast_window_s <= 0 or slow_window_s < fast_window_s:
            raise ValueError(
                "slo windows must satisfy 0 < fast <= slow, got "
                f"fast={fast_window_s} slow={slow_window_s}")
        self.target = float(target)
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._events: deque = deque()  # (t, met: bool), pruned vs slow window
        self._total = 0
        self._violations = 0

    # -- recording ----------------------------------------------------------

    def record(self, met: bool, now: Optional[float] = None) -> None:
        """Record one deadlined request outcome and refresh the gauges."""
        t = self._clock() if now is None else now
        with self._lock:
            self._events.append((t, bool(met)))
            self._prune(t)
            self._total += 1
            if not met:
                self._violations += 1
            fast = self._burn(t, self.fast_window_s)
            slow = self._burn(t, self.slow_window_s)
            attain = self._attainment(t)
        _metrics.inc("slo.deadlined")
        if not met:
            _metrics.inc("slo.violations")
        # (Re)set target on every record: the run scope may open after the
        # tracker is constructed, and gauges set before it are dropped.
        _metrics.set_gauge("slo.target", self.target)
        _metrics.set_gauge("slo.attainment", attain)
        _metrics.set_gauge("slo.burn_rate.fast", fast)
        _metrics.set_gauge("slo.burn_rate.slow", slow)

    # -- reading ------------------------------------------------------------

    def snapshot(self, now: Optional[float] = None) -> Dict[str, Any]:
        """Point-in-time SLO view for /healthz and tests."""
        t = self._clock() if now is None else now
        with self._lock:
            self._prune(t)
            return {
                "target": self.target,
                "deadlined": self._total,
                "violations": self._violations,
                "attainment": self._attainment(t),
                "burn_rate_fast": self._burn(t, self.fast_window_s),
                "burn_rate_slow": self._burn(t, self.slow_window_s),
                "fast_window_s": self.fast_window_s,
                "slow_window_s": self.slow_window_s,
            }

    # -- internals (lock held) ---------------------------------------------

    def _prune(self, now: float) -> None:
        horizon = now - self.slow_window_s
        ev = self._events
        while ev and ev[0][0] < horizon:
            ev.popleft()

    def _window_counts(self, now: float, window_s: float):
        horizon = now - window_s
        n = bad = 0
        for t, met in self._events:
            if t >= horizon:
                n += 1
                if not met:
                    bad += 1
        return n, bad

    def _burn(self, now: float, window_s: float) -> float:
        n, bad = self._window_counts(now, window_s)
        if n == 0:
            return 0.0
        budget = 1.0 - self.target
        return (bad / n) / budget

    def _attainment(self, now: float) -> float:
        n, bad = self._window_counts(now, self.slow_window_s)
        if n == 0:
            return 1.0
        return (n - bad) / n
