"""Mergeable relative-error quantile sketch (DDSketch-style).

The base-2 histograms in obs/metrics.py answer "which power-of-two
bucket" — fine for p50/p95 dashboards, useless for p99.9 at a million
samples (the top bucket spans a 2x range and swallows the whole tail).
This module adds the honest tail: a log-indexed sketch with a *stated*
relative-error bound that holds at any count.

Design (DDSketch, Masson et al.):

- A value ``v > 0`` lands in bucket ``i = ceil(log(v) / log(gamma))``
  with ``gamma = (1 + alpha) / (1 - alpha)``.  Reporting the bucket
  midpoint ``2 * gamma^i / (gamma + 1)`` guarantees
  ``|est - true| <= alpha * true`` for every quantile — a *relative*
  bound, so p99.99 is as honest as p50.
- Bucket counts are plain integers keyed by index, so two sketches over
  disjoint streams merge by adding counts: ``merge(a, b)`` equals the
  sketch of the concatenated stream exactly (merge-closed, associative,
  commutative) — the property fleet federation and timeline window
  deltas both lean on.
- Memory is fixed: when the bucket map exceeds ``max_bins`` the two
  *lowest* buckets collapse into one.  The error bound degrades only
  at the cheap end of the distribution; tail quantiles keep the
  guarantee (that is the end we care about).

Values ``<= 0`` (and exact zeros) go to a dedicated ``zeros`` count —
latencies are non-negative, but a defensive path must not poison the
log.  Pure stdlib; this module must stay jax-free (grep-locked in
tests/test_obs_live.py) so sidecars and offline readers import it
without dragging in an accelerator runtime.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Iterable, List, Optional

DEFAULT_ALPHA = 0.01     # 1% relative error: p99.9 of 250ms is +/- 2.5ms
DEFAULT_MAX_BINS = 1024  # ~2.5 decades of dynamic range at alpha=0.01

# Quantiles exported on /metrics and in timeline point values.
EXPORT_QUANTILES = (0.5, 0.9, 0.99, 0.999, 0.9999)


class QuantileSketch:
    """Fixed-memory mergeable quantile sketch with relative-error
    guarantee ``alpha`` (see module docstring for the math)."""

    __slots__ = ("alpha", "gamma", "_lg", "max_bins", "count", "zeros",
                 "sum", "min", "max", "bins", "collapsed")

    def __init__(self, alpha: float = DEFAULT_ALPHA,
                 max_bins: int = DEFAULT_MAX_BINS):
        if not 0.0 < alpha < 1.0:
            raise ValueError(f"alpha must be in (0, 1), got {alpha}")
        if max_bins < 2:
            raise ValueError(f"max_bins must be >= 2, got {max_bins}")
        self.alpha = alpha
        self.gamma = (1.0 + alpha) / (1.0 - alpha)
        self._lg = math.log(self.gamma)
        self.max_bins = max_bins
        self.count = 0
        self.zeros = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf
        self.bins: Dict[int, int] = {}
        self.collapsed = False

    # ------------------------------------------------------------ write
    def observe(self, v: float) -> None:
        v = float(v)
        if v != v:  # NaN: drop rather than poison min/max
            return
        self.count += 1
        self.sum += v
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v
        if v <= 0.0:
            self.zeros += 1
            return
        i = math.ceil(math.log(v) / self._lg)
        self.bins[i] = self.bins.get(i, 0) + 1
        if len(self.bins) > self.max_bins:
            self._collapse()

    def _collapse(self) -> None:
        # Fold the lowest bucket into its neighbour above: tail accuracy
        # is preserved, only the cheapest values blur together.
        keys = sorted(self.bins)
        lo, nxt = keys[0], keys[1]
        self.bins[nxt] += self.bins.pop(lo)
        self.collapsed = True

    # ------------------------------------------------------------- read
    def quantile(self, q: float) -> float:
        """Value at quantile ``q`` in [0, 1]; 0.0 for an empty sketch.
        Within ``alpha`` relative error of the exact stream quantile
        (exact-rank semantics: rank ``ceil(q * count)``)."""
        if self.count == 0:
            return 0.0
        q = min(max(q, 0.0), 1.0)
        rank = max(1, math.ceil(q * self.count))
        if rank <= self.zeros:
            # all mass at or below zero reports the observed floor
            return min(self.min, 0.0)
        cum = self.zeros
        for i in sorted(self.bins):
            cum += self.bins[i]
            if cum >= rank:
                # bucket i covers (gamma^(i-1), gamma^i]; midpoint halves
                # the worst-case multiplicative error to alpha.
                return 2.0 * self.gamma ** i / (self.gamma + 1.0)
        return self.max  # numeric slack: top bucket

    def quantiles_doc(self) -> Dict[str, float]:
        """The export view: p50/p90/p99/p999/p9999 rounded for JSON."""
        out: Dict[str, float] = {}
        for q in EXPORT_QUANTILES:
            key = "p" + format(q * 100, "g").replace(".", "")
            out[key] = round(self.quantile(q), 6)
        return out

    # ------------------------------------------------------------ merge
    def merge(self, other: "QuantileSketch") -> "QuantileSketch":
        """Fold ``other`` into self (in place; also returned).  Both
        sketches must share ``alpha`` — buckets are only additive on a
        common grid."""
        if abs(other.alpha - self.alpha) > 1e-12:
            raise ValueError(
                f"cannot merge sketches with alpha {self.alpha} and "
                f"{other.alpha}: bucket grids differ")
        self.count += other.count
        self.zeros += other.zeros
        self.sum += other.sum
        if other.count:
            self.min = min(self.min, other.min)
            self.max = max(self.max, other.max)
        for i, n in other.bins.items():
            self.bins[i] = self.bins.get(i, 0) + n
        while len(self.bins) > self.max_bins:
            self._collapse()
        self.collapsed = self.collapsed or other.collapsed
        return self

    # ------------------------------------------------- JSON round trip
    def summary(self) -> Dict[str, Any]:
        """JSON-safe snapshot: everything needed to reconstruct the
        sketch (``from_summary``) or merge it remotely.  Bucket keys are
        strings because JSON objects only key on strings."""
        empty = self.count == 0
        return {
            "alpha": self.alpha,
            "count": self.count,
            "zeros": self.zeros,
            "sum": round(self.sum, 6),
            "min": 0.0 if empty else self.min,
            "max": 0.0 if empty else self.max,
            "bins": {str(i): n for i, n in sorted(self.bins.items())},
            "collapsed": self.collapsed,
        }

    @classmethod
    def from_summary(cls, summ: Dict[str, Any],
                     max_bins: int = DEFAULT_MAX_BINS) -> "QuantileSketch":
        sk = cls(alpha=float(summ.get("alpha", DEFAULT_ALPHA)),
                 max_bins=max_bins)
        sk.count = int(summ.get("count", 0))
        sk.zeros = int(summ.get("zeros", 0))
        sk.sum = float(summ.get("sum", 0.0))
        if sk.count:
            sk.min = float(summ.get("min", 0.0))
            sk.max = float(summ.get("max", 0.0))
        sk.bins = {int(i): int(n)
                   for i, n in (summ.get("bins") or {}).items()}
        sk.collapsed = bool(summ.get("collapsed", False))
        return sk


def merge_summaries(summaries: Iterable[Dict[str, Any]]
                    ) -> Optional[Dict[str, Any]]:
    """Merge JSON summaries (the federation path: worker snapshots ->
    one fleet sketch).  Returns ``None`` for an empty iterable."""
    merged: Optional[QuantileSketch] = None
    for summ in summaries:
        sk = QuantileSketch.from_summary(summ)
        merged = sk if merged is None else merged.merge(sk)
    return None if merged is None else merged.summary()


def delta_summary(cur: Dict[str, Any], prev: Optional[Dict[str, Any]]
                  ) -> Optional[Dict[str, Any]]:
    """Windowed delta of two cumulative summaries (``cur - prev``): the
    sketch of just the samples that arrived between the two snapshots.
    Bucket additivity makes subtraction exact.  Returns ``None`` when
    ``cur`` regressed below ``prev`` (process restart -> the caller
    should treat ``cur`` as a fresh generation)."""
    if prev is None:
        return dict(cur)
    if int(cur.get("count", 0)) < int(prev.get("count", 0)):
        return None
    bins: Dict[str, int] = {}
    pbins = prev.get("bins") or {}
    for i, n in (cur.get("bins") or {}).items():
        d = int(n) - int(pbins.get(i, 0))
        if d < 0:
            return None  # collapse shifted mass: treat as regression
        if d > 0:
            bins[i] = d
    count = int(cur.get("count", 0)) - int(prev.get("count", 0))
    return {
        "alpha": cur.get("alpha", DEFAULT_ALPHA),
        "count": count,
        "zeros": int(cur.get("zeros", 0)) - int(prev.get("zeros", 0)),
        "sum": round(float(cur.get("sum", 0.0))
                     - float(prev.get("sum", 0.0)), 6),
        # min/max are not subtractable; the window inherits the
        # cumulative envelope (documented approximation).
        "min": cur.get("min", 0.0),
        "max": cur.get("max", 0.0),
        "bins": bins,
        "collapsed": bool(cur.get("collapsed", False)),
    }


def exact_quantile(values: List[float], q: float) -> float:
    """Exact-rank quantile of a finite list — the oracle the sketch is
    asserted against in tests and the seeded bench selftest."""
    if not values:
        return 0.0
    s = sorted(values)
    rank = max(1, math.ceil(min(max(q, 0.0), 1.0) * len(s)))
    return s[rank - 1]


def selftest(n: int = 100_000, seed: int = 7,
             alpha: float = DEFAULT_ALPHA) -> Dict[str, Any]:
    """Seeded lognormal tail-honesty check: sketch p99.9 vs exact, both
    whole-stream and after a two-way (worker -> fleet) merge.  Returns a
    record-style dict; ``ok`` is False if either estimate violates the
    stated relative-error bound.  Scaled down (n=1e5) this rides tier-1;
    bench runs it at 1e6."""
    import random

    rng = random.Random(seed)
    values = [rng.lognormvariate(3.0, 0.7) for _ in range(n)]
    whole = QuantileSketch(alpha=alpha)
    a, b = QuantileSketch(alpha=alpha), QuantileSketch(alpha=alpha)
    for i, v in enumerate(values):
        whole.observe(v)
        (a if i % 2 == 0 else b).observe(v)
    merged = a.merge(b)
    out: Dict[str, Any] = {"n": n, "seed": seed, "alpha": alpha,
                           "bound": alpha, "ok": True}
    for q, key in ((0.99, "p99"), (0.999, "p999"), (0.9999, "p9999")):
        exact = exact_quantile(values, q)
        est, est_m = whole.quantile(q), merged.quantile(q)
        rel = abs(est - exact) / exact
        rel_m = abs(est_m - exact) / exact
        out[key] = {"exact": round(exact, 4), "sketch": round(est, 4),
                    "rel_err": round(rel, 6),
                    "rel_err_merged": round(rel_m, 6)}
        if rel > alpha or rel_m > alpha:
            out["ok"] = False
    out["p999_rel_err"] = out["p999"]["rel_err"]
    return out
