"""Seeded fault-injection plane + resilience drills (ISSUE 5).

Hertzmann §3 makes the pyramid level the natural recovery unit, and the
engine already has level-granular retry + checkpoints — but recovery
paths that are never driven under realistic, reproducible fault
schedules are robust only by assertion.  This package is the machinery
that proves them:

- :mod:`chaos.plan`   — :class:`ChaosPlan`: a seed plus per-site fault
  rules (probability or explicit call schedule, fault kind).  Same seed
  ⇒ same fault schedule, so CI drills are reproducible.
- :mod:`chaos.inject` — the injection plane.  Engine layers register
  *sites* (``chaos.site("level.dispatch", ...)``) at their boundaries;
  each site is a named no-op when chaos is disarmed (one module-bool
  check, no metric/log/lock activity — the same zero-cost-off contract
  obs/ holds).
- :mod:`chaos.faults` — the fault kinds: transient errors, OOM-style
  ``RESOURCE_EXHAUSTED`` runtime errors, latency spikes / hangs,
  checkpoint byte corruption, worker-thread crashes.
- :mod:`chaos.runner` — ``ia chaos`` drills: run a workload under a
  plan and assert the resilience invariants (bit-identical output, no
  lost or hung request, queue drains, counters reconcile).

No module here imports jax — the plane is pure host-side control flow;
sites are data-driven (grep-locked in tests/test_chaos.py).
"""

from image_analogies_tpu.chaos.faults import ProcessDeath  # noqa: F401
from image_analogies_tpu.chaos.inject import (  # noqa: F401
    arm,
    armed,
    disarm,
    injected_total,
    plan_scope,
    plan_seed,
    site,
    snapshot,
)
from image_analogies_tpu.chaos.plan import (  # noqa: F401
    KNOWN_SITES,
    ChaosPlan,
    SiteRule,
)

FAULT_KINDS = ("transient", "oom", "latency", "corrupt", "crash",
               "process_death")
