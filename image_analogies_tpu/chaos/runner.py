"""Drill runner — ``ia chaos``: run workloads under fault plans and
assert the resilience invariants.

A drill is: clean reference run (disarmed) → chaos run (armed plan) →
invariant checks.  The invariants are the PR's acceptance criteria, not
soft goals:

- **bit-identical output** — recovery must reproduce the clean run's
  planes exactly (CPU backend; the engine is deterministic, so equality
  is the right assertion);
- **nothing lost** — every serve submit resolves to exactly one of
  ok / degraded / timeout / rejected, the queue drains, worker threads
  survive;
- **counters reconcile** — every injection is visible in the recovery
  counters it caused (retries, watchdog timeouts, quarantines, worker
  crashes).  An injection that no counter accounts for means a fault
  path silently swallowed something.

``selftest`` runs one canonical drill per fault kind plus a
schedule-determinism check (same seed ⇒ same fault schedule).
"""

from __future__ import annotations

import dataclasses
import os
import signal
import tempfile
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from image_analogies_tpu.chaos import drills, inject
from image_analogies_tpu.chaos.plan import ChaosPlan, SiteRule

# Fault kind -> canonical drill plan.  Schedules (not probabilities) so
# each selftest drill injects exactly once at a known visit.
_KIND_NOTES = {
    "transient": "level retry absorbs an injected transient",
    "oom": "RESOURCE_EXHAUSTED classifies transient via the real path",
    "latency": "watchdog converts a wedged dispatch into a retry",
    "corrupt": "checksum catches damaged checkpoint; quarantine+recompute",
    "crash": "worker crash containment requeues the batch",
    "process_death": "journal replay answers every admitted request "
                     "exactly once after kill+restart",
    "fleet_death": "router hands a dead worker's journal to its "
                   "replacement; spillover + dedupe answer exactly once",
    "fleet_death_subprocess": "REAL SIGKILL of a subprocess worker "
                              "mid-batch; replacement sweeps the foreign "
                              "stale lock, replays, and every request "
                              "answers exactly once",
    "batch_partial": "one lane faults mid-batch; the other lanes resolve "
                     "bit-identically",
    "devcache_tier": "mid-request catalog tier eviction falls through to "
                     "disk/rebuild bit-identically",
    "ann_corrupt": "sealed ANN basis damaged mid-request; quarantine + "
                   "exact fallback + rebuild, bit-identically",
    "archive_torn": "torn sealed archive segment quarantined at read, "
                    "valid prefix survives; disk-full drops counted, "
                    "never raised",
    "flash_crowd": "Zipf surge scales the fleet up under policy, a "
                   "worker dies mid-surge, the idle fleet shrinks back; "
                   "exactly-once, viral tenant throttles itself",
}

# What `selftest` (and the tier-1 parametrization) iterates: every raw
# fault kind plus the composite drills — fleet_death arms TWO sites
# (process_death at serve.journal, transient at router.forward) and
# batch_partial targets the batched engine's per-lane boundary — which
# are drill names rather than members of FAULT_KINDS.
def _drill_kinds():
    from image_analogies_tpu.chaos import FAULT_KINDS
    return tuple(FAULT_KINDS) + ("fleet_death", "fleet_death_subprocess",
                                 "batch_partial", "devcache_tier",
                                 "ann_corrupt", "archive_torn",
                                 "flash_crowd")


DRILL_KINDS = _drill_kinds()


def plan_for_kind(kind: str, seed: int = 0) -> ChaosPlan:
    if kind == "transient":
        sites = (("level.dispatch", SiteRule(kind="transient",
                                             schedule=(0,))),)
    elif kind == "oom":
        sites = (("level.dispatch", SiteRule(kind="oom", schedule=(1,))),)
    elif kind == "latency":
        # 2s hang vs the drill's 0.5s watchdog: the margin must be wide
        # in BOTH directions — the hang well above the watchdog so it
        # always trips, and the watchdog well above a legitimate tiny
        # dispatch so a loaded CI box can't trip it spuriously (a
        # spurious timeout exhausts the retry budget and flakes the
        # drill; seen at 200ms/50ms).
        sites = (("level.dispatch", SiteRule(kind="latency", schedule=(0,),
                                             latency_ms=2000.0, hang=True)),)
    elif kind == "corrupt":
        sites = (("ckpt.save", SiteRule(kind="corrupt", schedule=(0,))),)
    elif kind == "crash":
        sites = (("serve.dispatch", SiteRule(kind="crash", schedule=(0,))),)
    elif kind == "process_death":
        # Kill-restart drill geometry (one worker, max_batch == n == 4,
        # WAL-before-queue): journal visits 0..3 are the four admits,
        # then the worker alternates dispatched/done appends — 4=disp r0,
        # 5=done r0, 6=disp r1, 7=done r1.  Dying at visit 7 leaves one
        # request fully done (dedupe path), one computed but UNRECORDED
        # mid-done (the exactly-once edge: replay must re-run it to the
        # same bytes), and two admitted-only (plain replay).
        sites = (("serve.journal", SiteRule(kind="process_death",
                                            schedule=(7,))),)
    elif kind == "fleet_death":
        # Fleet drill geometry (2 workers, one shared exemplar so all 4
        # requests hash to ONE home worker; max_batch == n == 4): the
        # serve.journal schedule reuses the kill-restart placement —
        # visit 7 is "done r1" on the home worker, leaving one request
        # done, one computed-but-unrecorded, two admitted-only.  The
        # router.forward schedule fires on visit 4: visits 0..3 are the
        # four original routed submits, so the FIRST post-handoff
        # resubmit eats a transient hop fault and must spill to the
        # ring successor (which computes fresh, bit-identically).
        sites = (("serve.journal", SiteRule(kind="process_death",
                                            schedule=(7,))),
                 ("router.forward", SiteRule(kind="transient",
                                             schedule=(4,))))
    elif kind == "fleet_death_subprocess":
        # Subprocess fleet drill geometry: the death is a REAL SIGKILL
        # delivered by the drill itself (no serve.journal site — chaos
        # is armed only in the ROUTER process; the child never sees a
        # plan, which is itself the disarmed-zero-cost contract at
        # work).  router.forward visits 0..3 are the four original
        # routed submits; the post-handoff resubmits start at visit 4,
        # so the FIRST resubmit eats a transient hop fault and must
        # spill to the ring successor (which computes fresh,
        # bit-identically, in its own journal).
        sites = (("router.forward", SiteRule(kind="transient",
                                             schedule=(4,))),)
    elif kind == "devcache_tier":
        # Catalog-tier drill geometry (2 levels, warmed catalog): the
        # devcache.tier site is visited once per level's tier
        # resolution, coarsest level first — firing at BOTH visits
        # evicts each level's warmed entry from the memory tiers the
        # instant the request asks for it, so every level of the armed
        # run must recover through the sealed disk artifact (or a full
        # rebuild) and still produce the clean run's exact bytes.
        sites = (("devcache.tier", SiteRule(kind="corrupt",
                                            schedule=(0, 1))),)
    elif kind == "ann_corrupt":
        # ANN-artifact drill geometry (2 levels, sealed artifacts built
        # ahead of time): the match.prefilter site is visited once per
        # level's projection resolution — and, on a cold parity gate,
        # extra times by the gate's own probe syntheses, whose
        # probe-plane keys have no artifact (the damage helper no-ops on
        # absent paths).  p=1.0 rather than a schedule so EVERY visit of
        # the armed run corrupts regardless of how many probe visits
        # precede it: each level's artifact is damaged the instant the
        # request resolves it, so every level must quarantine, answer on
        # the exact path bit-identically, and re-seal a rebuilt basis.
        sites = (("match.prefilter", SiteRule(kind="corrupt", p=1.0)),)
    elif kind == "archive_torn":
        # Archive drill geometry (per-record segments): archive.append
        # is visited once per sealed record; the corrupt directive at
        # visit 1 tears record 1's segment AFTER a successful-looking
        # write — the torn-tail shape a power cut leaves on disk.  The
        # drill itself arms a second, raising rule at the same site for
        # the disk-full leg (one site carries one rule per plan).
        sites = (("archive.append", SiteRule(kind="corrupt",
                                             schedule=(1,))),)
    elif kind == "flash_crowd":
        # Elastic-fleet drill geometry: the surge, the mid-surge worker
        # kill, and the cool-down retire are all delivered by the drill
        # itself (loadgen arrival schedule + handle.kill + the control
        # plane's own policy).  The one armed site is a transient at a
        # level dispatch mid-surge — absorbed by the engine's level
        # retry — proving local fault recovery still holds while the
        # fleet is actively scaling around it.
        sites = (("level.dispatch", SiteRule(kind="transient",
                                             schedule=(2,))),)
    elif kind == "batch_partial":
        # Batched-engine drill geometry (k=3 lanes, 2 levels): the
        # engine.batch site is visited once per (level, lane), coarsest
        # level first — visits 0..2 are the coarse level's lanes 0..2.
        # Firing at visit 1 kills lane 1 at the COARSEST level, so the
        # drill proves a first-level fault stays contained for the whole
        # remaining coarse-to-fine run, not just the last launch.
        sites = (("engine.batch", SiteRule(kind="transient",
                                           schedule=(1,))),)
    else:
        raise ValueError(f"unknown fault kind {kind!r}")
    return ChaosPlan(seed=seed, sites=sites, name=f"selftest-{kind}")


def _wants_serve(plan: ChaosPlan) -> bool:
    return any(name.startswith("serve.") for name, _ in plan.sites)


def _counters(ctx) -> Dict[str, float]:
    return dict(ctx.registry.snapshot()["counters"]) if ctx else {}


def _reconcile(plan: ChaosPlan, counters: Dict[str, float]) -> List[str]:
    """Per-kind accounting: every injection must be matched by the
    recovery counter it should have caused.  Returns failure strings."""
    problems = []

    def want(name: str, expected: float) -> None:
        got = counters.get(name, 0)
        if got != expected:
            problems.append(f"{name}={got} != expected {expected}")

    by_kind: Dict[str, float] = {}
    for key, val in counters.items():
        if key.startswith("chaos.injected."):
            by_kind[key.split(".", 2)[2]] = val
    injected = counters.get("chaos.injected", 0)
    if sum(by_kind.values()) != injected:
        problems.append("per-kind chaos counters do not sum to total")
    # Expectations come from the PLAN (per-site injection counters x each
    # site's rule), because the same kind recovers differently by
    # placement: transient/oom under the level retry wrapper retry; a
    # hang surfaces as a watchdog timeout first, THEN retries; a plain
    # (non-hang) latency spike recovers by itself; corruption surfaces at
    # load as a quarantine; a crash as a contained worker crash.  A
    # raising kind at a serve batch boundary is contained as a crash
    # regardless of its class — the containment layer can't tell.
    retries = watchdogs = quarantines = crashes = deaths = 0.0
    hop_faults = lane_faults = tier_evictions = ann_faults = 0.0
    archive_faults = 0.0
    for name, rule in plan.sites:
        n = counters.get(f"chaos.site.{name}", 0)
        if not n:
            continue
        if name == "serve.admit":
            continue  # surfaces synchronously to the client; no recovery
        if name == "engine.batch":
            # a faulted lane is ISOLATED, not retried — the batch engine
            # marks the member failed and finishes the other lanes; the
            # only matching evidence is its lane-fault counter
            lane_faults += n
        elif name == "devcache.tier":
            # the "corrupt" directive here is applied as a mid-request
            # memory-tier eviction (NOT file damage): recovery is the
            # tier fall-through, evidenced by the catalog's eviction
            # counter — must be matched before the generic corrupt →
            # ckpt.quarantined accounting below
            tier_evictions += n
        elif name == "archive.append":
            # the corrupt directive tears the sealed segment AFTER a
            # successful-looking write (recovery is the READER's
            # quarantine) and raising kinds model disk-full (recovery
            # is the counted drop); both are the archive's own
            # accounting, checked jointly below — must be matched
            # before the generic corrupt → ckpt.quarantined branch
            archive_faults += n
        elif name == "match.prefilter":
            # the corrupt directive here damages the sealed ANN artifact
            # — but only when one exists at the resolved key (gate-probe
            # visits resolve probe-plane keys with no artifact, where the
            # damage helper no-ops), so the evidence is the quarantine →
            # exact-fallback → rebuild chain checked loosely below, not
            # an equality against the visit count
            ann_faults += n
        elif rule.kind == "process_death":
            # not contained: the worker thread dies; the only matching
            # evidence is the death counter (recovery is the journal's)
            deaths += n
        elif name == "router.forward" and rule.kind in (
                "transient", "oom", "crash"):
            # a raising fault on the hop is absorbed by the router's
            # spillover walk, not a level retry
            hop_faults += n
        elif name in ("serve.dispatch",) and rule.kind in (
                "transient", "oom", "crash"):
            crashes += n
        elif rule.kind in ("transient", "oom"):
            retries += n
        elif rule.kind == "latency" and rule.hang:
            watchdogs += n
            retries += n
        elif rule.kind == "corrupt":
            quarantines += n
        elif rule.kind == "crash":
            crashes += n
    if retries:
        want("level_retry", retries)
    if watchdogs:
        want("watchdog.timeouts", watchdogs)
    if quarantines:
        want("ckpt.quarantined", quarantines)
    if crashes:
        want("serve.worker_crashes", crashes)
    if deaths:
        want("serve.process_deaths", deaths)
    if hop_faults:
        want("router.hop_faults", hop_faults)
    if lane_faults:
        want("batch.lane_faults", lane_faults)
    if tier_evictions:
        want("catalog.chaos_evictions", tier_evictions)
    if archive_faults:
        accounted = (counters.get("obs.archive.quarantined", 0)
                     + counters.get("obs.archive.append_errors", 0))
        if accounted != archive_faults:
            problems.append(
                f"archive.append injected {archive_faults} faults but "
                f"quarantines+drops account for {accounted}")
    if ann_faults:
        quarantined = counters.get("ann.quarantined", 0)
        if not quarantined:
            problems.append(
                "match.prefilter fired but nothing was quarantined")
        if counters.get("ann.fallback_exact", 0) < quarantined:
            problems.append(
                f"{quarantined} ANN quarantines but only "
                f"{counters.get('ann.fallback_exact', 0)} exact fallbacks")
        if counters.get("ann.artifacts_rebuilt", 0) < quarantined:
            problems.append(
                f"{quarantined} ANN quarantines but only "
                f"{counters.get('ann.artifacts_rebuilt', 0)} rebuilds")
    return problems


def drill_image(plan: ChaosPlan, *, seed: int = 7,
                size=(20, 20), workdir: Optional[str] = None
                ) -> Dict[str, Any]:
    """Single-image drill: clean run, chaos run (and for checkpoint
    corruption a third resume run hitting the quarantine path), then the
    invariants."""
    from image_analogies_tpu.obs import trace as obs_trace

    a, ap, b = drills.make_inputs(size, seed)
    corrupting = any(r.kind == "corrupt" for _, r in plan.sites)
    hanging = any(r.kind == "latency" and r.hang for _, r in plan.sites)

    clean = drills.run_image(a, ap, b, drills.image_params(retries=0))

    with tempfile.TemporaryDirectory(dir=workdir) as tmp:
        params = drills.image_params(
            retries=3,
            checkpoint_dir=os.path.join(tmp, "ckpt"),
            # a hang only recovers when something bounds the wait; give
            # the watchdog a deadline well under the injected latency
            # but far above an honest dispatch (see plan_for_kind)
            dispatch_timeout_s=0.5 if hanging else 0.0)
        with obs_trace.run_scope(params) as ctx:
            with inject.plan_scope(plan):
                chaos_bp = drills.run_image(a, ap, b, params)
                snap = inject.snapshot()
            resumed_bp = None
            if corrupting:
                # resume run (disarmed): hits the damaged file, must
                # quarantine + recompute to the identical result
                resumed_bp = drills.run_image(
                    a, ap, b, params.replace(resume_from_level=0))
            counters = _counters(ctx)

    identical = bool(np.array_equal(clean, chaos_bp))
    if resumed_bp is not None:
        identical = identical and bool(np.array_equal(clean, resumed_bp))
    problems = [] if identical else ["output differs from clean run"]
    problems += _reconcile(plan, counters)
    injected = sum(st["injected"] for st in snap.values())
    if injected == 0:
        problems.append("plan injected nothing (dead drill)")
    return {
        "workload": "image",
        "plan": plan.to_dict(),
        "injected": injected,
        "sites": snap,
        "counters": {k: v for k, v in counters.items()
                     if k.startswith(("chaos.", "level_retry", "retry.",
                                      "watchdog.", "ckpt."))},
        "identical": identical,
        "ok": not problems,
        "problems": problems,
    }


def drill_catalog_tier(plan: ChaosPlan, *, seed: int = 7,
                       size=(20, 20), workdir: Optional[str] = None
                       ) -> Dict[str, Any]:
    """Catalog-tier eviction drill: clean run (no catalog) → warm run
    (disarmed, populates every tier + the sealed disk artifacts) →
    armed run whose ``devcache.tier`` directives evict the warmed
    entries MID-REQUEST.  Invariants: the armed run falls through the
    remaining tiers (disk hit or full rebuild) and produces the clean
    run's exact bytes, and every injection reconciles against
    ``catalog.chaos_evictions``."""
    from image_analogies_tpu.catalog import tiers as catalog_tiers
    from image_analogies_tpu.obs import trace as obs_trace

    a, ap, b = drills.make_inputs(size, seed)
    clean = drills.run_image(a, ap, b, drills.image_params(retries=0))

    catalog_tiers.clear()
    try:
        with tempfile.TemporaryDirectory(dir=workdir) as tmp:
            params = drills.catalog_params(os.path.join(tmp, "catalog"))
            with obs_trace.run_scope(params) as ctx:
                warm_bp = drills.run_image(a, ap, b, params)
                with inject.plan_scope(plan):
                    chaos_bp = drills.run_image(a, ap, b, params)
                    snap = inject.snapshot()
                counters = _counters(ctx)
    finally:
        catalog_tiers.clear()
        catalog_tiers.configure(None)

    identical = bool(np.array_equal(clean, warm_bp)
                     and np.array_equal(clean, chaos_bp))
    problems = [] if identical else ["output differs from clean run"]
    problems += _reconcile(plan, counters)
    if not counters.get("catalog.builds", 0):
        problems.append("warm run recorded no catalog builds")
    evicted = counters.get("catalog.chaos_evictions", 0)
    recovered = (counters.get("catalog.disk.hits", 0)
                 + counters.get("catalog.builds", 0))
    if evicted and recovered < evicted:
        problems.append(
            f"{evicted} evictions but only {recovered} disk-hit/rebuild "
            "recoveries (a hit survived the eviction it should not have)")
    injected = sum(st["injected"] for st in snap.values())
    if injected == 0:
        problems.append("plan injected nothing (dead drill)")
    return {
        "workload": "catalog_tier",
        "plan": plan.to_dict(),
        "injected": injected,
        "sites": snap,
        "counters": {k: v for k, v in counters.items()
                     if k.startswith(("chaos.", "catalog."))},
        "identical": identical,
        "ok": not problems,
        "problems": problems,
    }


def drill_ann_corrupt(plan: ChaosPlan, *, seed: int = 7,
                      size=(20, 20), workdir: Optional[str] = None
                      ) -> Dict[str, Any]:
    """ANN-artifact corruption drill: exact reference run → AOT catalog
    build (seals the per-level PCA artifacts) → warm two-stage run
    (disarmed; pays the parity-gate probe and proves the artifacts load)
    → armed run whose ``match.prefilter`` directives flip a byte of each
    level's sealed artifact the instant the request resolves it.
    Invariants: every damaged artifact quarantines (``.corrupt``), every
    quarantined level answers on the exact path — the armed run's output
    is bit-identical to the exact reference — and each quarantine is
    matched by a rebuilt, re-sealed artifact."""
    from image_analogies_tpu.catalog import build as catalog_build
    from image_analogies_tpu.catalog import tiers as catalog_tiers
    from image_analogies_tpu.obs import trace as obs_trace

    a, ap, b = drills.make_inputs(size, seed)
    catalog_tiers.clear()
    try:
        with tempfile.TemporaryDirectory(dir=workdir) as tmp:
            root = os.path.join(tmp, "catalog")
            params = drills.ann_params(root)
            exact_bp = drills.run_image(
                a, ap, b, params.replace(ann_prefilter=False))
            catalog_build.build_style(a, ap, params, root_dir=root,
                                      target=b)
            with obs_trace.run_scope(params) as ctx:
                # warm two-stage run: output is the gate-audited
                # approximate path, so only its counters are asserted
                drills.run_image(a, ap, b, params)
                with inject.plan_scope(plan):
                    chaos_bp = drills.run_image(a, ap, b, params)
                    snap = inject.snapshot()
                counters = _counters(ctx)
    finally:
        catalog_tiers.clear()
        catalog_tiers.configure(None)

    identical = bool(np.array_equal(exact_bp, chaos_bp))
    problems = [] if identical else ["output differs from exact run"]
    problems += _reconcile(plan, counters)
    if not counters.get("ann.artifact_hits", 0):
        problems.append("warm run never loaded a sealed ANN artifact")
    if not counters.get("ann.quarantined", 0):
        problems.append("armed run quarantined no damaged artifact")
    injected = sum(st["injected"] for st in snap.values())
    if injected == 0:
        problems.append("plan injected nothing (dead drill)")
    return {
        "workload": "ann_corrupt",
        "plan": plan.to_dict(),
        "injected": injected,
        "sites": snap,
        "counters": {k: v for k, v in counters.items()
                     if k.startswith(("chaos.", "ann."))},
        "identical": identical,
        "ok": not problems,
        "problems": problems,
    }


def drill_serve(plan: ChaosPlan, *, n: int = 6, seed: int = 7
                ) -> Dict[str, Any]:
    """Serve drill: burst-submit n requests under the plan; every future
    must resolve to exactly one known outcome, outputs must match direct
    engine runs, the queue must drain, and counters must reconcile."""
    from image_analogies_tpu.obs import trace as obs_trace
    from image_analogies_tpu.serve.server import Server
    from image_analogies_tpu.serve.types import DeadlineExceeded, Rejected

    cfg = drills.serve_config()
    load = drills.make_serve_load(n, seed=seed)
    baseline = {item["index"]: drills.run_image(
        item["a"], item["ap"], item["b"], cfg.params)
        for item in load}

    outcomes: Dict[int, str] = {}
    responses: Dict[int, Any] = {}
    unknown_errors: Dict[int, str] = {}
    with obs_trace.run_scope(cfg.params) as ctx:
        with inject.plan_scope(plan):
            with Server(cfg) as srv:
                futures = {}
                for item in load:
                    try:
                        futures[item["index"]] = srv.submit(
                            item["a"], item["ap"], item["b"])
                    except Exception as exc:  # noqa: BLE001 - admission faults
                        # injected admission faults surface synchronously,
                        # like any admission refusal
                        outcomes[item["index"]] = (
                            "rejected" if isinstance(exc, Rejected)
                            else "submit_fault")
                for idx, fut in futures.items():
                    try:
                        responses[idx] = fut.result(timeout=120)
                        outcomes[idx] = responses[idx].status
                    except Rejected:
                        outcomes[idx] = "rejected"
                    except DeadlineExceeded:
                        outcomes[idx] = "timeout"
                    except BaseException as exc:  # noqa: BLE001 - audited
                        outcomes[idx] = "error"
                        unknown_errors[idx] = repr(exc)
                drained = srv.queue_depth == 0
            snap = inject.snapshot()
        counters = _counters(ctx)

    problems = []
    if len(outcomes) != n:
        problems.append(f"{n - len(outcomes)} requests never resolved")
    if unknown_errors:
        problems.append(f"unexpected errors: {unknown_errors}")
    if not drained:
        problems.append("queue did not drain")
    identical = all(
        np.array_equal(responses[i].bp, baseline[i])
        for i in responses if responses[i].degraded is None)
    if not identical:
        problems.append("served output differs from direct engine run")
    problems += _reconcile(plan, counters)
    injected = sum(st["injected"] for st in snap.values())
    if injected == 0:
        problems.append("plan injected nothing (dead drill)")
    tally: Dict[str, int] = {}
    for o in outcomes.values():
        tally[o] = tally.get(o, 0) + 1
    return {
        "workload": "serve",
        "plan": plan.to_dict(),
        "injected": injected,
        "sites": snap,
        "outcomes": tally,
        "counters": {k: v for k, v in counters.items()
                     if k.startswith(("chaos.", "serve.", "level_retry",
                                      "retry.", "watchdog."))},
        "identical": identical,
        "ok": not problems,
        "problems": problems,
    }


def drill_kill_restart(plan: ChaosPlan, *, n: int = 4, seed: int = 7
                       ) -> Dict[str, Any]:
    """Process-death drill: a journaled single-worker server takes a full
    batch; the injected :class:`~chaos.faults.ProcessDeath` kills the
    worker mid-journal-append; the server is torn down NON-gracefully
    (queued and in-flight clients dropped, exactly as a real death drops
    them); a second server on the same journal replays.  Invariants:
    every admitted request is answered exactly once — pre-death responses
    and post-restart resubmissions alike bit-identical to direct engine
    runs — and the journal/replay counters reconcile."""
    from image_analogies_tpu.obs import trace as obs_trace
    from image_analogies_tpu.serve.server import Server

    with tempfile.TemporaryDirectory() as tmp:
        jdir = os.path.join(tmp, "journal")
        # Wide batch window in incarnation 1: the worker must coalesce
        # ALL n submits into one batch for the plan's visit schedule to
        # mean what the geometry comment in plan_for_kind says it means.
        cfg = drills.serve_config(workers=1, max_batch=n,
                                  batch_window_ms=2000.0, journal_dir=jdir)
        # Restart pops a < max_batch replay batch; a small window keeps
        # the drill from idling out the full coalescing wait.
        cfg2 = drills.serve_config(workers=1, max_batch=n,
                                   batch_window_ms=50.0, journal_dir=jdir)
        load = drills.make_serve_load(n, seed=seed)
        baseline = {item["index"]: drills.run_image(
            item["a"], item["ap"], item["b"], cfg.params)
            for item in load}
        ikey = "kill-restart-{}".format

        problems: List[str] = []
        with obs_trace.run_scope(cfg.params) as ctx:
            # -- incarnation 1: full batch, death mid-append ------------
            inject.arm(plan)
            try:
                srv = Server(cfg).start()
                futures = {}
                for item in load:
                    futures[item["index"]] = srv.submit(
                        item["a"], item["ap"], item["b"],
                        idempotency_key=ikey(item["index"]))
                end = time.monotonic() + 60.0
                while (inject.injected_total() < 1
                       and time.monotonic() < end):
                    time.sleep(0.01)
                srv.kill()
                snap = inject.snapshot()
            finally:
                inject.disarm()
            pre_done = {i: f.result(timeout=0) for i, f in futures.items()
                        if f.done() and f.exception() is None}
            unresolved = [i for i, f in futures.items() if not f.done()]
            if not pre_done:
                problems.append("no request finished before the death")
            if not unresolved:
                problems.append("death left nothing unresolved (dead drill)")

            # -- incarnation 2: same journal, disarmed replay -----------
            srv2 = Server(cfg2).start()
            stats = dict(srv2.recovery_stats or {})
            recovered = srv2.wait_recovered(timeout=120)
            # resubmit EVERY original request under its original key:
            # each must dedupe against the journal's recorded response
            replies = {}
            for item in load:
                replies[item["index"]] = srv2.submit(
                    item["a"], item["ap"], item["b"],
                    idempotency_key=ikey(item["index"])).result(timeout=120)
            srv2.shutdown()
            counters = _counters(ctx)

        bad = {k: v for k, v in recovered.items() if v != "ok"}
        if bad:
            problems.append(f"replayed work did not finish ok: {bad}")
        if stats.get("replayed", 0) != len(unresolved):
            problems.append(
                f"replayed {stats.get('replayed', 0)} entries "
                f"!= {len(unresolved)} unresolved at death")
        identical = all(
            np.array_equal(replies[i].bp, baseline[i]) for i in replies)
        identical = identical and all(
            np.array_equal(resp.bp, baseline[i])
            for i, resp in pre_done.items())
        if not identical:
            problems.append("recovered output differs from clean run")
        # exactly-once ledger: one done record per request, every
        # resubmission answered from it, no request re-admitted
        for name, expect in (("serve.journal.done", n),
                             ("serve.journal.deduped", n),
                             ("serve.journal.admitted", n)):
            got = counters.get(name, 0)
            if got != expect:
                problems.append(f"{name}={got} != expected {expect}")
        problems += _reconcile(plan, counters)
        injected = sum(st["injected"] for st in snap.values())
        if injected == 0:
            problems.append("plan injected nothing (dead drill)")
        return {
            "workload": "kill_restart",
            "plan": plan.to_dict(),
            "injected": injected,
            "sites": snap,
            "recovery": stats,
            "outcomes": {
                "pre_death_ok": len(pre_done),
                "replayed": stats.get("replayed", 0),
                "deduped": int(counters.get("serve.journal.deduped", 0)),
            },
            "counters": {k: v for k, v in counters.items()
                         if k.startswith(("chaos.", "serve."))},
            "identical": identical,
            "ok": not problems,
            "problems": problems,
        }


def drill_fleet(plan: ChaosPlan, *, n: int = 4, seed: int = 7
                ) -> Dict[str, Any]:
    """Fleet kill-restart drill: 2 routed workers, one shared exemplar so
    all n requests hash to ONE home worker.  The injected
    :class:`~chaos.faults.ProcessDeath` kills the home worker mid-batch;
    the fleet health loop declares it dead, hands its journal directory
    to a replacement (same wid, same ring slot), whose ``recover()``
    replays the incomplete entries while the router re-chains the
    stranded in-flight futures by idempotency key.  Every original
    request must still be answered exactly once, bit-identical to direct
    engine runs.  Then every request is RESUBMITTED under its original
    key: the first resubmit eats a scheduled transient at the new
    ``router.forward`` site and must spill to the ring successor (which
    computes fresh, bit-identically, in its own journal); the rest
    dedupe instantly against the home journal's done records."""
    from image_analogies_tpu.obs import trace as obs_trace
    from image_analogies_tpu.serve.fleet import Fleet
    from image_analogies_tpu.serve.types import FleetConfig

    with tempfile.TemporaryDirectory() as tmp:
        # Wide batch window: the home worker must coalesce all n submits
        # into one batch for the serve.journal visit schedule to mean
        # what plan_for_kind's geometry comment says (same reasoning as
        # drill_kill_restart; one template serves both incarnations, so
        # the replacement's replay batch idles out one window).
        cfg = drills.serve_config(workers=1, max_batch=n,
                                  batch_window_ms=1000.0)
        fcfg = FleetConfig(serve=cfg, size=2, vnodes=16,
                           journal_root=os.path.join(tmp, "journals"),
                           health_interval_s=0.05, death_checks=2,
                           backoff_s=0.01, backoff_cap_s=0.05)
        load = drills.make_serve_load(n, seed=seed)
        baseline = {item["index"]: drills.run_image(
            item["a"], item["ap"], item["b"], cfg.params)
            for item in load}
        ikey = "fleet-kill-{}".format

        problems: List[str] = []
        with obs_trace.run_scope(cfg.params) as ctx:
            inject.arm(plan)
            try:
                with Fleet(fcfg) as fl:
                    futures = {}
                    for item in load:
                        futures[item["index"]] = fl.submit(
                            item["a"], item["ap"], item["b"],
                            idempotency_key=ikey(item["index"]))
                    # the scheduled death fires mid-batch on the home
                    # worker; the health loop replaces it
                    end = time.monotonic() + 60.0
                    while not fl.handoffs and time.monotonic() < end:
                        time.sleep(0.01)
                    handoffs = list(fl.handoffs)
                    # every ORIGINAL future must still answer (rechained
                    # onto the replacement's recovery futures)
                    originals = {i: f.result(timeout=120)
                                 for i, f in futures.items()}
                    # resubmit EVERY request under its original key: the
                    # router.forward schedule makes the first one spill
                    # to the ring successor; the rest dedupe
                    replies = {}
                    for item in load:
                        replies[item["index"]] = fl.submit(
                            item["a"], item["ap"], item["b"],
                            idempotency_key=ikey(item["index"])
                        ).result(timeout=120)
                    fleet_health = fl.health()
                    snap = inject.snapshot()
            finally:
                inject.disarm()
            counters = _counters(ctx)

        if not handoffs:
            problems.append("no journal handoff happened (dead drill)")
        else:
            rec = handoffs[0].get("recovered", {})
            if rec.get("entries") != n:
                problems.append(
                    f"handoff recovered {rec.get('entries')} entries "
                    f"!= {n} admitted")
            if rec.get("poisoned"):
                problems.append(
                    f"handoff poisoned {rec.get('poisoned')} entries")
        # flight recorder: the ProcessDeath must have sealed a blackbox
        # dump into the DEAD worker's journal dir (the one the handoff
        # names), its seal must verify, and the render must show the
        # death the drill injected.
        blackbox: Dict[str, Any] = {}
        if handoffs:
            from image_analogies_tpu.obs import recorder as obs_recorder

            dead_dir = os.path.join(fcfg.journal_root,
                                    handoffs[0]["worker"])
            dumps = obs_recorder.list_dumps(dead_dir)
            if not dumps:
                problems.append("no flight-recorder dump in dead "
                                "worker's journal dir")
            else:
                try:
                    doc = obs_recorder.load_dump(dumps[-1])
                except ValueError as exc:
                    problems.append(f"blackbox seal broken: {exc}")
                else:
                    text = obs_recorder.render_dump(doc)
                    if "process_death" not in text:
                        problems.append("blackbox render does not show "
                                        "the process death")
                    if not doc.get("records"):
                        problems.append("blackbox dump has no records")
                    blackbox = {
                        "file": os.path.basename(dumps[-1]),
                        "reason": doc.get("reason"),
                        "scope": doc.get("scope"),
                        "records": len(doc.get("records") or []),
                    }
        identical = all(
            np.array_equal(originals[i].bp, baseline[i])
            for i in originals)
        identical = identical and all(
            np.array_equal(replies[i].bp, baseline[i]) for i in replies)
        if not identical:
            problems.append("fleet output differs from clean run")
        # exactly-once ledger across the handoff: the home journal holds
        # one done per original request; the spilled resubmit adds one
        # admit+done in the SUCCESSOR's journal; the other resubmits
        # dedupe against the home journal's records.
        for name, expect in (("serve.journal.admitted", n + 1),
                             ("serve.journal.done", n + 1),
                             ("serve.journal.deduped", n - 1),
                             ("router.deaths", 1),
                             ("router.handoffs", 1),
                             ("router.spills", 1)):
            got = counters.get(name, 0)
            if got != expect:
                problems.append(f"{name}={got} != expected {expect}")
        problems += _reconcile(plan, counters)
        injected = sum(st["injected"] for st in snap.values())
        if injected < 2:
            problems.append(
                f"expected both sites to inject, got {injected}")
        return {
            "workload": "fleet",
            "plan": plan.to_dict(),
            "injected": injected,
            "sites": snap,
            "handoffs": handoffs,
            "blackbox": blackbox,
            "fleet": {"pending": fleet_health.get("pending"),
                      "ring": fleet_health.get("ring")},
            "outcomes": {
                "answered": len(originals),
                "resubmitted": len(replies),
                "rechained": int(counters.get("router.rechained", 0)),
                "deduped": int(counters.get("serve.journal.deduped", 0)),
            },
            "counters": {k: v for k, v in counters.items()
                         if k.startswith(("chaos.", "serve.", "router."))},
            "identical": identical,
            "ok": not problems,
            "problems": problems,
        }


def drill_fleet_subprocess(plan: ChaosPlan, *, n: int = 4, seed: int = 7
                           ) -> Dict[str, Any]:
    """Fleet death drill against REAL subprocess workers.

    Same exactly-once bar as :func:`drill_fleet`, but the death is a
    real ``SIGKILL`` delivered to a child pid — no fault plane inside
    the worker, no python-level unwinding, the kernel just takes it.
    What that buys over the in-process drill:

    - the journal's advisory lock holds a FOREIGN pid, so the
      replacement exercises the true stale-lock sweep (dead-pid probe,
      ``serve.journal.stale_lock_swept``) instead of the same-process
      shortcut;
    - the router's in-flight hops die as socket disconnects
      (``router.hop_disconnects``), leaving futures unresolved for the
      handoff to re-answer — the wire-level version of the stranded
      future the in-process drill stages;
    - recovery replays in a fresh interpreter: bit-identity across the
      handoff is proven across a process boundary, not a scope swap.

    Flow: wave 1 routes one request to the home worker and waits for
    its ``done`` record (so the replacement must dedupe against a prior
    incarnation's segment).  Wave 2 routes n-1 more, waits until the
    home child's journal shows them admitted (mid-coalesce, wide batch
    window), then SIGKILLs the home pid.  The health loop declares
    death, re-spawns generation 1 on the SAME journal dir; recovery
    sweeps the foreign lock, advances the segment, replays the
    incomplete entries, and the router's re-forwards join-replay onto
    them.  Then every request is resubmitted under its original key:
    the first eats the scheduled ``router.forward`` transient and
    spills to the ring successor (fresh compute, own journal); the
    rest dedupe against the replacement's journal.  Ground truth is
    read twice: live via /healthz (lock pid, segment, sweep counter)
    and offline via ``RequestJournal.inspect()`` after shutdown.

    One honest difference from the in-process drill: SIGKILL runs no
    death hook, so there is NO flight-recorder blackbox to assert — the
    corpse's journal directory is the only evidence, which is exactly
    the point."""
    from image_analogies_tpu.obs import trace as obs_trace
    from image_analogies_tpu.serve import journal as serve_journal
    from image_analogies_tpu.serve.fleet import Fleet
    from image_analogies_tpu.serve.types import FleetConfig

    with tempfile.TemporaryDirectory() as tmp:
        # Wide batch window: wave 2 must still be coalescing when the
        # SIGKILL lands, so its entries are admitted-not-done and the
        # replacement has real replay work.
        cfg = drills.serve_config(workers=1, max_batch=n,
                                  batch_window_ms=2000.0)
        fcfg = FleetConfig(serve=cfg, size=2, vnodes=16,
                           journal_root=os.path.join(tmp, "journals"),
                           transport="subprocess",
                           health_interval_s=0.1, death_checks=2,
                           backoff_s=0.01, backoff_cap_s=0.05)
        load = drills.make_serve_load(n, seed=seed)
        baseline = {item["index"]: drills.run_image(
            item["a"], item["ap"], item["b"], cfg.params)
            for item in load}
        ikey = "fleet-kill-{}".format

        problems: List[str] = []
        with obs_trace.run_scope(cfg.params) as ctx:
            # Armed in the ROUTER process only: spawned children never
            # see the plan (nothing propagates a ChaosPlan over the
            # spawn handshake) — the disarmed-zero-cost contract holds
            # in every worker while the parent schedules hop faults.
            inject.arm(plan)
            try:
                with Fleet(fcfg) as fl:
                    futures = {}
                    # wave 1: one request, answered and journaled done
                    # before the death (forward visit 0)
                    item0 = load[0]
                    futures[item0["index"]] = fl.submit(
                        item0["a"], item0["ap"], item0["b"],
                        idempotency_key=ikey(item0["index"]))
                    futures[item0["index"]].result(timeout=180)

                    def _journal(wid):
                        w = fl.health()["workers"].get(wid, {})
                        return w.get("journal") or {}

                    home = next(
                        (wid for wid in fl.workers
                         if _journal(wid).get("done", 0) >= 1), None)
                    if home is None:
                        raise RuntimeError(
                            "no worker journaled wave-1 done")
                    victim_pid = fl.workers[home].pid

                    # wave 2: n-1 requests coalescing in the home
                    # child's batch window (forward visits 1..n-1)
                    for item in load[1:]:
                        futures[item["index"]] = fl.submit(
                            item["a"], item["ap"], item["b"],
                            idempotency_key=ikey(item["index"]))
                    end = time.monotonic() + 60.0
                    while (_journal(home).get("admitted", 0) < n - 1
                           and time.monotonic() < end):
                        time.sleep(0.02)
                    if _journal(home).get("admitted", 0) < n - 1:
                        raise RuntimeError(
                            "wave-2 requests never admitted")

                    # the real death: kernel-level, mid-coalesce
                    os.kill(victim_pid, signal.SIGKILL)

                    end = time.monotonic() + 120.0
                    while not fl.handoffs and time.monotonic() < end:
                        time.sleep(0.02)
                    handoffs = list(fl.handoffs)
                    # every ORIGINAL future must still answer — the
                    # handoff re-forwards join-replay onto the
                    # replacement's recovery
                    originals = {i: f.result(timeout=180)
                                 for i, f in futures.items()}
                    # resubmit EVERY request under its original key:
                    # visit n faults -> the first resubmit spills to
                    # the ring successor; the rest dedupe
                    replies = {}
                    for item in load:
                        replies[item["index"]] = fl.submit(
                            item["a"], item["ap"], item["b"],
                            idempotency_key=ikey(item["index"])
                        ).result(timeout=180)
                    fleet_health = fl.health()
                    replacement = fleet_health["workers"].get(home, {})
                    snap = inject.snapshot()
            finally:
                inject.disarm()
            counters = _counters(ctx)

        if not handoffs:
            problems.append("no journal handoff happened (dead drill)")
        else:
            rec = handoffs[0].get("recovered", {})
            if handoffs[0].get("worker") != home:
                problems.append("handoff names wrong worker")
            if rec.get("entries") != n:
                problems.append(
                    f"handoff recovered {rec.get('entries')} entries "
                    f"!= {n} admitted")
            if rec.get("poisoned"):
                problems.append(
                    f"handoff poisoned {rec.get('poisoned')} entries")
        # The replacement is a NEW process on the OLD journal dir: its
        # lock must hold its own (fresh) pid, the dead child's lock
        # must have been swept as a foreign stale pid, and the segment
        # must have advanced past the corpse's.
        rep_pid = replacement.get("pid")
        rep_journal = replacement.get("journal") or {}
        if replacement.get("generation") != 1:
            problems.append(
                f"replacement generation {replacement.get('generation')}"
                " != 1")
        if rep_pid in (None, victim_pid, os.getpid()):
            problems.append(
                f"replacement pid {rep_pid} is not a fresh child "
                f"(victim {victim_pid}, parent {os.getpid()})")
        if rep_journal.get("lock_pid") != rep_pid:
            problems.append(
                f"journal lock_pid {rep_journal.get('lock_pid')} != "
                f"replacement pid {rep_pid}")
        if rep_journal.get("segment") != 2:
            problems.append(
                f"journal segment {rep_journal.get('segment')} != 2 "
                "(did not advance past the corpse's)")
        if rep_journal.get("stale_lock_swept", 0) < 1:
            problems.append("foreign stale lock was not swept")
        identical = all(
            np.array_equal(originals[i].bp, baseline[i])
            for i in originals)
        identical = identical and all(
            np.array_equal(replies[i].bp, baseline[i]) for i in replies)
        if not identical:
            problems.append("fleet output differs from clean run")
        # Router-side ledger (journal counters live in the CHILDREN —
        # asserted via /healthz above and disk below, not here).
        for name, expect in (("router.deaths", 1),
                             ("router.handoffs", 1),
                             ("router.spills", 1),
                             ("router.resubmitted", n - 1),
                             ("router.hop_disconnects", n - 1),
                             ("router.crash_loops", 0)):
            got = counters.get(name, 0)
            if got != expect:
                problems.append(f"{name}={got} != expected {expect}")
        problems += _reconcile(plan, counters)
        injected = sum(st["injected"] for st in snap.values())
        if injected != 1:
            problems.append(
                f"expected exactly the hop transient, got {injected}")
        # Offline ground truth: both children are gone (SIGTERM drain on
        # fleet exit), so read the journals straight off disk.
        home_dir = os.path.join(fcfg.journal_root, home)
        disk = serve_journal.RequestJournal(home_dir).inspect()
        if disk.get("requests") != n:
            problems.append(
                f"home journal holds {disk.get('requests')} requests "
                f"!= {n}")
        if disk.get("states", {}).get("done", 0) != n:
            problems.append(
                f"home journal done states {disk.get('states')} != "
                f"all-{n}-done")
        if disk.get("segments") != 2:
            problems.append(
                f"home journal has {disk.get('segments')} segments "
                "!= 2 (one per incarnation)")
        if disk.get("incomplete") or disk.get("poisoned"):
            problems.append("home journal left incomplete/poisoned work")
        succ = next((w for w in fleet_health["workers"] if w != home),
                    None)
        sdisk = (serve_journal.RequestJournal(
            os.path.join(fcfg.journal_root, succ)).inspect()
            if succ else {})
        if sdisk.get("states", {}).get("done", 0) != 1:
            problems.append(
                f"successor journal {sdisk.get('states')} != exactly "
                "the one spilled request done")
        return {
            "workload": "fleet_subprocess",
            "plan": plan.to_dict(),
            "injected": injected,
            "sites": snap,
            "handoffs": handoffs,
            "victim_pid": victim_pid,
            "replacement": {"pid": rep_pid,
                            "generation": replacement.get("generation"),
                            "journal": rep_journal},
            "disk": {"home": disk, "successor": sdisk},
            "fleet": {"pending": fleet_health.get("pending"),
                      "ring": fleet_health.get("ring"),
                      "transport": fleet_health.get("transport")},
            "outcomes": {
                "answered": len(originals),
                "resubmitted": int(counters.get("router.resubmitted", 0)),
                "hop_disconnects": int(
                    counters.get("router.hop_disconnects", 0)),
                "stale_lock_swept": int(
                    rep_journal.get("stale_lock_swept", 0)),
            },
            "counters": {k: v for k, v in counters.items()
                         if k.startswith(("chaos.", "serve.", "router."))},
            "identical": identical,
            "ok": not problems,
            "problems": problems,
        }


def drill_batch_partial(plan: ChaosPlan, *, k: int = 3, seed: int = 7
                        ) -> Dict[str, Any]:
    """Batched-engine lane-fault drill: k targets dispatch as ONE engine
    launch; the plan faults one lane's dispatch mid-batch.  Invariants:
    exactly the faulted member comes back as its Exception, every other
    member resolves bit-identical to its sequential singleton run, and
    the injection reconciles against ``batch.lane_faults``."""
    from image_analogies_tpu.obs import trace as obs_trace

    a, ap, targets = drills.make_batch_load(k, seed=seed)
    params = drills.batch_params()

    # clean reference: each member's SEQUENTIAL singleton run — the bit-
    # identity bar the surviving lanes are held to
    baseline = [drills.run_image(a, ap, b, params) for b in targets]

    with obs_trace.run_scope(params) as ctx:
        with inject.plan_scope(plan):
            from image_analogies_tpu.batch import create_image_analogy_batch

            results = create_image_analogy_batch(a, ap, targets, params)
            snap = inject.snapshot()
        counters = _counters(ctx)

    problems = []
    faulted = [i for i, r in enumerate(results) if isinstance(r, Exception)]
    survived = [i for i, r in enumerate(results)
                if not isinstance(r, Exception)]
    injected = sum(st["injected"] for st in snap.values())
    if injected == 0:
        problems.append("plan injected nothing (dead drill)")
    if len(faulted) != injected:
        problems.append(
            f"{injected} injections but {len(faulted)} faulted members "
            "(isolation leaked or swallowed)")
    if len(survived) != k - len(faulted):
        problems.append("member count does not reconcile")
    identical = all(
        np.array_equal(np.asarray(results[i].bp), baseline[i])
        for i in survived)
    if not identical:
        problems.append("surviving lanes differ from sequential runs")
    problems += _reconcile(plan, counters)
    return {
        "workload": "batch_partial",
        "plan": plan.to_dict(),
        "injected": injected,
        "sites": snap,
        "outcomes": {"lanes": k, "faulted": len(faulted),
                     "survived": len(survived)},
        "counters": {key: v for key, v in counters.items()
                     if key.startswith(("chaos.", "batch."))},
        "identical": identical,
        "ok": not problems,
        "problems": problems,
    }


def drill_archive_torn(plan: ChaosPlan, *, seed: int = 7,
                       workdir: Optional[str] = None) -> Dict[str, Any]:
    """Torn-segment + disk-full drill for the durable telemetry archive
    (obs/archive.py).  Clean reference archive (disarmed) → chaos
    archive: the plan's corrupt directive tears ONE sealed segment
    AFTER a successful-looking write (per-record segments, so exactly
    one record is at stake) → offline replay: the reader must
    quarantine exactly the torn segment, keep every undamaged record,
    and reconstruct the same final timeline document as the clean
    archive.  A second, self-armed plan then models disk-full: a
    raising rule at the same site must surface as a counted drop
    (``obs.archive.append_errors``), never as an exception on the
    producer path — the archive is a witness, not a dependency."""
    from image_analogies_tpu.obs import archive as obs_archive
    from image_analogies_tpu.obs import trace as obs_trace

    n_records = 8
    docs = [{"armed": True, "now": float(i), "idx": i,
             "series": {"w0|serve.qps": [[float(i), float(i + seed)]]}}
            for i in range(n_records)]

    problems: List[str] = []
    with tempfile.TemporaryDirectory(dir=workdir) as tmp:
        clean = obs_archive.TelemetryArchive(
            os.path.join(tmp, "clean"), max_segment_bytes=1)
        for i, doc in enumerate(docs):
            clean.append("timeline", doc, now=float(i))
        clean_rep = clean.replay()

        params = drills.image_params(retries=0)
        with obs_trace.run_scope(params) as ctx:
            torn = obs_archive.TelemetryArchive(
                os.path.join(tmp, "torn"), max_segment_bytes=1)
            with inject.plan_scope(plan):
                appended = [torn.append("timeline", doc, now=float(i))
                            for i, doc in enumerate(docs)]
                snap = inject.snapshot()
            if not all(appended):
                problems.append(
                    "corrupt directive must not drop the write itself")
            rep = torn.replay()  # the reader quarantines the torn tail
            full_plan = ChaosPlan(
                seed=plan.seed,
                sites=(("archive.append",
                        SiteRule(kind="transient", schedule=(0,))),),
                name=f"{plan.name}-diskfull")
            with inject.plan_scope(full_plan):
                dropped_ok = torn.append("timeline", docs[-1],
                                         now=float(n_records))
                recovered_ok = torn.append("timeline", docs[-1],
                                           now=float(n_records + 1))
            counters = _counters(ctx)
        if dropped_ok:
            problems.append("disk-full append did not report the drop")
        if not recovered_ok:
            problems.append("append after disk-full did not recover")
        corrupt_files = [n for n in os.listdir(os.path.join(tmp, "torn"))
                         if n.endswith(".corrupt")]

    torn_total = sum(1 for _, r in plan.sites if r.kind == "corrupt")
    if len(corrupt_files) != torn_total:
        problems.append(f"{len(corrupt_files)} quarantined file(s) on "
                        f"disk, expected {torn_total}")
    identical = rep["timeline"] == clean_rep["timeline"]
    if not identical:
        problems.append("replayed final timeline document differs from "
                        "the clean archive's")
    survived = rep["kinds"].get("timeline", 0)
    if survived != n_records - torn_total:
        problems.append(f"{survived} records survived replay, expected "
                        f"{n_records - torn_total} (valid prefix lost?)")
    problems += _reconcile(plan, counters)
    injected = sum(st["injected"] for st in snap.values())
    if injected == 0:
        problems.append("plan injected nothing (dead drill)")
    return {
        "workload": "archive_torn",
        "plan": plan.to_dict(),
        "injected": injected,
        "sites": snap,
        "outcomes": {"records": n_records, "survived": survived,
                     "quarantined": len(corrupt_files),
                     "diskfull_drops":
                         int(counters.get("obs.archive.append_errors", 0))},
        "counters": {k: v for k, v in counters.items()
                     if k.startswith(("chaos.", "obs.archive."))},
        "identical": identical,
        "ok": not problems,
        "problems": problems,
    }


def drill_flash_crowd(plan: ChaosPlan, *, seed: int = 7) -> Dict[str, Any]:
    """Elastic-fleet flash-crowd drill: a Zipf-skewed surge against an
    autoscaling fleet under a declarative ControlPolicy + per-tenant QoS.

    The composite shape: paced submits follow the shared loadgen
    arrival schedule (base rate, then a surge multiplier); queue
    pressure drives the control plane past its hysteresis so it spawns
    workers mid-load; one worker is killed mid-surge (the health daemon
    hands its journal to a replacement, exactly as the fleet_death
    drills prove); once the crowd passes, the idle fleet retires back
    to ``min_workers``.  One armed transient at ``level.dispatch``
    proves local retry recovery still holds while all of that happens.

    Invariants: every answered request is bit-identical to a direct
    engine run; every submit resolves to exactly one outcome (answer or
    quota refusal — zero loss); ALL quota throttles land on the viral
    style while non-viral tenants complete untouched with a bounded
    p95; every scale verdict is reconstructable through the decision
    plane (``ia why ctl-scale_up-<wid>``) and reconciles against the
    ``control.*`` / ``serve.decision.*`` counters."""
    from image_analogies_tpu.obs import trace as obs_trace
    from image_analogies_tpu.serve import journal as serve_journal
    from image_analogies_tpu.serve import loadgen
    from image_analogies_tpu.serve import policy as serve_policy
    from image_analogies_tpu.serve.fleet import Fleet
    from image_analogies_tpu.serve.types import FleetConfig, Rejected

    # Zipf-in-spirit heavy hitter, with EXACT per-style counts so the
    # quota geometry is deterministic: style 0 is viral (30 requests,
    # far past any reachable token allowance), styles 1..2 are the long
    # tail (4 each, under the burst — they must never throttle).
    rng = np.random.RandomState(seed)
    shape = (12, 12)
    styles = [(rng.rand(*shape).astype(np.float32),
               rng.rand(*shape).astype(np.float32)) for _ in range(3)]
    picks = [0] * 30 + [1] * 4 + [2] * 4
    rng.shuffle(picks)
    n = len(picks)
    load = []
    for i, s in enumerate(picks):
        a, ap = styles[s]
        load.append({"index": i, "style": s, "a": a, "ap": ap,
                     "b": rng.rand(*shape).astype(np.float32)})
    # The drill and `ia bench` share ONE traffic model: the loadgen
    # flash-crowd schedule.  A short base-rate preamble, then a hard
    # surge that outruns a single worker.
    sched = loadgen.arrival_schedule(n, t0=0.2, duration=1.0, mult=20.0,
                                     base_rps=30.0, seed=seed)

    with tempfile.TemporaryDirectory() as tmp:
        cfg = drills.serve_config(workers=1, max_batch=4)
        # level retries absorb the armed transient; the tiny quota
        # (burst 5, negligible refill) is what the viral style's 30
        # requests must exceed even across every bucket incarnation a
        # scale-up / kill-replacement can mint (max_workers + spill
        # targets: 4 buckets x 5 tokens < 30).
        cfg = dataclasses.replace(
            cfg,
            params=cfg.params.replace(level_retries=3),
            qos=serve_policy.QosPolicy(quota_rps=0.01, quota_burst=5.0))
        policy = serve_policy.ControlPolicy(
            min_workers=1, max_workers=3, queue_high=2.0, queue_low=0.5,
            scale_up_windows=1, scale_down_windows=2,
            scale_up_cooldown_s=0.1, scale_down_cooldown_s=0.1)
        fcfg = FleetConfig(serve=cfg, size=3, vnodes=16,
                           journal_root=os.path.join(tmp, "journals"),
                           health_interval_s=0.03, death_checks=2,
                           backoff_s=0.01, backoff_cap_s=0.05,
                           policy=policy)
        baseline = {item["index"]: drills.run_image(
            item["a"], item["ap"], item["b"], cfg.params)
            for item in load}

        problems: List[str] = []
        throttles: Dict[int, int] = {}
        rejected_other: List[str] = []
        errors: Dict[int, BaseException] = {}
        originals: Dict[int, Any] = {}
        with obs_trace.run_scope(cfg.params) as ctx:
            inject.arm(plan)
            try:
                with Fleet(fcfg) as fl:
                    futures = {}
                    killed = None
                    t0 = time.perf_counter()
                    for item in load:
                        delay = sched[item["index"]] - (time.perf_counter()
                                                        - t0)
                        if delay > 0:
                            time.sleep(delay)
                        if (killed is None and item["index"] >= n // 2
                                and len(fl.workers) >= 2):
                            # mid-surge death: the health daemon must
                            # hand the journal to a replacement while
                            # the control plane keeps scaling
                            killed = sorted(fl.workers)[0]
                            fl.workers[killed].kill()
                        try:
                            futures[item["index"]] = fl.submit(
                                item["a"], item["ap"], item["b"],
                                idempotency_key="fc-{}".format(
                                    item["index"]),
                                priority=(serve_policy.PRIORITY_INTERACTIVE
                                          if item["style"] else
                                          serve_policy.PRIORITY_STANDARD))
                        except Rejected as exc:
                            if exc.reason == "quota":
                                throttles[item["style"]] = \
                                    throttles.get(item["style"], 0) + 1
                            else:
                                rejected_other.append(exc.reason)
                    if killed is None:
                        # surge drained before the kill window — wait
                        # for the scale-up and deliver the death anyway
                        end = time.monotonic() + 30.0
                        while len(fl.workers) < 2 \
                                and time.monotonic() < end:
                            time.sleep(0.01)
                        if len(fl.workers) >= 2:
                            killed = sorted(fl.workers)[0]
                            fl.workers[killed].kill()
                    for idx, fut in futures.items():
                        try:
                            originals[idx] = fut.result(timeout=120)
                        except BaseException as exc:  # noqa: BLE001
                            errors[idx] = exc
                    # cool-down: the idle fleet must shrink back to the
                    # policy floor on its own
                    end = time.monotonic() + 60.0
                    while (len(fl.workers) > policy.min_workers
                           and time.monotonic() < end):
                        time.sleep(0.02)
                    # The retirement's decision record lands AFTER the
                    # worker leaves the map (scale_down pops first so a
                    # racing forward spills to a live successor), so
                    # settle until the floor-reaching event is visible
                    # before snapshotting — else the counter read after
                    # scope exit can outrun the event list.
                    end = time.monotonic() + 10.0
                    while (not any(e["verdict"] == "scale_down"
                                   and e["size"] <= policy.min_workers
                                   for e in fl.control.events)
                           and time.monotonic() < end):
                        time.sleep(0.01)
                    final_size = len(fl.workers)
                    events = list(fl.control.events)
                    handoffs = list(fl.handoffs)
                    snap = inject.snapshot()
            finally:
                inject.disarm()
            counters = _counters(ctx)

        if killed is None:
            problems.append("fleet never scaled up; no worker to kill")
        up_events = [e for e in events if e["verdict"] == "scale_up"]
        down_events = [e for e in events if e["verdict"] == "scale_down"]
        if not up_events:
            problems.append("control plane never recorded a scale_up")
        if not down_events:
            problems.append("control plane never recorded a scale_down")
        if final_size != policy.min_workers:
            problems.append(
                f"fleet ended at {final_size} workers, policy floor is "
                f"{policy.min_workers}")
        if not handoffs:
            problems.append("mid-surge kill produced no journal handoff")
        # zero-loss accounting: every submit resolved to exactly one of
        # answer / quota refusal; nothing else
        if errors:
            problems.append(f"{len(errors)} futures errored: "
                            f"{sorted(type(e).__name__ for e in errors.values())}")
        if rejected_other:
            problems.append(f"non-quota rejections: {rejected_other}")
        if len(originals) + len(errors) + sum(throttles.values()) \
                + len(rejected_other) != n:
            problems.append("outcome accounting does not sum to n")
        # QoS: the viral style absorbs ALL throttles; the long tail
        # completes untouched with a bounded p95
        if not throttles.get(0):
            problems.append("viral style was never quota-throttled")
        if any(s for s in throttles if s != 0):
            problems.append(f"non-viral styles throttled: {throttles}")
        lat_tail = [originals[i].total_ms for i in originals if picks[i]]
        tail_p95 = loadgen.percentile(lat_tail, 95)
        if len(lat_tail) != 8:
            problems.append(
                f"only {len(lat_tail)}/8 non-viral requests answered")
        if tail_p95 > 30_000:
            problems.append(f"non-viral p95 {tail_p95}ms exceeds bound")
        identical = all(
            np.array_equal(originals[i].bp, baseline[i])
            for i in originals if originals[i].degraded is None)
        if not identical:
            problems.append("answered output differs from clean run")
        # decision plane: counters reconcile and `ia why` reconstructs
        # each scale verdict from the sealed log
        for name in ("control.scale_up", "control.scale_down"):
            got = counters.get(name, 0)
            want_n = len(up_events if name.endswith("up") else down_events)
            if got != want_n:
                problems.append(f"{name}={got} != {want_n} events")
            mirrored = counters.get(
                "serve.decision." + name.split(".", 1)[1], 0)
            if mirrored != got:
                problems.append(
                    f"serve.decision mirror {mirrored} != {name}={got}")
        for ev in up_events[:1] + down_events[:1]:
            idem = "ctl-{}-{}".format(ev["verdict"], ev["worker"])
            why = serve_journal.reconstruct(idem, fcfg.journal_root)
            if not why.get("found"):
                problems.append(f"ia why found no evidence for {idem}")
        problems += _reconcile(plan, counters)
        injected = sum(st["injected"] for st in snap.values())
        if injected < 1:
            problems.append("the armed transient never fired")
        return {
            "workload": "flash_crowd",
            "plan": plan.to_dict(),
            "injected": injected,
            "sites": snap,
            "handoffs": handoffs,
            "scale_events": events,
            "killed": killed,
            "final_size": final_size,
            "outcomes": {
                "answered": len(originals),
                "quota_throttled": {f"s{k}": v
                                    for k, v in sorted(throttles.items())},
                "tail_p95_ms": round(tail_p95, 2),
            },
            "counters": {k: v for k, v in counters.items()
                         if k.startswith(("chaos.", "serve.", "router.",
                                          "control."))},
            "identical": identical,
            "ok": not problems,
            "problems": problems,
        }


def run_drill(plan: ChaosPlan, **kw) -> Dict[str, Any]:
    """Dispatch a plan to the workload its sites target."""
    if "flash_crowd" in (plan.name or ""):
        return drill_flash_crowd(plan, **kw)
    if any(name == "archive.append" for name, _ in plan.sites):
        return drill_archive_torn(plan, **kw)
    if any(name == "match.prefilter" for name, _ in plan.sites):
        return drill_ann_corrupt(plan, **kw)
    if any(name == "devcache.tier" for name, _ in plan.sites):
        return drill_catalog_tier(plan, **kw)
    if any(name == "engine.batch" for name, _ in plan.sites):
        return drill_batch_partial(plan, **kw)
    if any(name == "router.forward" for name, _ in plan.sites):
        if "subprocess" in (plan.name or ""):
            return drill_fleet_subprocess(plan, **kw)
        return drill_fleet(plan, **kw)
    if any(name == "serve.journal" for name, _ in plan.sites):
        return drill_kill_restart(plan, **kw)
    if _wants_serve(plan):
        return drill_serve(plan, **kw)
    return drill_image(plan, **kw)


def check_determinism(seed: int = 0) -> Dict[str, Any]:
    """Same seed ⇒ same fault schedule: run a probabilistic plan's
    decision stream twice (no workload needed — the stream is a pure
    function of (plan, visit sequence)) and compare."""
    plan = ChaosPlan(seed=seed, sites=(
        ("level.dispatch", SiteRule(kind="latency", p=0.5, latency_ms=0.0)),
        ("devcache.upload", SiteRule(kind="latency", p=0.3,
                                     latency_ms=0.0)),
    ), name="determinism")
    runs = []
    for _ in range(2):
        with inject.plan_scope(plan):
            for _visit in range(64):
                inject.site("level.dispatch")
                inject.site("devcache.upload")
            runs.append(inject.snapshot())
    ok = runs[0] == runs[1]
    return {"workload": "determinism", "plan": plan.to_dict(),
            "injected": sum(st["injected"] for st in runs[0].values()),
            "ok": ok,
            "problems": [] if ok else [f"schedules differ: {runs}"]}


def selftest(seed: int = 0, kinds: Optional[Sequence[str]] = None
             ) -> Dict[str, Any]:
    """One canonical drill per drill kind + the determinism check."""
    reports = []
    for kind in (kinds or DRILL_KINDS):
        plan = plan_for_kind(kind, seed)
        report = run_drill(plan)
        report["kind"] = kind
        report["note"] = _KIND_NOTES.get(kind, "")
        reports.append(report)
    det = check_determinism(seed)
    det["kind"] = "determinism"
    det["note"] = "same seed, same schedule"
    reports.append(det)
    return {"seed": seed, "ok": all(r["ok"] for r in reports),
            "reports": reports}


def render(result: Dict[str, Any]) -> str:
    lines = [f"chaos selftest (seed {result['seed']}): "
             f"{'PASS' if result['ok'] else 'FAIL'}"]
    for r in result["reports"]:
        status = "ok " if r["ok"] else "FAIL"
        line = (f"  [{status}] {r.get('kind', r['plan'].get('name', '?')):12s}"
                f" injected={r.get('injected', 0)}")
        if "outcomes" in r:
            line += f" outcomes={r['outcomes']}"
        if r.get("note"):
            line += f"  ({r['note']})"
        lines.append(line)
        for p in r.get("problems", []):
            lines.append(f"         ! {p}")
    return "\n".join(lines)
