"""Drill runner — ``ia chaos``: run workloads under fault plans and
assert the resilience invariants.

A drill is: clean reference run (disarmed) → chaos run (armed plan) →
invariant checks.  The invariants are the PR's acceptance criteria, not
soft goals:

- **bit-identical output** — recovery must reproduce the clean run's
  planes exactly (CPU backend; the engine is deterministic, so equality
  is the right assertion);
- **nothing lost** — every serve submit resolves to exactly one of
  ok / degraded / timeout / rejected, the queue drains, worker threads
  survive;
- **counters reconcile** — every injection is visible in the recovery
  counters it caused (retries, watchdog timeouts, quarantines, worker
  crashes).  An injection that no counter accounts for means a fault
  path silently swallowed something.

``selftest`` runs one canonical drill per fault kind plus a
schedule-determinism check (same seed ⇒ same fault schedule).
"""

from __future__ import annotations

import os
import tempfile
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from image_analogies_tpu.chaos import drills, inject
from image_analogies_tpu.chaos.plan import ChaosPlan, SiteRule

# Fault kind -> canonical drill plan.  Schedules (not probabilities) so
# each selftest drill injects exactly once at a known visit.
_KIND_NOTES = {
    "transient": "level retry absorbs an injected transient",
    "oom": "RESOURCE_EXHAUSTED classifies transient via the real path",
    "latency": "watchdog converts a wedged dispatch into a retry",
    "corrupt": "checksum catches damaged checkpoint; quarantine+recompute",
    "crash": "worker crash containment requeues the batch",
}


def plan_for_kind(kind: str, seed: int = 0) -> ChaosPlan:
    if kind == "transient":
        sites = (("level.dispatch", SiteRule(kind="transient",
                                             schedule=(0,))),)
    elif kind == "oom":
        sites = (("level.dispatch", SiteRule(kind="oom", schedule=(1,))),)
    elif kind == "latency":
        sites = (("level.dispatch", SiteRule(kind="latency", schedule=(0,),
                                             latency_ms=200.0, hang=True)),)
    elif kind == "corrupt":
        sites = (("ckpt.save", SiteRule(kind="corrupt", schedule=(0,))),)
    elif kind == "crash":
        sites = (("serve.dispatch", SiteRule(kind="crash", schedule=(0,))),)
    else:
        raise ValueError(f"unknown fault kind {kind!r}")
    return ChaosPlan(seed=seed, sites=sites, name=f"selftest-{kind}")


def _wants_serve(plan: ChaosPlan) -> bool:
    return any(name.startswith("serve.") for name, _ in plan.sites)


def _counters(ctx) -> Dict[str, float]:
    return dict(ctx.registry.snapshot()["counters"]) if ctx else {}


def _reconcile(plan: ChaosPlan, counters: Dict[str, float]) -> List[str]:
    """Per-kind accounting: every injection must be matched by the
    recovery counter it should have caused.  Returns failure strings."""
    problems = []

    def want(name: str, expected: float) -> None:
        got = counters.get(name, 0)
        if got != expected:
            problems.append(f"{name}={got} != expected {expected}")

    by_kind: Dict[str, float] = {}
    for key, val in counters.items():
        if key.startswith("chaos.injected."):
            by_kind[key.split(".", 2)[2]] = val
    injected = counters.get("chaos.injected", 0)
    if sum(by_kind.values()) != injected:
        problems.append("per-kind chaos counters do not sum to total")
    # Expectations come from the PLAN (per-site injection counters x each
    # site's rule), because the same kind recovers differently by
    # placement: transient/oom under the level retry wrapper retry; a
    # hang surfaces as a watchdog timeout first, THEN retries; a plain
    # (non-hang) latency spike recovers by itself; corruption surfaces at
    # load as a quarantine; a crash as a contained worker crash.  A
    # raising kind at a serve batch boundary is contained as a crash
    # regardless of its class — the containment layer can't tell.
    retries = watchdogs = quarantines = crashes = 0.0
    for name, rule in plan.sites:
        n = counters.get(f"chaos.site.{name}", 0)
        if not n:
            continue
        if name == "serve.admit":
            continue  # surfaces synchronously to the client; no recovery
        if name in ("serve.dispatch",) and rule.kind in (
                "transient", "oom", "crash"):
            crashes += n
        elif rule.kind in ("transient", "oom"):
            retries += n
        elif rule.kind == "latency" and rule.hang:
            watchdogs += n
            retries += n
        elif rule.kind == "corrupt":
            quarantines += n
        elif rule.kind == "crash":
            crashes += n
    if retries:
        want("level_retry", retries)
    if watchdogs:
        want("watchdog.timeouts", watchdogs)
    if quarantines:
        want("ckpt.quarantined", quarantines)
    if crashes:
        want("serve.worker_crashes", crashes)
    return problems


def drill_image(plan: ChaosPlan, *, seed: int = 7,
                size=(20, 20), workdir: Optional[str] = None
                ) -> Dict[str, Any]:
    """Single-image drill: clean run, chaos run (and for checkpoint
    corruption a third resume run hitting the quarantine path), then the
    invariants."""
    from image_analogies_tpu.obs import trace as obs_trace

    a, ap, b = drills.make_inputs(size, seed)
    corrupting = any(r.kind == "corrupt" for _, r in plan.sites)
    hanging = any(r.kind == "latency" and r.hang for _, r in plan.sites)

    clean = drills.run_image(a, ap, b, drills.image_params(retries=0))

    with tempfile.TemporaryDirectory(dir=workdir) as tmp:
        params = drills.image_params(
            retries=3,
            checkpoint_dir=os.path.join(tmp, "ckpt"),
            # a hang only recovers when something bounds the wait; give
            # the watchdog a deadline well under the injected latency
            dispatch_timeout_s=0.05 if hanging else 0.0)
        with obs_trace.run_scope(params) as ctx:
            with inject.plan_scope(plan):
                chaos_bp = drills.run_image(a, ap, b, params)
                snap = inject.snapshot()
            resumed_bp = None
            if corrupting:
                # resume run (disarmed): hits the damaged file, must
                # quarantine + recompute to the identical result
                resumed_bp = drills.run_image(
                    a, ap, b, params.replace(resume_from_level=0))
            counters = _counters(ctx)

    identical = bool(np.array_equal(clean, chaos_bp))
    if resumed_bp is not None:
        identical = identical and bool(np.array_equal(clean, resumed_bp))
    problems = [] if identical else ["output differs from clean run"]
    problems += _reconcile(plan, counters)
    injected = sum(st["injected"] for st in snap.values())
    if injected == 0:
        problems.append("plan injected nothing (dead drill)")
    return {
        "workload": "image",
        "plan": plan.to_dict(),
        "injected": injected,
        "sites": snap,
        "counters": {k: v for k, v in counters.items()
                     if k.startswith(("chaos.", "level_retry", "retry.",
                                      "watchdog.", "ckpt."))},
        "identical": identical,
        "ok": not problems,
        "problems": problems,
    }


def drill_serve(plan: ChaosPlan, *, n: int = 6, seed: int = 7
                ) -> Dict[str, Any]:
    """Serve drill: burst-submit n requests under the plan; every future
    must resolve to exactly one known outcome, outputs must match direct
    engine runs, the queue must drain, and counters must reconcile."""
    from image_analogies_tpu.obs import trace as obs_trace
    from image_analogies_tpu.serve.server import Server
    from image_analogies_tpu.serve.types import DeadlineExceeded, Rejected

    cfg = drills.serve_config()
    load = drills.make_serve_load(n, seed=seed)
    baseline = {item["index"]: drills.run_image(
        item["a"], item["ap"], item["b"], cfg.params)
        for item in load}

    outcomes: Dict[int, str] = {}
    responses: Dict[int, Any] = {}
    unknown_errors: Dict[int, str] = {}
    with obs_trace.run_scope(cfg.params) as ctx:
        with inject.plan_scope(plan):
            with Server(cfg) as srv:
                futures = {}
                for item in load:
                    try:
                        futures[item["index"]] = srv.submit(
                            item["a"], item["ap"], item["b"])
                    except Exception as exc:  # noqa: BLE001 - admission faults
                        # injected admission faults surface synchronously,
                        # like any admission refusal
                        outcomes[item["index"]] = (
                            "rejected" if isinstance(exc, Rejected)
                            else "submit_fault")
                for idx, fut in futures.items():
                    try:
                        responses[idx] = fut.result(timeout=120)
                        outcomes[idx] = responses[idx].status
                    except Rejected:
                        outcomes[idx] = "rejected"
                    except DeadlineExceeded:
                        outcomes[idx] = "timeout"
                    except BaseException as exc:  # noqa: BLE001 - audited
                        outcomes[idx] = "error"
                        unknown_errors[idx] = repr(exc)
                drained = srv.queue_depth == 0
            snap = inject.snapshot()
        counters = _counters(ctx)

    problems = []
    if len(outcomes) != n:
        problems.append(f"{n - len(outcomes)} requests never resolved")
    if unknown_errors:
        problems.append(f"unexpected errors: {unknown_errors}")
    if not drained:
        problems.append("queue did not drain")
    identical = all(
        np.array_equal(responses[i].bp, baseline[i])
        for i in responses if responses[i].degraded is None)
    if not identical:
        problems.append("served output differs from direct engine run")
    problems += _reconcile(plan, counters)
    injected = sum(st["injected"] for st in snap.values())
    if injected == 0:
        problems.append("plan injected nothing (dead drill)")
    tally: Dict[str, int] = {}
    for o in outcomes.values():
        tally[o] = tally.get(o, 0) + 1
    return {
        "workload": "serve",
        "plan": plan.to_dict(),
        "injected": injected,
        "sites": snap,
        "outcomes": tally,
        "counters": {k: v for k, v in counters.items()
                     if k.startswith(("chaos.", "serve.", "level_retry",
                                      "retry.", "watchdog."))},
        "identical": identical,
        "ok": not problems,
        "problems": problems,
    }


def run_drill(plan: ChaosPlan, **kw) -> Dict[str, Any]:
    """Dispatch a plan to the workload its sites target."""
    if _wants_serve(plan):
        return drill_serve(plan, **kw)
    return drill_image(plan, **kw)


def check_determinism(seed: int = 0) -> Dict[str, Any]:
    """Same seed ⇒ same fault schedule: run a probabilistic plan's
    decision stream twice (no workload needed — the stream is a pure
    function of (plan, visit sequence)) and compare."""
    plan = ChaosPlan(seed=seed, sites=(
        ("level.dispatch", SiteRule(kind="latency", p=0.5, latency_ms=0.0)),
        ("devcache.upload", SiteRule(kind="latency", p=0.3,
                                     latency_ms=0.0)),
    ), name="determinism")
    runs = []
    for _ in range(2):
        with inject.plan_scope(plan):
            for _visit in range(64):
                inject.site("level.dispatch")
                inject.site("devcache.upload")
            runs.append(inject.snapshot())
    ok = runs[0] == runs[1]
    return {"workload": "determinism", "plan": plan.to_dict(),
            "injected": sum(st["injected"] for st in runs[0].values()),
            "ok": ok,
            "problems": [] if ok else [f"schedules differ: {runs}"]}


def selftest(seed: int = 0, kinds: Optional[Sequence[str]] = None
             ) -> Dict[str, Any]:
    """One canonical drill per fault kind + the determinism check."""
    from image_analogies_tpu.chaos import FAULT_KINDS

    reports = []
    for kind in (kinds or FAULT_KINDS):
        plan = plan_for_kind(kind, seed)
        report = run_drill(plan)
        report["kind"] = kind
        report["note"] = _KIND_NOTES.get(kind, "")
        reports.append(report)
    det = check_determinism(seed)
    det["kind"] = "determinism"
    det["note"] = "same seed, same schedule"
    reports.append(det)
    return {"seed": seed, "ok": all(r["ok"] for r in reports),
            "reports": reports}


def render(result: Dict[str, Any]) -> str:
    lines = [f"chaos selftest (seed {result['seed']}): "
             f"{'PASS' if result['ok'] else 'FAIL'}"]
    for r in result["reports"]:
        status = "ok " if r["ok"] else "FAIL"
        line = (f"  [{status}] {r.get('kind', r['plan'].get('name', '?')):12s}"
                f" injected={r.get('injected', 0)}")
        if "outcomes" in r:
            line += f" outcomes={r['outcomes']}"
        if r.get("note"):
            line += f"  ({r['note']})"
        lines.append(line)
        for p in r.get("problems", []):
            lines.append(f"         ! {p}")
    return "\n".join(lines)
