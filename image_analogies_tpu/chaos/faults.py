"""Fault implementations — what an armed site actually does.

Raising kinds throw exception types chosen to exercise the REAL
classification paths, not shortcuts:

- ``transient`` raises :class:`ChaosTransient`, a subclass of
  ``utils.failure.InjectedFailure`` — the retry wrapper's canonical
  synthetic transient.
- ``oom`` raises a class literally named ``XlaRuntimeError`` carrying a
  ``RESOURCE_EXHAUSTED`` status message, so ``failure._is_transient``'s
  name-based jax-error matching (and its status-code filter) is the
  thing under test, exactly as a real device OOM would hit it.
- ``crash`` raises :class:`WorkerCrash` — deliberately NOT transient:
  retry wrappers must not absorb it; the serve worker's crash
  containment (batch requeue) is the only recovery path.

``corrupt`` is not raised at all: the site returns the ``"corrupt"``
directive and the call site (checkpoint save) applies
:func:`corrupt_file` — deterministic byte flips seeded by the plan, so
the same plan always produces the same corruption.
"""

from __future__ import annotations

import hashlib
import os
import random

from image_analogies_tpu.utils.failure import InjectedFailure


def stream_seed(*parts) -> int:
    """Stable int seed from mixed parts.  ``hash()`` of a str is
    randomized per process (PYTHONHASHSEED), so seeding Random with a
    tuple containing site names would silently break the cross-process
    determinism contract — digest instead."""
    digest = hashlib.sha256(repr(parts).encode()).digest()
    return int.from_bytes(digest[:8], "big")


class ChaosTransient(InjectedFailure):
    """Injected transient device fault (retryable by design)."""


class XlaRuntimeError(RuntimeError):
    """Injected runtime error whose NAME is what the transient classifier
    keys on — messages carry an XLA status code so both the retryable
    (RESOURCE_EXHAUSTED) and bug (INVALID_ARGUMENT) branches are
    reachable from drills."""


class WorkerCrash(RuntimeError):
    """Injected worker-thread crash: non-transient on purpose."""


class ProcessDeath(BaseException):
    """Injected process death — the whole process is gone, mid-write.

    Deliberately derives from ``BaseException`` AND is excluded from the
    serve worker's crash containment: a dead process cannot requeue its
    batch, resolve futures, or append a journal line.  In-process drills
    model death by letting this escape the worker thread (it exits
    silently, futures unresolved) and then tearing the server down
    non-gracefully; the write-ahead journal replay on restart is the only
    recovery path, which is exactly what the kill-restart drill verifies.
    """


def oom_error(site: str, visit: int) -> XlaRuntimeError:
    return XlaRuntimeError(
        f"RESOURCE_EXHAUSTED: chaos oom at {site} (visit {visit}): "
        "attempting to allocate 9.99G hbm")


def corrupt_file(path: str, seed: int, n_flips: int = 16) -> int:
    """Deterministically flip ``n_flips`` bytes of ``path`` in place.

    Returns the number of bytes flipped (0 when the file is empty or
    missing — corruption of nothing is a no-op, not an error).  Flips
    land in the back half of the file so container headers survive and
    the damage surfaces as payload corruption (truncated/garbled npz),
    the realistic partial-write failure mode.
    """
    try:
        size = os.path.getsize(path)
    except OSError:
        return 0
    if size == 0:
        return 0
    rng = random.Random(stream_seed(seed, os.path.basename(path), size))
    offsets = sorted({rng.randrange(size // 2, size)
                      for _ in range(min(n_flips, size))})
    with open(path, "r+b") as f:
        for off in offsets:
            f.seek(off)
            b = f.read(1)
            f.seek(off)
            f.write(bytes([b[0] ^ 0xFF]))
    return len(offsets)
