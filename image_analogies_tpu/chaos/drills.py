"""Drill workloads: small, deterministic, CPU-friendly jobs the runner
executes under a fault plan.

Everything here is seeded numpy — the SAME inputs and params are used
for the clean reference run and the chaos run, so "bit-identical output"
is a meaningful assertion, not a tolerance check.  jax is only touched
inside the engine calls (lazy imports keep chaos/ importable — and
grep-locked jax-free — on any host).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np


def make_inputs(size: Tuple[int, int] = (20, 20), seed: int = 7
                ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Deterministic (A, A', B) planes for one synthesis."""
    h, w = size
    rng = np.random.RandomState(seed)
    return (rng.rand(h, w).astype(np.float32),
            rng.rand(h, w).astype(np.float32),
            rng.rand(h, w).astype(np.float32))


def image_params(*, levels: int = 2, retries: int = 3,
                 checkpoint_dir: Optional[str] = None,
                 dispatch_timeout_s: float = 0.0):
    """Small CPU engine config for image drills.  Patch 3 / tiny planes:
    a drill exercises control flow, not throughput."""
    from image_analogies_tpu.config import AnalogyParams

    return AnalogyParams(backend="cpu", levels=levels, patch_size=3,
                         coarse_patch_size=3, level_retries=retries,
                         checkpoint_dir=checkpoint_dir,
                         dispatch_timeout_s=dispatch_timeout_s,
                         metrics=True)


def catalog_params(catalog_dir: str, *, levels: int = 2):
    """Catalog-tier drill config: tiny CPU engine with the exemplar
    catalog rooted at ``catalog_dir``.  No retries — the devcache.tier
    directive never raises; recovery is the tier fall-through itself."""
    from image_analogies_tpu.config import AnalogyParams

    return AnalogyParams(backend="cpu", levels=levels, patch_size=3,
                         coarse_patch_size=3, level_retries=0,
                         catalog_dir=catalog_dir, metrics=True)


def ann_params(catalog_dir: str, *, levels: int = 2):
    """Two-stage ANN drill config: TPU-backend wavefront engine (the ANN
    matcher lives in the TPU backend; its XLA programs compile on any
    host) with the exemplar catalog rooted at ``catalog_dir`` and the
    prefilter armed.  No retries — the ``match.prefilter`` corrupt
    directive never raises; recovery is the quarantine → exact-fallback
    → rebuild chain itself."""
    from image_analogies_tpu.config import AnalogyParams

    return AnalogyParams(backend="tpu", strategy="wavefront", levels=levels,
                         patch_size=3, coarse_patch_size=3, level_retries=0,
                         ann_prefilter=True, catalog_dir=catalog_dir,
                         metrics=True)


def run_image(a: np.ndarray, ap: np.ndarray, b: np.ndarray, params
              ) -> np.ndarray:
    """One engine synthesis; returns the host bp plane."""
    from image_analogies_tpu.models.analogy import create_image_analogy

    return np.asarray(create_image_analogy(a, ap, b, params).bp)


def batch_params(*, levels: int = 2):
    """Batched-engine drill config: TPU-backend XLA programs (they
    compile on any host), no luminance remap (random targets would
    diverge the A/A' DB and refuse the batch), no level retries (the
    engine refuses those — per-lane isolation IS its recovery story)."""
    from image_analogies_tpu.config import AnalogyParams

    return AnalogyParams(backend="tpu", strategy="batched", levels=levels,
                         patch_size=3, coarse_patch_size=3,
                         remap_luminance=False, level_retries=0,
                         metrics=True)


def make_batch_load(k: int, size: Tuple[int, int] = (16, 16), seed: int = 7
                    ) -> Tuple[np.ndarray, np.ndarray, List[np.ndarray]]:
    """One exemplar pair + k distinct same-shape targets (the batched
    engine's admission shape)."""
    rng = np.random.RandomState(seed)
    h, w = size
    return (rng.rand(h, w).astype(np.float32),
            rng.rand(h, w).astype(np.float32),
            [rng.rand(h, w).astype(np.float32) for _ in range(k)])


def make_serve_load(n: int, size: Tuple[int, int] = (12, 12), seed: int = 7
                    ) -> List[Dict[str, np.ndarray]]:
    """N batch-compatible requests (shared exemplars, distinct targets)."""
    rng = np.random.RandomState(seed)
    h, w = size
    a = rng.rand(h, w).astype(np.float32)
    ap = rng.rand(h, w).astype(np.float32)
    return [{"index": i, "a": a, "ap": ap,
             "b": rng.rand(h, w).astype(np.float32)}
            for i in range(n)]


def serve_config(*, workers: int = 2, max_batch: int = 4,
                 crash_requeues: int = 1, breaker_threshold: int = 5,
                 deadline_ordering: bool = True,
                 batch_window_ms: float = 2.0,
                 journal_dir: Optional[str] = None):
    """Small CPU serve config for serve drills.

    ``journal_dir`` arms the write-ahead journal (kill-restart drill);
    drill journals skip fsync — the drill restarts in-process, so
    OS-buffer durability is enough and the selftest stays fast."""
    from image_analogies_tpu.serve.types import ServeConfig

    return ServeConfig(
        params=image_params(levels=1, retries=0),
        queue_depth=64,
        batch_window_ms=batch_window_ms,
        max_batch=max_batch,
        workers=workers,
        request_retries=2,
        crash_requeues=crash_requeues,
        breaker_threshold=breaker_threshold,
        deadline_ordering=deadline_ordering,
        drain_timeout_s=60.0,
        journal_dir=journal_dir,
        journal_fsync=False,
    )
