"""Fault plans: what to inject, where, and when — deterministically.

A :class:`ChaosPlan` is a seed plus a rule per injection site.  Each
:class:`SiteRule` fires either on an explicit ``schedule`` of per-site
call indices (0-based: ``[0, 3]`` faults the 1st and 4th visit) or with
probability ``p`` per visit, capped by ``max_faults``.  Probability
draws come from a per-``(seed, site)`` stream, so the schedule a seed
produces is a pure function of the plan — re-running a drill with the
same plan replays the exact same faults.

Plans serialize to/from plain JSON so CI can keep drill plans as
checked-in files:

    {
      "seed": 42,
      "sites": {
        "level.dispatch": {"kind": "transient", "p": 0.5, "max_faults": 2},
        "ckpt.save":      {"kind": "corrupt", "schedule": [0]},
        "serve.dispatch": {"kind": "crash", "schedule": [1]}
      }
    }
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, Optional, Tuple

_KINDS = ("transient", "oom", "latency", "corrupt", "crash",
          "process_death")

# Every injection site wired into the codebase (chaos/inject.py's
# docstring is the prose version).  A plan naming a site outside this
# registry would arm NOTHING — the typo'd rule silently never fires and
# a drill (or a soak) passes vacuously — so loaders validate against it.
KNOWN_SITES = (
    "level.dispatch",    # models/analogy.py  — per-level device dispatch
    "devcache.upload",   # utils/devcache.py  — host→device upload
    "devcache.tier",     # catalog/tiers.py   — catalog tier resolution
    "match.prefilter",   # backends/tpu.py    — ANN projection resolution
    "ckpt.save",         # utils/checkpoint.py — checkpoint write
    "ckpt.load",         # utils/checkpoint.py — checkpoint read
    "serve.admit",       # serve/queue.py     — request admission
    "serve.dispatch",    # serve/worker.py    — batch dispatch
    "serve.journal",     # serve/journal.py   — journal append
    "engine.batch",      # batch/engine.py    — per-lane batched dispatch
    "mesh.step",         # parallel/step.py   — multichip level step
    "router.forward",    # serve/router.py    — fleet hop forward
    "archive.append",    # obs/archive.py     — sealed telemetry append
)


@dataclasses.dataclass(frozen=True)
class SiteRule:
    """One site's fault behavior.

    ``kind``       one of transient | oom | latency | corrupt | crash |
                   process_death.
    ``p``          per-visit fault probability (ignored when ``schedule``
                   is given).
    ``schedule``   explicit 0-based call indices that fault.
    ``max_faults`` total injection cap for the site (0 = unlimited).
    ``latency_ms`` sleep length for the latency kind (fixed delay).
    ``latency_p50_ms`` / ``latency_p99_ms``
                   latency only: when both are set (> 0) the sleep is
                   drawn from a lognormal with that median and 99th
                   percentile instead of the fixed ``latency_ms`` —
                   realistic tail-latency drills.  Draws come from the
                   per-``(seed, site)`` stream, so the same plan always
                   produces the same delays.
    ``hang``       latency only: after the sleep, raise instead of
                   resuming — models a wedged op that never completes
                   (the watchdog drill's fault; a plain sleep models a
                   slow-but-successful op).
    """

    kind: str
    p: float = 0.0
    schedule: Tuple[int, ...] = ()
    max_faults: int = 0
    latency_ms: float = 50.0
    latency_p50_ms: float = 0.0
    latency_p99_ms: float = 0.0
    hang: bool = False

    def __post_init__(self):
        if self.kind not in _KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}; "
                             f"expected one of {_KINDS}")
        if not self.schedule and not (0.0 <= self.p <= 1.0):
            raise ValueError(f"p must be in [0, 1], got {self.p}")
        if self.max_faults < 0 or self.latency_ms < 0:
            raise ValueError("max_faults/latency_ms must be >= 0")
        if self.latency_p50_ms < 0 or self.latency_p99_ms < 0:
            raise ValueError("latency percentiles must be >= 0")
        if bool(self.latency_p50_ms) != bool(self.latency_p99_ms):
            raise ValueError(
                "latency_p50_ms and latency_p99_ms must be set together")
        if self.latency_p50_ms and self.latency_p99_ms < self.latency_p50_ms:
            raise ValueError("latency_p99_ms must be >= latency_p50_ms")


@dataclasses.dataclass(frozen=True)
class ChaosPlan:
    """A seed + site rules; the unit `ia chaos` arms and replays."""

    seed: int = 0
    sites: Tuple[Tuple[str, SiteRule], ...] = ()
    name: str = ""

    def rule_for(self, site: str) -> Optional[SiteRule]:
        for name, rule in self.sites:
            if name == site:
                return rule
        return None

    def validate_sites(self, known: Optional[Tuple[str, ...]] = None
                       ) -> "ChaosPlan":
        """Reject site names outside ``known`` (default: the wired-in
        :data:`KNOWN_SITES` registry).  A typo'd site would never fire
        and the drill would pass vacuously — loud beats vacuous.
        Returns ``self`` so loaders can chain it."""
        registry = tuple(known) if known is not None else KNOWN_SITES
        unknown = [name for name, _ in self.sites if name not in registry]
        if unknown:
            raise ValueError(
                f"unknown injection site(s) {sorted(unknown)!r}; "
                f"known sites: {sorted(registry)}")
        return self

    def to_dict(self) -> Dict[str, Any]:
        return {
            "seed": self.seed,
            "name": self.name,
            "sites": {
                name: {k: (list(v) if isinstance(v, tuple) else v)
                       for k, v in dataclasses.asdict(rule).items()
                       # keep the JSON minimal: drop inert defaults
                       if not (k == "p" and not v)
                       and not (k == "schedule" and not v)
                       and not (k == "max_faults" and not v)
                       and not (k == "latency_p50_ms" and not v)
                       and not (k == "latency_p99_ms" and not v)
                       and not (k == "hang" and not v)}
                for name, rule in self.sites
            },
        }

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "ChaosPlan":
        if not isinstance(d, dict):
            raise ValueError("chaos plan must be a JSON object")
        sites_raw = d.get("sites", {})
        if not isinstance(sites_raw, dict):
            raise ValueError("chaos plan 'sites' must be an object")
        sites = []
        for name, spec in sites_raw.items():
            if not isinstance(spec, dict) or "kind" not in spec:
                raise ValueError(f"site {name!r} needs a 'kind'")
            kw = dict(spec)
            if "schedule" in kw:
                kw["schedule"] = tuple(int(x) for x in kw["schedule"])
            sites.append((str(name), SiteRule(**kw)))
        return ChaosPlan(seed=int(d.get("seed", 0)),
                         sites=tuple(sites),
                         name=str(d.get("name", "")))

    @staticmethod
    def from_json(blob: str) -> "ChaosPlan":
        return ChaosPlan.from_dict(json.loads(blob))

    @staticmethod
    def load(path: str) -> "ChaosPlan":
        """Load a checked-in plan file.  Unlike the programmatic
        constructors (tests build plans against synthetic sites), a
        FILE plan is an operator artifact: its site names are validated
        against :data:`KNOWN_SITES` here, at load time, so a typo fails
        loudly instead of never firing."""
        with open(path) as f:
            return ChaosPlan.from_dict(json.load(f)).validate_sites()
