"""The injection plane: named sites at every layer boundary.

Engine layers call ``site("name", **ctx)`` at their boundaries:

    level.dispatch    models/analogy.py   — per-level device dispatch
    devcache.upload   utils/devcache.py   — host→device upload (miss path)
    devcache.tier     catalog/tiers.py    — per-level catalog tier
                                            resolution ("corrupt" =
                                            evict the key mid-request)
    match.prefilter   backends/tpu.py     — per-level ANN projection
                                            resolution ("corrupt" =
                                            damage the sealed artifact)
    ckpt.save         utils/checkpoint.py — checkpoint write
    ckpt.load         utils/checkpoint.py — checkpoint read
    serve.admit       serve/queue.py      — request admission
    serve.dispatch    serve/worker.py     — batch dispatch
    engine.batch      batch/engine.py     — per-lane batched dispatch
    mesh.step         parallel/step.py    — multichip level step

Disarmed (the production default), ``site()`` is one module-bool check
and an immediate ``return None`` — no lock, no metric, no allocation
(locked by tests/test_chaos.py's zero-activity test, matching the obs/
off-path contract).  Armed, the site consults the plan: raising kinds
throw, ``latency`` sleeps, and ``corrupt`` returns a directive string
the call site applies itself.

Determinism: each site draws from its own stably-seeded per-(seed, name)
``random.Random`` stream and keeps its own visit counter, so a plan's fault schedule is a
pure function of (plan, per-site call sequence) — re-running the same
drill replays the same faults.  Visit counters are taken under one lock
(serve drills are multi-threaded); which *thread* sees visit k may vary,
but the k-th visit faulting or not never does — and the drill invariants
(bit-identical output, nothing lost) hold regardless of which request a
fault lands on.
"""

from __future__ import annotations

import contextlib
import math
import random
import threading
import time
from typing import Any, Dict, Optional

from image_analogies_tpu.chaos import faults as _faults
from image_analogies_tpu.chaos.plan import ChaosPlan, SiteRule
from image_analogies_tpu.obs import metrics as _metrics
from image_analogies_tpu.obs import trace as _trace

# Disarmed fast path: ONE module bool guards everything below.
_ARMED = False
_PLAN: Optional[ChaosPlan] = None
_LOCK = threading.Lock()
_STATE: Dict[str, Dict[str, Any]] = {}  # site -> {visits, injected, rng}


def armed() -> bool:
    return _ARMED


def arm(plan: ChaosPlan) -> None:
    """Install ``plan`` and reset all site streams/counters."""
    global _ARMED, _PLAN
    with _LOCK:
        _PLAN = plan
        _STATE.clear()
        for name, _rule in plan.sites:
            _STATE[name] = {"visits": 0, "injected": 0,
                            "rng": random.Random(
                                _faults.stream_seed(plan.seed, name))}
        _ARMED = True


def disarm() -> None:
    global _ARMED, _PLAN
    with _LOCK:
        _ARMED = False
        _PLAN = None
        _STATE.clear()


@contextlib.contextmanager
def plan_scope(plan: ChaosPlan):
    """Arm ``plan`` for a with-block; always disarms on exit (drills must
    never leak armed state into the suite — the conftest fixture is the
    second belt)."""
    arm(plan)
    try:
        yield
    finally:
        disarm()


def snapshot() -> Dict[str, Dict[str, int]]:
    """Per-site {visits, injected} tallies of the armed (or last) plan."""
    with _LOCK:
        return {name: {"visits": st["visits"], "injected": st["injected"]}
                for name, st in _STATE.items()}


def injected_total() -> int:
    with _LOCK:
        return sum(st["injected"] for st in _STATE.values())


def plan_seed() -> Optional[int]:
    """Seed of the armed plan (None when disarmed) — call sites applying
    a ``corrupt`` directive use it so the damage is plan-deterministic."""
    plan = _PLAN
    return plan.seed if plan is not None else None


# z-score of the 99th percentile of the standard normal: with
# sigma = ln(p99/p50) / Z99, lognormal(ln(p50), sigma) has exactly the
# requested median and 99th percentile.
_Z99 = 2.3263478740408408


def _latency_s(name: str, rule: SiteRule) -> float:
    """Sleep length for a firing latency rule, in seconds.

    Fixed ``latency_ms`` by default; when the rule carries a lognormal
    spec (p50/p99 both set) the delay is drawn from the site's seeded
    stream — deterministic per (plan seed, site, visit sequence), so a
    replayed drill sleeps the same tail."""
    if not rule.latency_p50_ms:
        return rule.latency_ms / 1e3
    sigma = math.log(rule.latency_p99_ms / rule.latency_p50_ms) / _Z99
    with _LOCK:
        st = _STATE.get(name)
        if st is None:
            return rule.latency_ms / 1e3
        return st["rng"].lognormvariate(
            math.log(rule.latency_p50_ms), sigma) / 1e3


def _decide(name: str, rule: SiteRule) -> Optional[int]:
    """Take one visit at ``name``; returns the visit index when the rule
    fires, else None.  Single lock section: counter bump + draw."""
    with _LOCK:
        st = _STATE.get(name)
        if st is None:  # site visited but not in _STATE (plan replaced)
            return None
        visit = st["visits"]
        st["visits"] += 1
        if rule.max_faults and st["injected"] >= rule.max_faults:
            return None
        if rule.schedule:
            fire = visit in rule.schedule
        else:
            fire = rule.p > 0 and st["rng"].random() < rule.p
        if not fire:
            return None
        st["injected"] += 1
        return visit


def site(name: str, **ctx: Any) -> Optional[str]:
    """Injection site: no-op returning None when chaos is disarmed.

    Armed, consults the plan's rule for ``name``; when a fault fires it
    either raises (transient/oom/crash), sleeps (latency; with
    ``hang=True`` the sleep ends in a transient raise — a wedge that
    never completes), or returns a directive string (``"corrupt"``) the
    call site applies itself.  Every injection bumps ``chaos.injected``
    (+ per-site/kind counters) and emits a ``chaos_inject`` record into
    the active run log, so drills reconcile injections against the
    recovery counters they caused.
    """
    if not _ARMED:
        return None
    plan = _PLAN
    rule = plan.rule_for(name) if plan is not None else None
    if rule is None:
        return None
    visit = _decide(name, rule)
    if visit is None:
        return None
    _metrics.inc("chaos.injected")
    _metrics.inc(f"chaos.injected.{rule.kind}")
    _metrics.inc(f"chaos.site.{name}")
    _trace.emit_record({"event": "chaos_inject", "site": name,
                        "kind": rule.kind, "visit": visit,
                        **{k: v for k, v in ctx.items()
                           if isinstance(v, (str, int, float, bool))}})
    if rule.kind == "transient":
        raise _faults.ChaosTransient(
            f"chaos transient at {name} (visit {visit})")
    if rule.kind == "oom":
        raise _faults.oom_error(name, visit)
    if rule.kind == "latency":
        time.sleep(_latency_s(name, rule))
        if rule.hang:
            # the wedged op never completes: by the time this raise
            # unwinds, a watchdogged caller has already timed out and
            # moved on — the abandoned thread's error is swallowed there
            raise _faults.ChaosTransient(
                f"chaos hang released at {name} (visit {visit})")
        return None
    if rule.kind == "crash":
        raise _faults.WorkerCrash(
            f"chaos worker crash at {name} (visit {visit})")
    if rule.kind == "process_death":
        raise _faults.ProcessDeath(
            f"chaos process death at {name} (visit {visit})")
    return rule.kind  # "corrupt": directive for the call site
