"""image_analogies_tpu — a TPU-native (JAX/XLA/Pallas/pjit) Image Analogies framework.

Implements the full capability surface of the reference
(`rubychen0611/image-analogies-python`, Hertzmann et al., SIGGRAPH 2001 "Image
Analogies"): given a training pair A -> A' and a new image B, synthesize B' such
that A : A' :: B : B'.  One engine, several applications: artistic filters,
texture synthesis, texture-by-numbers, super-resolution, and (new here) batched
video analogies.

Architecture (see SURVEY.md for the layer map):

- ``ops/``      pure array ops: color (YIQ), Gaussian pyramid, neighborhood
  feature extraction (the shared semantic spec, NumPy + JAX twins), distance
  kernels, and the Pallas fused distance+argmin TPU kernel.
- ``backends/`` the pluggable ``Matcher`` seam (BASELINE.json north star): a
  NumPy/cKDTree CPU oracle and the JAX/Pallas TPU backend.  Only
  ``build_features()`` / ``best_match()`` / ``synthesize_level()`` cross it.
- ``models/``   the synthesis driver (coarse-to-fine loop) and application
  modes (filter, texture-by-numbers, super-res, texture synthesis, video).
- ``parallel/`` device-mesh utilities and the sharded patch-DB argmin
  (``lax.pmin`` + index all-reduce over the ICI mesh).
- ``utils/``    image I/O, checkpoint/resume, structured logging, SSIM eval.

The reference mount was empty at survey time (SURVEY.md §0); semantics are
pinned by the Hertzmann 2001 paper + BASELINE.json and locked by this package's
own CPU oracle + test suite.
"""

from image_analogies_tpu.config import AnalogyParams
from image_analogies_tpu.models.analogy import create_image_analogy

__version__ = "0.1.0"

__all__ = ["AnalogyParams", "create_image_analogy", "__version__"]
