"""`TraceSpec` — the replayable traffic model behind ``ia soak``.

One JSON artifact fixes an entire soak's request stream: Zipf style
popularity over the catalog (tenant skew), diurnal + flash-crowd
arrival shapes on top of the shared Poisson pacing machinery, a mixed
session population (one-shot, batch lanes, journaled resubmits) and
priority classes.  Everything is a pure function of the spec — same
spec ⇒ byte-identical request stream, locked by :meth:`stream_digest`
and the determinism test.

The arrival model here is THE arrival model: ``loadgen.arrival_schedule``
(the `--selftest` / drill / bench pacing) delegates to
:meth:`TraceSpec.arrivals`, so selftests and soaks can never drift onto
parallel traffic generators.

jax-free and serve-free at module scope (content generation borrows
``loadgen.make_load`` lazily), so ``ia soak --spec`` can validate a
spec without touching an accelerator.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import math
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

SESSION_KINDS = ("oneshot", "resubmit", "batch")
PRIORITY_NAMES = ("interactive", "standard", "background")

# Seed-stream offsets: content (make_load), pacing, and population draws
# must never share bytes — each derived stream gets its own salt.
PACE_SALT = 0x9E37       # shared with the historic arrival_schedule
POPULATION_SALT = 0x51ED


def _pairs(raw: Any, what: str) -> Tuple[Tuple[str, float], ...]:
    out = []
    for entry in raw:
        name, weight = entry[0], float(entry[1])
        if weight <= 0:
            raise ValueError(f"{what} weight for {name!r} must be > 0")
        out.append((str(name), weight))
    if not out:
        raise ValueError(f"{what} mix must not be empty")
    return tuple(out)


@dataclasses.dataclass(frozen=True)
class TraceSpec:
    """One soak's traffic, bounds, and fault shape — all from one seed.

    ``flash_crowds`` is a tuple of ``(t0, duration, mult)`` surge
    windows; ``diurnal_period_s``/``diurnal_amplitude`` superimpose a
    sinusoidal day-shape on the base rate (amplitude 0 = flat).
    ``sessions`` / ``priorities`` are weighted mixes drawn per request
    from the spec's own seeded stream.  ``deadline_ms`` is cycled per
    request (``None`` entries = undeadlined bulk).  The ``chaos`` dict
    is an inline :class:`~image_analogies_tpu.chaos.plan.ChaosPlan`
    document armed for the whole run (``None`` = the driver's default
    plan); ``kill_every`` delivers a driver-side worker SIGKILL after
    every N-th submitted request.  ``p999_bound_ms`` and ``audit`` are
    the invariant-gate knobs: the DDSketch p99.9 latency ceiling and
    the size of the seeded bit-identity audit subset.
    """

    name: str = "soak"
    seed: int = 0
    requests: int = 40
    shapes: Tuple[Tuple[int, int], ...] = ((12, 12),)
    zipf: Optional[float] = 1.1
    styles: int = 3
    base_rps: float = 30.0
    flash_crowds: Tuple[Tuple[float, float, float], ...] = ()
    diurnal_period_s: float = 0.0
    diurnal_amplitude: float = 0.0
    deadline_ms: Tuple[Optional[float], ...] = ()
    sessions: Tuple[Tuple[str, float], ...] = (
        ("oneshot", 0.7), ("resubmit", 0.2), ("batch", 0.1))
    priorities: Tuple[Tuple[str, float], ...] = (
        ("interactive", 0.3), ("standard", 0.6), ("background", 0.1))
    chaos: Optional[Dict[str, Any]] = None
    kill_every: int = 0
    p999_bound_ms: float = 60_000.0
    audit: int = 8

    def __post_init__(self):
        if self.requests < 0:
            raise ValueError("requests must be >= 0")
        if not self.shapes:
            raise ValueError("shapes must not be empty")
        if self.zipf is not None and self.zipf < 0:
            raise ValueError("zipf skew must be >= 0")
        if self.styles < 0:
            raise ValueError("styles must be >= 0")
        if self.base_rps <= 0:
            raise ValueError("base_rps must be > 0")
        for t0, duration, mult in self.flash_crowds:
            if t0 < 0 or duration <= 0 or mult < 1:
                raise ValueError(
                    "flash crowd needs t0 >= 0, duration > 0, mult >= 1")
        if self.diurnal_period_s < 0:
            raise ValueError("diurnal_period_s must be >= 0")
        if not 0.0 <= self.diurnal_amplitude < 1.0:
            raise ValueError("diurnal_amplitude must be in [0, 1)")
        for kind, _w in _pairs(self.sessions, "session"):
            if kind not in SESSION_KINDS:
                raise ValueError(f"unknown session kind {kind!r}; "
                                 f"expected one of {SESSION_KINDS}")
        for pri, _w in _pairs(self.priorities, "priority"):
            if pri not in PRIORITY_NAMES:
                raise ValueError(f"unknown priority {pri!r}; "
                                 f"expected one of {PRIORITY_NAMES}")
        if self.kill_every < 0 or self.audit < 0:
            raise ValueError("kill_every/audit must be >= 0")
        if self.p999_bound_ms <= 0:
            raise ValueError("p999_bound_ms must be > 0")

    # ------------------------------------------------------------ codec

    def to_dict(self) -> Dict[str, Any]:
        doc = dataclasses.asdict(self)
        doc["shapes"] = [list(s) for s in self.shapes]
        doc["flash_crowds"] = [list(fc) for fc in self.flash_crowds]
        doc["deadline_ms"] = list(self.deadline_ms)
        doc["sessions"] = [list(kv) for kv in self.sessions]
        doc["priorities"] = [list(kv) for kv in self.priorities]
        return doc

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "TraceSpec":
        if not isinstance(d, dict):
            raise ValueError("trace spec must be a JSON object")
        kw = dict(d)
        unknown = set(kw) - {f.name for f in dataclasses.fields(TraceSpec)}
        if unknown:
            raise ValueError(f"unknown trace spec field(s) "
                             f"{sorted(unknown)}")
        if "shapes" in kw:
            kw["shapes"] = tuple((int(h), int(w)) for h, w in kw["shapes"])
        if "flash_crowds" in kw:
            kw["flash_crowds"] = tuple(
                (float(t0), float(du), float(m))
                for t0, du, m in kw["flash_crowds"])
        if "deadline_ms" in kw:
            kw["deadline_ms"] = tuple(
                None if v is None else float(v) for v in kw["deadline_ms"])
        if "sessions" in kw:
            kw["sessions"] = _pairs(kw["sessions"], "session")
        if "priorities" in kw:
            kw["priorities"] = _pairs(kw["priorities"], "priority")
        return TraceSpec(**kw)

    @staticmethod
    def from_json(blob: str) -> "TraceSpec":
        return TraceSpec.from_dict(json.loads(blob))

    @staticmethod
    def load(path: str) -> "TraceSpec":
        with open(path) as f:
            return TraceSpec.from_dict(json.load(f))

    @staticmethod
    def from_flags(n: int, seed: int, *,
                   shapes: Sequence[Tuple[int, int]],
                   zipf: Optional[float] = None, styles: int = 0,
                   flash_crowd: Optional[Dict[str, float]] = None,
                   deadline_ms: Optional[Any] = None,
                   base_rps: float = 50.0) -> "TraceSpec":
        """The `--selftest` flag surface as a spec — the one arrival
        model selftests and soaks share (`--zipf/--styles`,
        `--flash-crowd T0,DUR,MULT`, scalar-or-cycled `--deadline-ms`)."""
        if deadline_ms is None:
            deadlines: Tuple[Optional[float], ...] = ()
        elif isinstance(deadline_ms, (int, float)):
            deadlines = (float(deadline_ms),)
        else:
            deadlines = tuple(None if v is None else float(v)
                              for v in deadline_ms)
        crowds = ()
        if flash_crowd:
            crowds = ((float(flash_crowd["t0"]),
                       float(flash_crowd["duration"]),
                       float(flash_crowd["mult"])),)
        return TraceSpec(
            name="flags", seed=int(seed), requests=max(0, int(n)),
            shapes=tuple((int(h), int(w)) for h, w in shapes),
            zipf=None if zipf is None else float(zipf),
            styles=int(styles), base_rps=float(base_rps),
            flash_crowds=crowds, deadline_ms=deadlines)

    # --------------------------------------------------------- arrivals

    def rate_at(self, t: float) -> float:
        """Instantaneous arrival rate (req/s) at run-offset ``t``: the
        base rate, shaped by the diurnal sinusoid, multiplied by every
        surge window covering ``t``."""
        rate = self.base_rps
        if self.diurnal_period_s > 0:
            rate *= 1.0 + self.diurnal_amplitude * math.sin(
                2.0 * math.pi * t / self.diurnal_period_s)
        for t0, duration, mult in self.flash_crowds:
            if t0 <= t < t0 + duration:
                rate *= mult
        return max(rate, 1e-9)

    def arrivals(self) -> List[float]:
        """Deterministic Poisson arrival offsets (seconds from run
        start) under the shaped rate.  One seed fixes the whole
        schedule — drills, selftests, and soaks replay the exact same
        traffic."""
        rng = np.random.RandomState(
            (int(self.seed) + PACE_SALT) & 0x7FFFFFFF)
        t = 0.0
        out: List[float] = []
        for _ in range(self.requests):
            t += float(rng.exponential(1.0 / self.rate_at(t)))
            out.append(t)
        return out

    # ------------------------------------------------------ population

    def deadline_for(self, i: int) -> Optional[float]:
        """Request ``i``'s deadline in SECONDS (None = undeadlined) —
        the cycled mixed-deadline load EDF ordering exists for."""
        if not self.deadline_ms:
            return None
        v = self.deadline_ms[i % len(self.deadline_ms)]
        return None if v is None else v / 1e3

    def idem_for(self, i: int) -> str:
        """Stable idempotency key: the handle journals, resubmits, and
        ``ia why`` agree on."""
        return f"{self.name or 'soak'}-{self.seed}-{i}"

    def build_load(self) -> List[Dict[str, Any]]:
        """The full request population: content planes from the shared
        ``loadgen.make_load`` draw (Zipf over styles when armed),
        decorated with the per-request session kind, priority class,
        deadline, and idempotency key — all from the spec's own seeded
        streams."""
        from image_analogies_tpu.serve import loadgen

        load = loadgen.make_load(self.requests, self.shapes, self.seed,
                                 zipf=self.zipf, styles=self.styles)
        rng = np.random.RandomState(
            (int(self.seed) + POPULATION_SALT) & 0x7FFFFFFF)
        s_names = [k for k, _ in self.sessions]
        s_probs = np.array([w for _, w in self.sessions], dtype=np.float64)
        s_probs /= s_probs.sum()
        p_names = [k for k, _ in self.priorities]
        p_probs = np.array([w for _, w in self.priorities],
                           dtype=np.float64)
        p_probs /= p_probs.sum()
        s_picks = rng.choice(len(s_names), size=max(self.requests, 1),
                             p=s_probs)
        p_picks = rng.choice(len(p_names), size=max(self.requests, 1),
                             p=p_probs)
        for item in load:
            i = item["index"]
            item["session"] = s_names[int(s_picks[i])]
            item["priority"] = p_names[int(p_picks[i])]
            item["deadline_s"] = self.deadline_for(i)
            item["idem"] = self.idem_for(i)
        return load

    # ----------------------------------------------------------- digest

    def stream_digest(self) -> str:
        """sha256 over the complete request stream — every content
        byte, every population label, every arrival offset.  Two specs
        produce the same digest iff they produce the same traffic;
        the determinism test locks replays to this."""
        h = hashlib.sha256()
        h.update(json.dumps(self.to_dict(), sort_keys=True,
                            default=str).encode())
        sched = self.arrivals()
        for item, t in zip(self.build_load(), sched):
            head = (f"{item['index']}|{item.get('style', '')}"
                    f"|{item['session']}|{item['priority']}"
                    f"|{item['deadline_s']}|{item['idem']}"
                    f"|{float(t).hex()}|")
            h.update(head.encode())
            for key in ("a", "ap", "b"):
                arr = np.ascontiguousarray(item[key])
                h.update(str(arr.shape).encode())
                h.update(arr.tobytes())
        return h.hexdigest()


def smoke_spec(seed: int = 7) -> TraceSpec:
    """The built-in tier-1 smoke: ~20-30 s on CPU.  Small but complete —
    Zipf tenant skew, a diurnal ripple under one flash crowd, every
    session kind, mixed deadlines, two driver kills, and the default
    chaos plan (armed by the driver) covering worker death recovery,
    tier eviction, artifact tearing, and hop latency."""
    return TraceSpec(
        name="smoke", seed=seed, requests=24, shapes=((12, 12),),
        zipf=1.1, styles=3, base_rps=30.0,
        flash_crowds=((0.2, 0.6, 8.0),),
        diurnal_period_s=4.0, diurnal_amplitude=0.3,
        deadline_ms=(None, None, 30_000.0),
        kill_every=9, p999_bound_ms=60_000.0, audit=6)


def full_spec(seed: int = 7) -> TraceSpec:
    """The bench-profile soak: the same composite shape at duration —
    hundreds of requests, two surges over a diurnal cycle, periodic
    kills throughout.  Emits the ``soak_p999_ms`` / ``soak_loss``
    headlines ``ia bench --check`` records."""
    return TraceSpec(
        name="full", seed=seed, requests=240, shapes=((16, 16),),
        zipf=1.1, styles=6, base_rps=40.0,
        flash_crowds=((1.0, 2.0, 10.0), (5.0, 1.5, 6.0)),
        diurnal_period_s=8.0, diurnal_amplitude=0.4,
        deadline_ms=(None, None, None, 60_000.0),
        kill_every=48, p999_bound_ms=120_000.0, audit=16)


def trace_plan(n: int, shapes: Sequence[Tuple[int, int]], seed: int, *,
               zipf: Optional[float] = None, styles: int = 0,
               flash_crowd: Optional[Dict[str, float]] = None,
               deadline_ms: Optional[Any] = None
               ) -> Tuple[List[Dict[str, Any]], Optional[List[float]],
                          Callable[[int], Optional[float]]]:
    """(load, schedule, deadline_fn) for the `--selftest` flag surface —
    the single entry both ``loadgen.selftest`` paths consume, so the
    selftests and the soak share ONE arrival model."""
    spec = TraceSpec.from_flags(n, seed, shapes=shapes, zipf=zipf,
                                styles=styles, flash_crowd=flash_crowd,
                                deadline_ms=deadline_ms)
    sched = spec.arrivals() if flash_crowd else None
    return spec.build_load(), sched, spec.deadline_for
