"""Trace-driven soak harness (ISSUE 20 / ROADMAP item 5).

Every drill in chaos/ is seconds long and hand-shaped; a service for
millions of users is validated against *traffic*.  This package is the
driver that ROADMAP item 5 left open once the PR 17 witness layer
(honest DDSketch quantiles, ceiling trend watchdogs, durable telemetry
archive) landed:

- :mod:`soak.trace`      — :class:`TraceSpec`: a JSON artifact (seed,
  Zipf style popularity, diurnal + flash-crowd arrival shapes, mixed
  session kinds, priority classes) that is fully replayable from one
  seed — same spec ⇒ byte-identical request stream, locked by digest.
- :mod:`soak.driver`     — runs a spec against an autoscaling fleet
  with a chaos plan armed for the whole run (worker SIGKILLs, catalog
  tier evictions, torn telemetry artifacts, injected hop latency)
  while the PR 17 witnesses sample.
- :mod:`soak.invariants` — the end-of-run gate for what only duration
  proves: zero-loss accounting reconciled against every worker journal
  (``journal.reconstruct`` names the culprit), bit-identity of a
  seeded audit subset vs the sequential baseline, the DDSketch p99.9
  bound, zero ``obs.ceiling.*`` alarms, and journal growth bounded
  under autocompaction.

``ia soak --spec FILE`` is the CLI; the seeded smoke spec rides tier-1
and the full profile emits the ``soak_p999_ms`` / ``soak_loss``
headlines ``ia bench --check`` records.
"""

from image_analogies_tpu.soak.trace import TraceSpec  # noqa: F401
