"""The end-of-run soak gate: what only duration proves.

Each invariant is a pure function of the driver's fact document, so a
test can feed synthetic facts and the CLI can re-render a stored run.
Every failing verdict carries a ``culprit`` wherever one exists — an
idempotency key ``ia why <idem> --journal-root <dir>`` can reconstruct,
so a red gate is the START of a debugging session, not the end of one.

The gate is deliberately inequality-based where the drill runner's
reconciliation is strict: a soak overlaps recoveries (a crash requeue
re-visits the same sites), so exact per-site equalities that hold in a
three-second drill are replaced by "at least the injected evidence"
bounds that stay deterministic across schedulers.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from image_analogies_tpu.chaos.plan import ChaosPlan
from image_analogies_tpu.soak.trace import TraceSpec

# Rejection reasons that are VERDICTS about a request (admission control
# doing its job) rather than lost work: they complete the accounting.
_SHED_REASONS = ("quota", "queue_full", "breaker_open", "circuit_open")


def p999_ms(facts: Dict[str, Any]) -> Optional[float]:
    """The DDSketch p99.9 of answered-request latency (None when
    nothing answered) — the honest tail the bench headline records."""
    from image_analogies_tpu.obs import quantiles as obs_quantiles

    lats = facts.get("latencies_ms") or []
    if not lats:
        return None
    sk = obs_quantiles.QuantileSketch()
    for v in lats:
        sk.observe(float(v))
    return round(float(sk.quantile(0.999)), 3)


def lost(facts: Dict[str, Any]) -> int:
    """Submitted requests that neither answered nor shed cleanly — the
    ``soak_loss`` headline.  Hard rejections (poison, worker_crash,
    crash_loop), raw future errors, and silently vanished submits all
    count: lost work is lost however it was labelled."""
    rejected = facts.get("rejected") or {}
    shed = sum(n for r, n in rejected.items() if r in _SHED_REASONS)
    return max(0, facts.get("submitted", 0)
               - facts.get("answered", 0) - shed)


def _verdict(name: str, ok: bool, detail: str,
             culprit: Optional[str] = None) -> Dict[str, Any]:
    v = {"name": name, "ok": bool(ok), "detail": detail}
    if culprit:
        v["culprit"] = culprit
    return v


def evaluate(spec: TraceSpec, plan: ChaosPlan,
             facts: Dict[str, Any]) -> List[Dict[str, Any]]:
    """All gate verdicts, in reporting order."""
    out: List[Dict[str, Any]] = []
    counters = facts.get("counters") or {}
    rejected = facts.get("rejected") or {}
    errors = facts.get("errors") or {}
    journals = facts.get("journals") or {}
    sites = {name: st.get("injected", 0)
             for name, st in (facts.get("sites") or {}).items()}

    # 1. zero-loss accounting: every submit resolved to exactly one
    # outcome; hard rejections (poison, worker_crash, crash_loop) and
    # raw future errors are lost work even though they "resolved".
    shed = sum(n for r, n in rejected.items() if r in _SHED_REASONS)
    hard = {r: n for r, n in rejected.items() if r not in _SHED_REASONS}
    total = facts.get("answered", 0) + shed + sum(hard.values()) \
        + len(errors)
    culprit = None
    if errors:
        culprit = spec.idem_for(sorted(errors, key=int)[0])
    out.append(_verdict(
        "zero_loss",
        total == facts.get("submitted", 0) and not hard and not errors,
        f"answered={facts.get('answered', 0)} shed={shed} "
        f"hard={hard or 0} errors={len(errors)} "
        f"of submitted={facts.get('submitted', 0)}",
        culprit))

    # 2. no poisoned keys, reconciled across handoffs against every
    # worker journal (the culprit reconstructs via `ia why`).
    poisoned = sorted({idem for doc in journals.values()
                       for idem in doc.get("poisoned") or []})
    out.append(_verdict(
        "no_poison", not poisoned,
        f"{len(poisoned)} poisoned key(s) across "
        f"{len(journals)} worker journal(s)",
        poisoned[0] if poisoned else None))

    # 3. bit-identity of the seeded audit subset vs the sequential
    # baseline (degraded answers are valid; mismatches are not).
    audit = facts.get("audit") or {}
    mism = sorted(int(i) for i, st in audit.items() if st == "mismatch")
    checked = sum(1 for st in audit.values() if st == "ok")
    out.append(_verdict(
        "bit_identity", not mism,
        f"{checked}/{len(audit)} audited answers bit-identical "
        f"({len(mism)} mismatched)",
        spec.idem_for(mism[0]) if mism else None))

    # 4. journaled resubmits dedupe to the first answer's exact bytes.
    out.append(_verdict(
        "resubmit_dedupe", bool(facts.get("resubmit_identical", True)),
        f"{facts.get('resubmits', 0)} resubmit(s) answered from the "
        "journal"))

    # 5. DDSketch p99.9 latency bound.
    p999 = p999_ms(facts)
    out.append(_verdict(
        "p999_bound",
        p999 is not None and p999 <= spec.p999_bound_ms,
        f"p99.9={p999}ms bound={spec.p999_bound_ms}ms "
        f"({len(facts.get('latencies_ms') or [])} samples)"))

    # 6. the run ended with ZERO resource-ceiling alarms.
    alarms = {k: v for k, v in counters.items()
              if k.startswith("obs.ceiling.")}
    out.append(_verdict(
        "no_ceiling_alarms", not alarms,
        f"ceiling counters: {alarms or 'none'}"))

    # 7. journals bounded under compaction: every seeded kill's replace
    # ran the autocompact decision (multi-segment corpses compacted,
    # already-bounded corpses skipped), a worker killed more than once
    # demonstrably compacted at least once, and each journal compacts
    # offline to a single segment at end of run.
    kills = facts.get("kills") or []
    repeat = (len(kills)
              - len({k.get("worker") for k in kills})) if kills else 0
    autoc = counters.get("serve.journal.autocompact", 0)
    skipped = counters.get("serve.journal.autocompact_skipped", 0)
    fat = {wid: doc.get("segments") for wid, doc in journals.items()
           if doc.get("segments", 0) > 1}
    failed_compact = {wid: doc["compacted"]["error"]
                      for wid, doc in journals.items()
                      if isinstance(doc.get("compacted"), dict)
                      and "error" in doc["compacted"]}
    out.append(_verdict(
        "journal_bounded",
        autoc + skipped >= len(kills)
        and (autoc >= 1 if repeat else True)
        and not fat and not failed_compact,
        f"autocompact={autoc} skipped={skipped} kills={len(kills)} "
        f"(repeat={repeat}) post-run segments>1: {fat or 'none'} "
        f"compact errors: {failed_compact or 'none'}"))

    # 8. chaos stayed armed the whole run: every planned required site
    # observed at least one injection, and every driver kill resolved
    # to a journal handoff.
    from image_analogies_tpu.soak import driver as soak_driver

    planned = {name for name, _ in plan.sites}
    required = [s for s in soak_driver.REQUIRED_SITES if s in planned]
    silent = [s for s in required if not sites.get(s)]
    want_kills = bool(spec.kill_every
                      and spec.requests > spec.kill_every)
    handoffs = facts.get("handoffs") or []
    out.append(_verdict(
        "chaos_armed",
        not silent and sum(sites.values()) >= 1
        and (not want_kills or (kills and len(handoffs) >= len(kills))),
        f"injections={sites} kills={len(kills)} "
        f"handoffs={len(handoffs)} silent_sites={silent or 'none'}"))

    # 9. every injection reconciles against its recovery evidence
    # (inequalities — overlapping recoveries re-visit sites).
    recon: List[str] = []
    tier = sites.get("devcache.tier", 0)
    if tier:
        evicted = counters.get("catalog.chaos_evictions", 0)
        refilled = (counters.get("catalog.disk.hits", 0)
                    + counters.get("catalog.builds", 0))
        if evicted != tier:
            recon.append(f"catalog.chaos_evictions={evicted} != "
                         f"{tier} injected")
        if refilled < evicted:
            recon.append(f"{evicted} evictions but only {refilled} "
                         "disk-hit/rebuild recoveries")
    if sites.get("archive.append", 0):
        q = facts.get("archive", {}).get("quarantined", 0) \
            + counters.get("obs.archive.append_errors", 0)
        if q < 1:
            recon.append("archive.append fired but the reader "
                         "quarantined nothing")
    lvl = sites.get("level.dispatch", 0)
    if lvl and counters.get("level_retry", 0) < lvl:
        recon.append(f"level_retry={counters.get('level_retry', 0)} < "
                     f"{lvl} injected transients")
    out.append(_verdict(
        "chaos_reconciled", not recon,
        "; ".join(recon) or "all injections matched by recovery "
        "evidence"))
    return out


def render(result: Dict[str, Any]) -> str:
    """Human gate report for ``ia soak`` (one line per invariant)."""
    lines = ["ia soak: {} ({} requests, wall {}s)".format(
        "PASS" if result.get("ok") else "FAIL",
        result.get("facts", {}).get("submitted", 0),
        result.get("facts", {}).get("wall_s", "?"))]
    for v in result.get("verdicts", []):
        mark = "ok " if v["ok"] else "FAIL"
        line = f"  [{mark}] {v['name']}: {v['detail']}"
        if v.get("culprit"):
            line += f"  (culprit: ia why {v['culprit']})"
        lines.append(line)
    lines.append(f"  p999_ms={result.get('p999_ms')} "
                 f"loss={result.get('loss')}")
    return "\n".join(lines) + "\n"
