"""The soak driver: one :class:`TraceSpec` against a live fleet, chaos
armed the entire run.

Where a chaos drill proves ONE recovery path in seconds, the soak
replays a whole traffic trace — Zipf tenant skew, diurnal ripple,
flash crowds, mixed sessions and priorities — against an autoscaling
fleet while the fault plane stays armed throughout: periodic worker
kills (journal handoffs + autocompaction), catalog tier evictions
mid-request, torn telemetry archive segments, injected hop latency,
and transient dispatch faults the level retries must keep absorbing.
The PR 17 witnesses (timeline, ceilings trend watchdogs, durable
archive) sample the whole time via the fleet health loop.

The driver only *collects facts*; the verdicts live in
:mod:`soak.invariants` so the gate is a pure function a test can feed
synthetic facts.  Everything here is seeded — two runs of the same
spec submit byte-identical streams and reach the same verdicts.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import tempfile
import time
from typing import Any, Dict, List, Optional

import numpy as np

from image_analogies_tpu.chaos import drills, inject
from image_analogies_tpu.chaos.plan import ChaosPlan, SiteRule
from image_analogies_tpu.soak import invariants as soak_invariants
from image_analogies_tpu.soak.trace import TraceSpec

AUDIT_SALT = 0xA0D1  # seeded audit-subset draw; disjoint from trace salts

# Sites every default soak must observe firing (the acceptance gate's
# "chaos armed throughout" witness list).  Worker kills are driver-side
# SIGKILLs, counted separately via journal handoffs.
REQUIRED_SITES = ("devcache.tier", "archive.append")

# Trend-watchdog thresholds for a soak (bytes/sec slope over a full
# window).  The fleet defaults are tuned for long-lived processes; a
# soak front-loads a legitimate ramp (jax init, catalog builds, journal
# payload spills at surge rate) that would trip them in the first
# seconds.  These still catch pathological runaway growth, and the
# ABSOLUTE journal bound is invariant 7's job (compacts to one
# segment), not the trend watchdog's.
SOAK_THRESHOLDS = {
    "proc.rss_bytes": 256 << 20,
    "devcache.bytes": 64 << 20,
    "journal.bytes": 16 << 20,
    "archive.bytes": 16 << 20,
}


def default_plan(seed: int) -> ChaosPlan:
    """The standing soak fault shape: every injection must be one the
    fleet recovers from WITHOUT changing answered bytes.

    - ``level.dispatch`` transients — absorbed by level retries.
    - ``devcache.tier`` corrupt — mid-request catalog eviction; the
      directive never raises, recovery is the tier fall-through.
    - ``archive.append`` corrupt — tears a sealed telemetry segment
      after a successful-looking write; the offline reader quarantines.
    - ``router.forward`` latency — injected hop delay, self-recovering.
    """
    return ChaosPlan(
        seed=seed,
        sites=(
            ("level.dispatch", SiteRule(kind="transient", p=0.05,
                                        max_faults=6)),
            ("devcache.tier", SiteRule(kind="corrupt",
                                       schedule=(1, 5, 11))),
            ("archive.append", SiteRule(kind="corrupt", schedule=(0,))),
            ("router.forward", SiteRule(kind="latency", p=0.1,
                                        latency_ms=15.0, max_faults=8)),
        ),
        name=f"soak-default-{seed}").validate_sites()


def audit_indices(spec: TraceSpec) -> List[int]:
    """The seeded bit-identity audit subset: ``spec.audit`` request
    indices drawn from the spec's own seed (disjoint salt), so replays
    audit the same requests."""
    if spec.requests == 0 or spec.audit == 0:
        return []
    rng = np.random.RandomState((int(spec.seed) + AUDIT_SALT) & 0x7FFFFFFF)
    k = min(spec.audit, spec.requests)
    return sorted(int(i) for i in
                  rng.choice(spec.requests, size=k, replace=False))


@contextlib.contextmanager
def _rundir(workdir: Optional[str]):
    """The run's scratch root.  An explicit ``workdir`` PERSISTS (so a
    red gate's journals/archive stay on disk for ``ia why`` /
    ``ia archive diff``); without one, a tempdir is swept."""
    if workdir:
        path = os.path.abspath(workdir)
        os.makedirs(path, exist_ok=True)
        yield path
    else:
        with tempfile.TemporaryDirectory() as tmp:
            yield tmp


def _serve_config(params):
    """Soak per-worker config: the drill template with a deeper crash
    budget (driver kills land mid-flight; requeues must absorb every
    seeded kill without poisoning a key)."""
    cfg = drills.serve_config(workers=1, max_batch=4, crash_requeues=3)
    return dataclasses.replace(cfg, params=params, request_retries=3)


def run(spec: TraceSpec, *, workdir: Optional[str] = None,
        plan: Optional[ChaosPlan] = None) -> Dict[str, Any]:
    """Execute one soak; returns ``{"facts", "verdicts", "ok", ...}``.

    ``plan`` overrides the fault shape (tests use hostile plans to
    prove the gate fails loudly); otherwise ``spec.chaos`` (validated)
    or :func:`default_plan`.
    """
    from image_analogies_tpu.catalog import tiers as catalog_tiers
    from image_analogies_tpu.obs import archive as obs_archive
    from image_analogies_tpu.obs import ceilings as obs_ceilings
    from image_analogies_tpu.obs import trace as obs_trace
    from image_analogies_tpu.serve import journal as serve_journal
    from image_analogies_tpu.serve import policy as serve_policy
    from image_analogies_tpu.serve.fleet import Fleet
    from image_analogies_tpu.serve.types import FleetConfig, Rejected

    if plan is None:
        if spec.chaos is not None:
            plan = ChaosPlan.from_dict(spec.chaos).validate_sites()
        else:
            plan = default_plan(spec.seed)

    load = spec.build_load()
    sched = spec.arrivals()
    audit = audit_indices(spec)
    t_start = time.perf_counter()

    catalog_tiers.clear()
    old_archive_env = os.environ.get("IA_ARCHIVE_DIR")
    # Pre-arm the ceilings plane with soak thresholds; the fleet's own
    # arm() joins this monitor instead of installing the fleet-default
    # one, so the health loop trends against soak-scale slopes.
    obs_ceilings.arm(monitor=obs_ceilings.CeilingMonitor(
        thresholds=SOAK_THRESHOLDS))
    try:
        with _rundir(workdir) as tmp:
            archive_root = os.path.join(tmp, "archive")
            journal_root = os.path.join(tmp, "journals")
            params = drills.catalog_params(
                os.path.join(tmp, "catalog")).replace(level_retries=3)
            cfg = _serve_config(params)
            policy = serve_policy.ControlPolicy(
                min_workers=1, max_workers=3, queue_high=2.0,
                queue_low=0.5, scale_up_windows=1, scale_down_windows=2,
                scale_up_cooldown_s=0.1, scale_down_cooldown_s=0.1)
            fcfg = FleetConfig(
                serve=cfg, size=3, vnodes=16, journal_root=journal_root,
                health_interval_s=0.03, death_checks=2,
                backoff_s=0.01, backoff_cap_s=0.05,
                crash_loop_threshold=0,  # seeded kills always respawn
                policy=policy)
            os.environ["IA_ARCHIVE_DIR"] = archive_root

            answered: Dict[int, Any] = {}
            rejected: Dict[str, int] = {}
            errors: Dict[int, str] = {}
            resubmit_hits = 0
            resubmit_identical = True
            kills: List[Dict[str, Any]] = []
            with obs_trace.run_scope(cfg.params) as ctx:
                # Sequential baseline for the audit subset BEFORE chaos
                # arms — this also seals the catalog tiers the armed
                # run's evictions will fall through.
                baseline = {i: drills.run_image(
                    load[i]["a"], load[i]["ap"], load[i]["b"], cfg.params)
                    for i in audit}
                inject.arm(plan)
                try:
                    with Fleet(fcfg) as fl:
                        futures: Dict[int, Any] = {}
                        t0 = time.perf_counter()
                        for item in load:
                            i = item["index"]
                            # batch sessions coalesce: no pacing wait,
                            # they pile onto the worker's batch lanes
                            if item["session"] != "batch":
                                delay = sched[i] - (time.perf_counter()
                                                    - t0)
                                if delay > 0:
                                    time.sleep(delay)
                            if (spec.kill_every
                                    and i and i % spec.kill_every == 0):
                                victims = sorted(fl.workers)
                                wid = victims[len(kills) % len(victims)]
                                fl.workers[wid].kill()
                                kills.append({"worker": wid, "at": i})
                                obs_trace.emit_record(
                                    {"event": "soak_kill", "worker": wid,
                                     "request": i})
                                # witness tick at the fault: the armed
                                # archive seals a timeline doc here
                                obs_archive.sample(force=True)
                            try:
                                futures[i] = fl.submit(
                                    item["a"], item["ap"], item["b"],
                                    deadline_s=item["deadline_s"],
                                    idempotency_key=item["idem"],
                                    priority=serve_policy.PRIORITY_CLASSES[
                                        item["priority"]])
                            except Rejected as exc:
                                rejected[exc.reason] = \
                                    rejected.get(exc.reason, 0) + 1
                        for i, fut in sorted(futures.items()):
                            try:
                                answered[i] = fut.result(timeout=120)
                            except Rejected as exc:
                                rejected[exc.reason] = \
                                    rejected.get(exc.reason, 0) + 1
                            except BaseException as exc:  # noqa: BLE001
                                errors[i] = type(exc).__name__
                        # journaled resubmits: the dedupe plane must
                        # answer each resubmitted key from its journal,
                        # byte-identical to the first answer
                        for item in load:
                            i = item["index"]
                            if item["session"] != "resubmit" \
                                    or i not in answered:
                                continue
                            try:
                                again = fl.submit(
                                    item["a"], item["ap"], item["b"],
                                    idempotency_key=item["idem"],
                                    priority=serve_policy.PRIORITY_CLASSES[
                                        item["priority"]]).result(
                                            timeout=120)
                            except BaseException:  # noqa: BLE001
                                resubmit_identical = False
                                continue
                            resubmit_hits += 1
                            if not np.array_equal(again.bp,
                                                  answered[i].bp):
                                resubmit_identical = False
                        # every seeded kill must resolve to a handoff
                        # before the fleet retires
                        end = time.monotonic() + 60.0
                        while (len(fl.handoffs) < len(kills)
                               and time.monotonic() < end):
                            time.sleep(0.02)
                        obs_archive.sample(force=True)
                        handoffs = list(fl.handoffs)
                        scale_events = list(fl.control.events)
                        final_size = len(fl.workers)
                        snap = inject.snapshot()
                finally:
                    inject.disarm()
                # Post-mortem, still inside the obs scope so recovery
                # counters land in ctx: the archive reader quarantines
                # torn segments; each worker journal must compact
                # offline to one bounded segment.
                archive = obs_archive.TelemetryArchive(archive_root)
                archive_replay = archive.replay()
                archive_stats = archive.stats()
                journals: Dict[str, Dict[str, Any]] = {}
                if os.path.isdir(journal_root):
                    for wid in sorted(os.listdir(journal_root)):
                        jdir = os.path.join(journal_root, wid)
                        if not os.path.isdir(jdir) or wid == "payloads":
                            continue
                        j = serve_journal.RequestJournal(jdir)
                        try:
                            compacted: Optional[Dict[str, Any]] = \
                                j.compact()
                        except (RuntimeError, OSError) as exc:
                            compacted = {"error": str(exc)}
                        doc = j.inspect()
                        doc["compacted"] = compacted
                        journals[wid] = doc
                counters = dict(ctx.registry.snapshot()["counters"])

            facts = {
                "spec": spec.to_dict(),
                "plan": plan.to_dict(),
                "submitted": spec.requests,
                "answered": len(answered),
                "rejected": dict(sorted(rejected.items())),
                "errors": errors,
                "degraded": sum(1 for r in answered.values()
                                if r.degraded is not None),
                "resubmits": resubmit_hits,
                "resubmit_identical": resubmit_identical,
                "kills": kills,
                "handoffs": handoffs,
                "scale_events": len(scale_events),
                "final_size": final_size,
                # per-index audit status: only a byte mismatch on a
                # full-fidelity answer is a violation — degraded or
                # unanswered (rejected/lost) indices are judged by the
                # accounting invariants, not this one
                "audit": {
                    i: ("unanswered" if i not in answered
                        else "degraded"
                        if answered[i].degraded is not None
                        else "ok"
                        if np.array_equal(answered[i].bp, baseline[i])
                        else "mismatch")
                    for i in audit},
                "latencies_ms": sorted(
                    round(float(r.total_ms), 3)
                    for r in answered.values()),
                "sites": snap,
                "archive": {
                    "kinds": dict(archive_replay.get("kinds") or {}),
                    "quarantined": int(
                        archive_stats.get("quarantined", 0)),
                    "bytes": int(archive_stats.get("bytes", 0)),
                },
                "journals": journals,
                "journal_root": journal_root if workdir else None,
                "archive_root": archive_root if workdir else None,
                "counters": counters,
                "wall_s": round(time.perf_counter() - t_start, 3),
            }
    finally:
        obs_ceilings.disarm()
        if old_archive_env is None:
            os.environ.pop("IA_ARCHIVE_DIR", None)
        else:
            os.environ["IA_ARCHIVE_DIR"] = old_archive_env
        catalog_tiers.clear()
        catalog_tiers.configure(None)

    verdicts = soak_invariants.evaluate(spec, plan, facts)
    return {
        "workload": "soak",
        "facts": facts,
        "verdicts": verdicts,
        "ok": all(v["ok"] for v in verdicts),
        "p999_ms": soak_invariants.p999_ms(facts),
        "loss": soak_invariants.lost(facts),
    }
