"""TPU backend: JAX/XLA on-device synthesis (BASELINE.json:5 north star).

Design (SURVEY.md §7 steps 4-6):

- Feature building is the JAX twin of the shared spec (`build_features_jax`),
  one fused XLA program per level — no host round-trips.
- The within-level raster scan runs ON DEVICE inside a single jitted
  `lax.fori_loop` carrying (B' plane, source map): host dispatches cost
  ~100ms each over the PJRT tunnel, so only the coarse-to-fine level loop
  stays in Python (SURVEY.md §7 step 5).
- All scan functions are MODULE-LEVEL jits over a pytree-registered
  `TpuLevelDB`, so each (shape, strategy) compiles once per process and is
  reused across levels/calls — per-call closures would retrace every time.

Strategies (see config.AnalogyParams.strategy):

- "exact": per-pixel sequential scan; brute-force approximate search via the
  matmul trick on the MXU + Ashikhmin coherence + kappa blend — semantically
  the CPU oracle's decision, pixel by pixel.  Slow (loop-carried scalar work),
  kept for parity validation.
- "rowwise": batched approximate search per scan row + sequential exact
  coherence/kappa pass.
- "batched": the causal window is restricted to strictly-above rows
  for queries, DB masking AND coherence candidates, so a whole scan row
  resolves in parallel: one fused Pallas distance+argmin (HBM-resident DB,
  sharded over the mesh 'db' axis when db_shards > 1), one batched coherence
  gather, then `refine_passes` cheap vectorized passes that restore same-row
  left-propagation of the source map (the dominant coherence mechanism).
  Fastest; a different-but-comparable synthesis vs the oracle.
- "wavefront": the PARITY fast path (VERDICT.md round-1 item 1).  The raster
  scan is re-scheduled onto anti-diagonals skewed by c = patch_radius + 1:
  pixel (i, j) runs at time t = j + c*i, so every causal dependency —
  including edge-CLAMPED window positions — is computed on a strictly
  earlier diagonal (proof in `wavefront_scan_core`).  Each diagonal's ~W/c
  pixels therefore resolve in ONE batch (fused Pallas full-DB argmin +
  batched Ashikhmin coherence + kappa rule with the oracle's exact
  metric), and the result is the ORACLE'S OUTPUT by construction — same
  per-pixel rule, same dependency values, identical up to fp tie-breaks —
  at batched-strategy speed (~4k batched steps at 1024² instead of ~1M
  sequential pixel steps).  This is what strategy="auto" resolves to.
"""

from __future__ import annotations

import contextlib
import functools
import threading
import time
from dataclasses import dataclass, field, fields
from typing import Any, Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from image_analogies_tpu.backends.base import LevelJob, Matcher
from image_analogies_tpu.obs import device as obs_device
from image_analogies_tpu.obs import metrics as obs_metrics
from image_analogies_tpu.obs import trace as obs_trace
from image_analogies_tpu.utils import logging as ia_logging
from image_analogies_tpu.ops.features import (
    build_features_jax,
    causal_mask,
    window_offsets,
)
from image_analogies_tpu.ops.pallas_match import (
    _lex_lt,
    _round_up,
    argmin_l2,
    bf16_split3,
    packed2k_best,
    packed3_best,
    pertile_champions_queries,
    prepadded_argmin2_queries,
    prepadded_argmin_queries,
)
from image_analogies_tpu.tune import buckets as tune_buckets
from image_analogies_tpu.tune import resolve as tune

# Kernel tile geometry — argmin tile rows, the packed anchor-scan cap,
# and the raised VMEM budget — is RESOLVED, not hard-coded: every call
# site asks image_analogies_tpu.tune.resolve (override > env > store >
# the legacy defaults in tune.geometry, which preserve the round-5
# measured values and their VMEM rationale verbatim).  Resolution runs
# on the host at trace time, so the chosen ints are baked into jit
# programs exactly like the old module constants were.

_F32 = jnp.float32
_HIGHEST = jax.lax.Precision.HIGHEST

# Left-propagation refinement passes of the batched strategy (each pass lets
# coherent source-map runs extend `fine_radius` pixels further left-to-right).
_REFINE_PASSES = 3

# The wavefront scan's packed (Nb, 2) carry stores source-map indices as
# exact f32 VALUES (int bit patterns would be denormal-flushed by real TPU
# data paths — measured round 4); f32 represents integers exactly below
# 2^24, so exemplars beyond 4096^2 rows are rejected at trace time.  The
# bound itself resolves through tune.resolve ("wavefront_max_rows" — the
# last geometry constant to move behind the funnel); resolution clamps
# any configured value to the 2^24 correctness ceiling.


@dataclass
class TpuLevelDB:
    """Device-resident per-level state.  Registered as a JAX pytree: array
    fields are leaves, layout ints/strategy are static aux data, so jitted
    scan functions cache on (shapes, layout) across calls."""

    db: jax.Array  # (Na, F)
    db_sqnorm: jax.Array  # (Na,)
    db_rowsafe: jax.Array  # (Na, F) fine_filt block masked to rows-above
    db_rowsafe_sqnorm: jax.Array  # (Na,)
    static_q: jax.Array  # (Nb, F) fine_filt block zero
    flat_idx: jax.Array  # (Nb, nf) int32
    valid: jax.Array  # (Nb, nf) f32
    written: jax.Array  # (Nb, nf) f32
    rowsafe: jax.Array  # (nf,) f32: causal offsets with di < 0 only
    a_filt_flat: jax.Array  # (Na,)
    fine_sqrtw: jax.Array  # (nf,)
    off: jax.Array  # (nf, 2) int32 window offsets
    db_sharded: Optional[jax.Array]  # (Npad, Fp) laid out over mesh 'db' axis
    dbn_sharded: Optional[jax.Array]
    afilt_sharded: Optional[jax.Array]  # (Npad,) A' values, sharded alongside
    # round-5 sharded [live | dead norm | A'] rows (packed mesh wavefront
    # only) — the step's coherence/re-score/value psum source
    dblive_sharded: Optional[jax.Array]  # (Npad, L+2) over 'db' or None
    diag: Optional[Tuple[jax.Array, ...]]  # anti-diagonal schedule
    # segments (wavefront): tuple of (T_s, M_s) index arrays, tight widths
    # Pre-padded rowsafe DB for the hot loop (tile-aligned rows, 128-aligned
    # features, +inf norms on padding) — pads ONCE per level instead of every
    # scan row inside the fori_loop.
    db_pad: Optional[jax.Array]  # (Npad128, Fp)
    # second packed weight array of the exact_hi2 3-pass scan (W2 = [d3|d1];
    # db_pad holds W1 = [d1|d2]) — None for every other pad mode
    db_pad2: Optional[jax.Array]  # (Npad128, Kp)
    dbn_pad: Optional[jax.Array]  # (1, Npad128)
    # HALF squared norms (+inf on padding rows) for the per-tile champion
    # scan kernel, whose score is q.db - ||db||^2/2 (one VPU sub per
    # element); built alongside dbn_pad for both fp32 and bf16 pads.
    dbnh_pad: Optional[jax.Array]  # (1, Npad128)
    # two-pass scan: per-level feature column mean subtracted from the bf16
    # scan copy AND the queries (distances are shift-invariant; the bf16
    # absolute error ~|q|.|d| is not — centering shrinks it ~10x for these
    # all-positive features).  None for fp32 pads / non-wavefront.
    feat_mean: Optional[jax.Array]  # (Fp,) or None
    # query-live feature columns (FeatureSpec.query_live_mask nonzeros) —
    # the ONE derivation shared by the packed-DB lane layout and the
    # anchor's query packing; only set for pad_mode="packed"
    live_idx: Optional[jax.Array]  # (L,) int32 or None
    # live/dead-split scoring array (round-4, single-chip wavefront on
    # TPU): queries are identically ZERO on dead dims, so the exact fp32
    # distance decomposes as  d = sum_live (cf - q)^2 + dead_sqnorm[row]
    # with dead_sqnorm a NON-NEGATIVE per-row sum (no cancellation, near-
    # zero d stays accurate — unlike the norm trick).  Layout: the live
    # columns PLUS the dead-norm as a final column, (Na, L+1), so the
    # re-score and coherence read ONE gathered row each (TPU gathers cost
    # per row) instead of full (F) rows plus a second norm gather.
    # Summation order differs from the full-row form only like any
    # XLA-vs-NumPy reordering — fp-band ties the audit explains (verified
    # on-chip round 4: 256^2 explained=1.0; the 1024^2 record lands in
    # the driver-written BENCH_r04.json at round end).  Round 5 appends
    # the A' VALUE as a final column — [live | dead norm | A'] — so the
    # fused step's one row gather also yields the output value.
    db_live: Optional[jax.Array]  # (Na, L+2) fp32 or None
    ha: int = field(metadata=dict(static=True))
    wa: int = field(metadata=dict(static=True))
    hb: int = field(metadata=dict(static=True))
    wb: int = field(metadata=dict(static=True))
    fine_start: int = field(metadata=dict(static=True))
    n_rowsafe: int = field(metadata=dict(static=True))
    strategy: str = field(metadata=dict(static=True))
    # batched strategy's left-propagation refinement passes (config knob)
    refine_passes: int = field(default=_REFINE_PASSES,
                               metadata=dict(static=True))
    # wavefront anchor scheme (config.AnalogyParams.match_mode, RESOLVED
    # per level — "auto" picks exact_hi2 above the measured DB-size
    # crossover, exact_hi below; see make_anchor_fn for every mode)
    match_mode: str = field(default="exact_hi", metadata=dict(static=True))
    # mesh for the sharded whole-level step (db_shards > 1); hashable, so a
    # valid static field — synthesize_level dispatches to parallel/step.py
    mesh: Any = field(default=None, metadata=dict(static=True))
    # Shape-bucketed levels (tune.buckets): the REAL A extent as a traced
    # (2,) int32 leaf [ha, wa], with the static ha/wa set to the 0
    # sentinel — jit programs then cache on the BUCKETED array shapes
    # instead of the exact A size, so a new exemplar size whose rows land
    # in the same bucket reuses the compiled runner.  None (default)
    # keeps ha/wa static and the generated HLO bit-identical to the
    # unbucketed engine; all consumers go through a_dims()/a_rows().
    dims_a: Optional[jax.Array] = None
    # QUERY-side bucketing (batched strategy only, ROADMAP direction 4
    # stepping stone): the REAL B row count hb as a traced (1,) int32
    # leaf with static hb set to the 0 sentinel, so the batched scan
    # caches on the BUCKETED static_q row count and differently-sized
    # targets share one program (and one batched-lane program —
    # batch/engine.py).  ``wb`` stays STATIC always: it is the
    # `dynamic_slice` SIZE in `_row_queries`.  The wavefront strategy
    # cannot query-bucket — its packed (Nb, 2) carry and anti-diagonal
    # schedule are program structure keyed on the exact (hb, wb).
    dims_b: Optional[jax.Array] = None
    # Two-stage ANN matcher state (ISSUE 13 / ROADMAP item 3) — all None
    # unless the level was built with ann_prefilter on AND past the
    # parity gate (`_ann_gate_allows`): the (F, Kp) PCA basis and the
    # (F,) mean it centers on (catalog-sealed artifact when one exists,
    # else computed on device), plus the pre-projected (Na, Kp) DB and
    # its (Na,) HALF squared norms the prefilter ranks against.  The
    # projection source is the strategy's scoring DB (full for
    # wavefront, rowsafe-masked for batched), decided at build time like
    # db_pad's — the exact re-score then reads db/db_rowsafe untouched.
    ann_proj: Optional[jax.Array] = None  # (F, Kp)
    ann_mean: Optional[jax.Array] = None  # (F,)
    ann_dbp: Optional[jax.Array] = None  # (Na, Kp)
    ann_dbnh: Optional[jax.Array] = None  # (Na,)

    def a_dims(self):
        """(ha, wa) as ints (static path) or traced scalars (bucketed)."""
        if self.dims_a is not None:
            return self.dims_a[0], self.dims_a[1]
        return self.ha, self.wa

    def a_rows(self):
        """Real DB row count ha*wa (excludes bucket padding rows)."""
        ha, wa = self.a_dims()
        return ha * wa

    def b_dims(self):
        """(hb, wb): hb an int (static path) or traced scalar (query-
        bucketed); wb is always the static int (dynamic_slice size)."""
        if self.dims_b is not None:
            return self.dims_b[0], self.wb
        return self.hb, self.wb

    def b_rows(self):
        """Real query row count hb*wb (excludes bucket padding rows)."""
        hb, wb = self.b_dims()
        return hb * wb


jax.tree_util.register_dataclass(
    TpuLevelDB,
    data_fields=[f.name for f in fields(TpuLevelDB)
                 if not f.metadata.get("static")],
    meta_fields=[f.name for f in fields(TpuLevelDB)
                 if f.metadata.get("static")],
)


def _diag_schedule(h: int, w: int, c: int) -> Tuple[jax.Array, ...]:
    """Device-resident wavefront schedule: the cached NumPy segments of
    `_diag_schedule_np`, device_put at use site.  Caching NUMPY (not device
    buffers) keeps the lru_cache from pinning megabytes of schedule on
    whatever device was default at first call for process lifetime
    (round-2 ADVICE item 5); a per-level device_put of a few MB is noise
    next to the level's feature build."""
    return tuple(jax.device_put(jnp.asarray(s))
                 for s in _diag_schedule_np(h, w, c))


@functools.lru_cache(maxsize=64)
def _diag_schedule_np(h: int, w: int, c: int) -> Tuple[np.ndarray, ...]:
    """Anti-diagonal wavefront schedule, skew c, as a tuple of SEGMENTS:
    within each segment, row t holds the flat indices of every pixel (i, j)
    with j + c*i == t (-1 padding on short diagonals).

    With c = patch_radius + 1 all of pixel (i, j)'s causal dependencies lie on
    strictly earlier diagonals (see `wavefront_scan_core`), so each schedule
    row is an independently-resolvable batch.  Diagonal width ramps up from 1,
    plateaus at ~min(h, w/c), and ramps back down; padding every row to the
    plateau width would waste ~25% of the argmin kernel's MXU work on dead
    lanes at 1024², so the unimodal width curve is cut into contiguous
    segments, each padded only to ITS maximum width (8-aligned, short
    segments merged).  Segment order preserves t order, so the scan
    semantics are untouched — this is purely an occupancy optimization."""
    t_total = c * (h - 1) + w
    m_max = min(h, (w + c - 1) // c)
    ii = np.arange(h)
    rows = []
    counts = np.empty((t_total,), np.int64)
    for t in range(t_total):
        jj = t - c * ii
        ok = (jj >= 0) & (jj < w)
        rows.append((ii[ok] * w + jj[ok]).astype(np.int32))
        counts[t] = rows[-1].size

    # cut where the 8-aligned quartile bucket of the width changes; merge
    # segments shorter than 64 steps into their successor (avoid a pile of
    # tiny compiled loop bodies)
    def bucket(n):
        q = max(1, m_max // 4)
        return min(3, (n - 1) // q)

    cuts = [0]
    for t in range(1, t_total):
        if bucket(counts[t]) != bucket(counts[t - 1]):
            cuts.append(t)
    cuts.append(t_total)
    spans = [(a, b) for a, b in zip(cuts[:-1], cuts[1:])]
    merged = []
    for span in spans:
        if merged and (span[1] - span[0] < 64
                       or merged[-1][1] - merged[-1][0] < 64):
            merged[-1] = (merged[-1][0], span[1])
        else:
            merged.append(span)

    segs = []
    for a, b in merged:
        seg_m = int(_round_up(max(int(counts[a:b].max()), 1), 8))
        sched = np.full((b - a, seg_m), -1, np.int32)
        for k, t in enumerate(range(a, b)):
            sched[k, :rows[t].size] = rows[t]
        segs.append(sched)
    return tuple(segs)


@functools.lru_cache(maxsize=64)
def _gather_maps_device(h: int, w: int, p: int):
    """Device-computed twin of ops.features.fine_gather_maps.

    These maps are (H*W, p*p) — ~100 MB each at 1024^2.  Computing them from
    iota on the device (and caching per shape) avoids shipping hundreds of MB
    from the host per level, which dominated level wall-clock over the PJRT
    tunnel.  Semantics are locked to the NumPy twin by
    tests/test_backend_equivalence.py::test_device_gather_maps_match_numpy.
    """
    off = jnp.asarray(window_offsets(p))  # (n,2) tiny host->device
    n = p * p
    ii = jax.lax.broadcasted_iota(jnp.int32, (h, w), 0).reshape(-1, 1)
    jj = jax.lax.broadcasted_iota(jnp.int32, (h, w), 1).reshape(-1, 1)
    qi = ii + off[None, :, 0].reshape(1, n)
    qj = jj + off[None, :, 1].reshape(1, n)
    inb = (qi >= 0) & (qi < h) & (qj >= 0) & (qj < w)
    flat = jnp.clip(qi, 0, h - 1) * w + jnp.clip(qj, 0, w - 1)
    causal = jnp.asarray(causal_mask(p) > 0)[None, :]
    q = ii * w + jj
    valid = (inb & causal).astype(jnp.float32)
    written = (causal & (flat < q)).astype(jnp.float32)
    return (jax.device_put(flat.astype(jnp.int32)),
            jax.device_put(valid), jax.device_put(written))


def _packed_weight_arrays(src, spec, npad: int, mode2p: bool):
    """THE packed-scan build shared by the single-chip pad and the sharded
    builder — one derivation of the live-dim shift, the bf16 hi/mid/lo
    split, and the lane layout, so the solo-vs-mesh bit-identical parity
    can never drift between the two paths.

    Returns (w1, w2, dbnh_row (npad,), shift (f,), live_idx).  ``mode2p``
    builds the exact_hi2_2p K-wide single-array layout consumed by
    `pallas_match.packed2k_best`:

        w1 = [ d1 | d2 | norm lanes | d1 | d3 | 0pad ]   (4L + 3 lanes)

    with w2 = None — the negative half-norms ride lanes [2L, 2L+3)
    (`add_norm_lanes` rationale), d1 is laid down twice so the q1 and q2
    row-blocks both meet it in ONE K~256 MXU dot, and the whole scan is
    one weight stream with no dbnh input and no VPU add/subtract passes.
    Non-2p: exact_hi2's W1=[d1|d2] / W2=[d3|d1] pair (subtract-based
    3-pass kernel).  A narrow single-stream variant that dropped the
    q1.d3 term was measured and REJECTED (256^2 tie-audit: explained
    0.999873, first divergence not a tie); its kernels remain in
    ops/pallas_match for the record but have no production build."""
    from image_analogies_tpu.ops.pallas_match import add_norm_lanes

    n, f = src.shape
    live = np.nonzero(spec.query_live_mask())[0]
    lw = live.size
    shift = jnp.zeros((f,), _F32).at[live].set(
        jnp.mean(src[:, live], axis=0))
    srcc = src - shift[None, :]
    nrm = jnp.sum(srcc * srcc, axis=1)
    # bitmask split — the dtype-round-trip split is folded away under
    # --xla_allow_excess_precision (see bf16_split3)
    h1, h2, r2 = bf16_split3(srcc[:, live])
    d1, d2, d3 = (x.astype(jnp.bfloat16) for x in (h1, h2, r2))
    dbnh = jnp.full((npad,), jnp.inf, _F32).at[:n].set(0.5 * nrm)
    live_idx = jnp.asarray(live, jnp.int32)

    if mode2p:
        o2 = 2 * lw + 3
        pk = max((o2 + 2 * lw + 127) // 128 * 128, 128)
        wk = jnp.zeros((npad, pk), jnp.bfloat16)
        ins = lambda w, x, col: jax.lax.dynamic_update_slice(
            w, jnp.zeros((npad, lw), jnp.bfloat16).at[:n].set(x), (0, col))
        wk = ins(wk, d1, 0)
        wk = ins(wk, d2, lw)
        wk = add_norm_lanes(wk, dbnh, lw)  # lanes [2lw, 2lw+3)
        wk = ins(wk, d1, o2)
        wk = ins(wk, d3, o2 + lw)
        return wk, None, dbnh, shift, live_idx

    pk = max((2 * lw + 127) // 128 * 128, 128)

    def pack(left, right):
        return jnp.zeros((npad, pk), jnp.bfloat16).at[
            :n, :lw].set(left).at[:n, lw:2 * lw].set(right)

    return pack(d1, d2), pack(d3, d1), dbnh, shift, live_idx


# jit entry points below are wrapped in obs.device compile-aware shims
# (a no-op passthrough unless a metrics run is active): every level
# program's compile wall-time, cache hit/recompile, and XLA cost
# estimate lands in the run log.  static_argnums mirror each jit's
# static_argnames positions — the AOT executable takes only dynamic args.


@functools.partial(jax.jit, static_argnames=("spec", "pad_tile", "pad_full",
                                             "pad_mode", "db_rows_pad",
                                             "q_rows_pad"))
def _prepare_level_arrays(
    spec, a_src, a_filt, a_src_coarse, a_filt_coarse, a_temporal,
    b_src, b_src_coarse, b_filt_coarse, b_temporal, rowsafe, pad_tile,
    pad_full=False, pad_mode="f32", db_rows_pad=0, q_rows_pad=0,
):
    """All device-side level preparation fused into ONE program: eager
    per-op dispatch over the PJRT tunnel costs ~1s/level otherwise.

    ``pad_full`` selects which DB the pre-padded argmin tiles score against:
    the rowsafe-masked DB (batched strategy's symmetric metric) or the FULL
    DB (wavefront strategy — the oracle's metric: full A/A' rows vs
    zero-masked queries).  ``pad_mode`` selects the scan copy's layout:

    - "f32": plain fp32 pre-pad (exact_hi / exact_hi_merged / batched).
    - "bf16": centered bf16 copy (the approximate scan_rescue/two_pass
      schemes: half the HBM stream, one MXU pass); ``dbn_pad`` keeps EXACT
      fp32 row norms so identical rows score identically and ties stay
      lowest-index.
    - "packed": the exact_hi2 hi/lo lane-packed bf16 copy — query-LIVE
      dims only (dead dims reach scores via the norm term exactly),
      centered on live dims, hi halves in lanes [0, L) and lo residuals in
      [L, 2L).  One bf16 HBM stream + 2 stacked MXU passes reproduce
      HIGHEST's exact product set (see make_anchor_fn).

    The fp32 ``db`` stays the re-score / coherence source in every mode.

    ``db_rows_pad`` (shape bucketing, tune/buckets.py) grows every
    Na-sized array to the bucketed row count AFTER the real-row builds:
    means/norms/shifts are computed over real rows only, scan-copy pads
    carry +inf norms so the argmin never picks them, and full-array pads
    are zero rows that no gather reaches (coherence candidates clip to
    the real A extent; the anchor clamps to the real row count).  0 (the
    default) reproduces the unbucketed arrays bit-for-bit.

    ``q_rows_pad`` (query-side bucketing, batched strategy only) grows
    ``static_q`` to the bucketed QUERY row count with zero rows.  The
    batched scan's row loop runs only over the REAL hb (traced through
    ``TpuLevelDB.dims_b``), so padded query rows are never read and
    never written — padding honesty holds by construction, whatever the
    pad contents (tests/test_batch.py adversarially overwrites them)."""
    db = build_features_jax(spec, a_src, a_filt, a_src_coarse, a_filt_coarse,
                            temporal_fine=a_temporal)
    static_q = build_features_jax(spec, b_src, None, b_src_coarse,
                                  b_filt_coarse, temporal_fine=b_temporal)
    fsl = spec.fine_filt_slice
    db_sqnorm = jnp.sum(db * db, axis=1)
    if pad_full:
        # wavefront never scores against the rowsafe-masked DB; alias the
        # full DB instead of materializing a second (Na, F) copy in HBM.
        db_rowsafe, db_rowsafe_sqnorm = db, db_sqnorm
    else:
        db_rowsafe = db.at[:, fsl].multiply(rowsafe[None, :])
        db_rowsafe_sqnorm = jnp.sum(db_rowsafe * db_rowsafe, axis=1)
    out = {
        "db": db,
        "db_sqnorm": db_sqnorm,
        "db_rowsafe": db_rowsafe,
        "db_rowsafe_sqnorm": db_rowsafe_sqnorm,
        "static_q": static_q,
        "a_filt_flat": a_filt.reshape(-1),
        "db_pad": None,
        "db_pad2": None,
        "dbn_pad": None,
        "dbnh_pad": None,
        "feat_mean": None,
        "live_idx": None,
        "db_live": None,
    }
    if pad_full and pad_tile and pad_mode.startswith("packed"):
        # live/dead-split scoring arrays (see TpuLevelDB) — TPU wavefront
        # packed modes only: the CPU/XLA test paths keep full-row scoring
        # so their exact-equality fixtures stay byte-stable.  Layout
        # (Na, L+2): [live cols | dead norm | A' value] — the A' value
        # rides the same gathered row (rows cost per fetch; round 5), so
        # the fused step reads score AND output value in one gather.
        live_np = np.nonzero(spec.query_live_mask())[0]
        dead_np = np.setdiff1d(np.arange(spec.total), live_np)
        out["db_live"] = jnp.concatenate(
            [db[:, live_np],
             jnp.sum(db[:, dead_np] ** 2, axis=1)[:, None],
             a_filt.reshape(-1)[:, None]], axis=1)
    if pad_tile:
        src = db if pad_full else db_rowsafe
        srcn = out["db_sqnorm"] if pad_full else out["db_rowsafe_sqnorm"]
        n, f = src.shape
        fp = max((f + 127) // 128 * 128, 128)
        n_goal = max(n, db_rows_pad)
        npad = (n_goal + pad_tile - 1) // pad_tile * pad_tile
        if pad_mode == "bf16":
            # centered bf16 scan copy + EXACT fp32 norms of the centered
            # rows (identical rows stay identical -> ties stay lowest-index)
            mean = jnp.mean(src, axis=0)
            srcc = src - mean[None, :]
            nrm = jnp.sum(srcc * srcc, axis=1)
            out["feat_mean"] = jnp.zeros((fp,), _F32).at[:f].set(mean)
            out["db_pad"] = jnp.zeros((npad, fp), jnp.bfloat16).at[
                :n, :f].set(srcc.astype(jnp.bfloat16))
            out["dbn_pad"] = jnp.full((1, npad), jnp.inf, _F32).at[
                0, :n].set(nrm)
        elif pad_mode in ("packed", "packed2"):
            # exact_hi2 family: live-dim hi/mid/lo lane packing (3-way bf16
            # split covers ~24 mantissa bits; product sets documented in
            # ops/pallas_match._packed_kernel).  The shift vector is the
            # live-masked column mean — dead dims stay RAW (queries are
            # identically zero there, so shifting them would break the
            # distance-shift invariance); centering shrinks |q||db| and
            # with it every dropped-term error.  The build itself is
            # `_packed_weight_arrays`, SHARED with the sharded builder.
            w1, w2, dbnh_row, shift, live_idx = _packed_weight_arrays(
                src, spec, npad, mode2p=pad_mode == "packed2")
            out["feat_mean"] = jnp.zeros((fp,), _F32).at[:f].set(shift)
            out["db_pad"] = w1
            out["db_pad2"] = w2  # None for packed1w (norms ride W1)
            out["live_idx"] = live_idx
            out["dbnh_pad"] = dbnh_row[None, :]
            nrm = None  # dbnh_pad already set; skip the shared tail
        else:
            out["db_pad"] = jnp.zeros((npad, fp), _F32).at[:n, :f].set(src)
            out["dbn_pad"] = jnp.full((1, npad), jnp.inf, _F32).at[
                0, :n].set(srcn)
            nrm = None  # f32 pads have no champion-kernel consumer
        if nrm is not None:
            # half norms for the champion scan kernels (bf16 / packed only)
            out["dbnh_pad"] = jnp.full((1, npad), jnp.inf, _F32).at[
                0, :n].set(0.5 * nrm)
    if db_rows_pad and db_rows_pad > db.shape[0]:
        grow = db_rows_pad - db.shape[0]
        zrows = lambda x: jnp.pad(
            x, ((0, grow),) + ((0, 0),) * (x.ndim - 1))
        out["db"] = zrows(out["db"])
        out["db_sqnorm"] = jnp.pad(out["db_sqnorm"], (0, grow),
                                   constant_values=jnp.inf)
        if pad_full:
            out["db_rowsafe"] = out["db"]
            out["db_rowsafe_sqnorm"] = out["db_sqnorm"]
        else:
            out["db_rowsafe"] = zrows(out["db_rowsafe"])
            out["db_rowsafe_sqnorm"] = jnp.pad(
                out["db_rowsafe_sqnorm"], (0, grow),
                constant_values=jnp.inf)
        out["a_filt_flat"] = zrows(out["a_filt_flat"])
        if out["db_live"] is not None:
            out["db_live"] = zrows(out["db_live"])
    if q_rows_pad and q_rows_pad > out["static_q"].shape[0]:
        grow_q = q_rows_pad - out["static_q"].shape[0]
        out["static_q"] = jnp.pad(out["static_q"], ((0, grow_q), (0, 0)))
    return out


_prepare_level_arrays = obs_device.instrument(
    _prepare_level_arrays, "tpu.prepare_level_arrays",
    # spec, pad_tile, pad_full, pad_mode, db_rows_pad, q_rows_pad
    static_argnums=(0, 11, 12, 13, 14, 15))


@functools.lru_cache(maxsize=None)
def _cached_sharded_db_builder(mesh, spec, pad_full: bool, npad: int,
                               fp: int, packed: bool):
    """Jit that builds a level's scoring DB DIRECTLY sharded over the mesh's
    'db' axis (out_shardings): GSPMD partitions the window-gather feature
    build by output rows, so each chip materializes only ITS shard — the
    full (Na, F) DB never exists on any single device, closing the
    transient-build memory bound that `shard_level_db`'s
    device_put-after-build path had.

    With ``packed`` (the wavefront mesh scan on real TPUs) the builder also
    emits the exact_hi2_2p K-wide weight shards (the round-4 single-array
    layout [d1|d2|norms|d1|d3] — see `_packed_weight_arrays`) and the
    (replicated) live-dim centering shift — the shift reduces over the
    FULL row set (GSPMD inserts the cross-shard mean), so scan scores are
    globally comparable and the cross-shard tie-break stays
    lowest-global-index (parallel/sharded_match.packed_champion_allreduce).
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    sh_db = NamedSharding(mesh, P("db", None))
    sh_row = NamedSharding(mesh, P("db"))
    sh_rep = NamedSharding(mesh, P())

    def build(a_src, a_filt, a_src_coarse, a_filt_coarse, a_temporal,
              rowsafe):
        # edge_gather: this program compiles with row-sharded out_shardings,
        # where the SPMD partitioner miscompiles the edge-pad window build
        # (every element exactly doubled when the per-shard row count is
        # not a multiple of the image width) — the clip-gather twin is
        # bit-identical and partitions correctly (ops/features.py).
        db = build_features_jax(spec, a_src, a_filt, a_src_coarse,
                                a_filt_coarse, temporal_fine=a_temporal,
                                edge_gather=True)
        if not pad_full:  # batched scores against the rowsafe-masked DB
            db = db.at[:, spec.fine_filt_slice].multiply(rowsafe[None, :])
        dbn = jnp.sum(db * db, axis=1)
        n, f = db.shape
        dbp = jnp.zeros((npad, fp), _F32).at[:n, :f].set(db)
        dbnp = jnp.full((npad,), jnp.inf, _F32).at[:n].set(dbn)
        afp = jnp.zeros((npad,), _F32).at[:n].set(
            a_filt.reshape(-1).astype(_F32))
        if not packed:
            return dbp, dbnp, afp
        # SAME build as the single-chip exact_hi2_2p pad (shared helper) —
        # GSPMD turns the helper's full-row mean into the cross-shard
        # collective, keeping scan scores globally comparable
        wk, _, _, shift, _ = _packed_weight_arrays(db, spec, npad,
                                                   mode2p=True)
        shiftp = jnp.zeros((fp,), _F32).at[:f].set(shift)
        # sharded twin of the single-chip db_live (round-5 mesh gather
        # diet): [live cols | dead norm | A' value] — the step's coherence
        # psum moves L+2 columns instead of full-F rows, and the A'-value
        # psum disappears (parallel/step.py row_live_fn)
        live_np = np.nonzero(spec.query_live_mask())[0]
        dead_np = np.setdiff1d(np.arange(spec.total), live_np)
        lw = live_np.size
        dbl = jnp.zeros((npad, lw + 2), _F32)
        dbl = dbl.at[:n, :lw].set(db[:, live_np])
        dbl = dbl.at[:n, lw].set(jnp.sum(db[:, dead_np] ** 2, axis=1))
        dbl = dbl.at[:n, lw + 1].set(a_filt.reshape(-1).astype(_F32))
        return (dbp, dbnp, afp, wk, shiftp, dbl)

    outs = (sh_db, sh_row, sh_row)
    if packed:
        outs = outs + (sh_db, sh_rep, sh_db)
    return obs_device.instrument(jax.jit(build, out_shardings=outs),
                                 "tpu.sharded_db_build")


@functools.partial(jax.jit, static_argnames=("spec",))
def _prepare_query_arrays(spec, b_src, b_src_coarse, b_filt_coarse,
                          b_temporal):
    """Query-side features only — the sharded build path computes the DB
    side in `_cached_sharded_db_builder` and must not also run
    `_prepare_level_arrays`, whose program materializes the full DB."""
    return build_features_jax(spec, b_src, None, b_src_coarse,
                              b_filt_coarse, temporal_fine=b_temporal)


_prepare_query_arrays = obs_device.instrument(
    _prepare_query_arrays, "tpu.prepare_query_arrays", static_argnums=(0,))


@functools.partial(jax.jit, static_argnames=("spec",))
def _prepare_query_arrays_batch(spec, b_src, b_src_coarse, b_filt_coarse,
                                b_temporal):
    """Stacked-over-frames twin of `_prepare_query_arrays` for the mesh
    video path: ONE dispatch builds every frame's (Nb, F) query features
    from (T, H, W) stacks — the old per-frame serial jit loop cost T
    dispatches per level over a ~0.1 s-latency tunnel (round-3 VERDICT
    weak item 5).  Optional inputs pass None (vmap treats the empty
    pytree as unbatched)."""
    fn = lambda bs, bsc, bfc, bt: build_features_jax(
        spec, bs, None, bsc, bfc, temporal_fine=bt)
    return jax.vmap(fn)(b_src, b_src_coarse, b_filt_coarse, b_temporal)


_prepare_query_arrays_batch = obs_device.instrument(
    _prepare_query_arrays_batch, "tpu.prepare_query_arrays_batch",
    static_argnums=(0,))


def build_sharded_db(spec, a_src, a_filt, a_src_coarse, a_filt_coarse,
                     a_temporal, rowsafe, mesh, pad_full: bool, tile: int,
                     packed: bool = False):
    """Build the level's sharded scoring arrays over the mesh's 'db' axis
    without any chip holding the full DB (see `_cached_sharded_db_builder`).
    Used by the single-image sharded path and the sharded video phase.

    Returns a 6-tuple (dbp, dbnp, afiltp, wk, shift, dbl); the last three
    are None unless ``packed`` (the exact_hi2_2p mesh scan — wk is the
    round-4 K-wide weight array, dbl the round-5 sharded
    [live | dead norm | A'] scoring rows)."""
    from image_analogies_tpu.parallel.sharded_match import \
        sharded_pad_geometry

    ha, wa = a_filt.shape[:2]
    npad, fp = sharded_pad_geometry(ha * wa, spec.total, mesh.shape["db"],
                                    tile)
    fn = _cached_sharded_db_builder(mesh, spec, pad_full, npad, fp, packed)
    out = fn(a_src, a_filt, a_src_coarse, a_filt_coarse, a_temporal,
             rowsafe)
    return out if packed else out + (None, None, None)


def make_level_template(params, job: LevelJob, strategy: str,
                        match_mode: str = "exact_hi") -> TpuLevelDB:
    """Slim per-level TpuLevelDB for the mesh step: real query-side maps
    (gather indices, masks, schedule, weights), 1-row placeholders for every
    DB-sized array — the mesh step reads DB rows only through the sharded
    inputs, so the full arrays must never exist per chip.

    The wavefront scan computes its window indices/masks from iota math
    inside the step (`wavefront_scan_core`), so for that strategy the
    (Nb, p^2) gather maps are 1-row placeholders too — at 1024^2 that drops
    ~300 MB of HBM (and of replicated mesh-template shipping) per level."""
    spec = job.spec
    hb, wb = job.b_shape
    ha, wa = job.a_shape
    if strategy == "wavefront":
        flat_idx = jnp.zeros((1, spec.fine_n), jnp.int32)
        valid = written = jnp.zeros((1, spec.fine_n), _F32)
    else:
        flat_idx, valid, written = _gather_maps_device(hb, wb, spec.fine_size)
    # live columns always ride the template (tiny): the packed anchors —
    # single-chip AND the mesh step — read them from here, so the lane
    # layout derivation stays spec.query_live_mask() everywhere
    live_idx = jnp.asarray(np.nonzero(spec.query_live_mask())[0], jnp.int32)
    off = window_offsets(spec.fine_size)
    rowsafe = ((off[:, 0] < 0).astype(np.float32)
               * causal_mask(spec.fine_size))
    diag = (_diag_schedule(hb, wb, spec.fine_size // 2 + 1)
            if strategy == "wavefront" else None)
    z2 = jnp.zeros((1, spec.total), _F32)
    z1 = jnp.zeros((1,), _F32)
    fsl = spec.fine_filt_slice
    return TpuLevelDB(
        db=z2, db_sqnorm=z1, db_rowsafe=z2, db_rowsafe_sqnorm=z1,
        static_q=z2, flat_idx=flat_idx, valid=valid, written=written,
        rowsafe=jnp.asarray(rowsafe), a_filt_flat=z1,
        fine_sqrtw=jnp.asarray(spec.sqrt_weights()[fsl]),
        off=jnp.asarray(off), db_sharded=None, dbn_sharded=None,
        afilt_sharded=None, dblive_sharded=None, diag=diag, db_pad=None,
        db_pad2=None, dbn_pad=None,
        dbnh_pad=None, feat_mean=None, live_idx=live_idx,
        db_live=None,
        ha=ha, wa=wa, hb=hb, wb=wb, fine_start=fsl.start,
        n_rowsafe=(spec.fine_size // 2) * spec.fine_size,
        strategy=strategy, refine_passes=params.refine_passes,
        match_mode=match_mode)


def slim_for_mesh(db: TpuLevelDB, keep_sharded: bool = False) -> TpuLevelDB:
    """Replace the per-chip copies of DB-sized arrays with 1-row
    placeholders — the ONE definition of which fields the sharded-memory
    story slims.  The mesh step (parallel/step.py) reads DB rows and A'
    values ONLY through the sharded inputs and psum lookups, so shipping the
    full arrays replicated would defeat the story.  Query-side (Nb-sized)
    arrays stay: they shard over 'data' (video) or are genuinely per-chip
    state (single image).

    ``keep_sharded=True`` retains the sharded arrays + mesh (build_features
    uses this for the steady-state LevelDB); the default also drops them —
    the shard_map template must not re-ship what the step receives as
    sharded inputs.  ``static_q`` is slimmed too: the step receives the
    query features as its own (sharded) input and reads only the template's
    feature WIDTH, so shipping the (Nb, F) copy replicated would waste
    hundreds of MB per chip at 1024^2 (round-2 ADVICE item 1)."""
    import dataclasses

    z2 = jnp.zeros((1, db.static_q.shape[1]), _F32)
    z1 = jnp.zeros((1,), _F32)
    kw = {} if keep_sharded else dict(db_sharded=None, dbn_sharded=None,
                                      afilt_sharded=None,
                                      dblive_sharded=None, mesh=None)
    return dataclasses.replace(
        db, db=z2, db_sqnorm=z1, db_rowsafe=z2, db_rowsafe_sqnorm=z1,
        static_q=z2, a_filt_flat=z1, db_pad=None, db_pad2=None,
        dbn_pad=None, dbnh_pad=None, db_live=None, **kw)


# --------------------------------------------------------------- exact scan


def _exact_qvec(db: TpuLevelDB, q, bp):
    dyn = bp[db.flat_idx[q]] * db.written[q] * db.fine_sqrtw
    return jax.lax.dynamic_update_slice(
        db.static_q[q], dyn, (db.fine_start,))


def _rescore_d_app(db: TpuLevelDB, qvec, p_app):
    """Oracle re-score of a precomputed approx anchor: exact fp32 squared
    distance of the FULL db row to the causal query (rowwise strategy)."""
    return p_app, jnp.sum((db.db[p_app] - qvec) ** 2)


def _resolve_pixel(db: TpuLevelDB, q, bp, s, p_app, d_app_fn, kappa_mult):
    """The per-pixel decision shared by the exact / rowwise strategies:
    build the causal query vector, get d_app via `d_app_fn(qvec)`
    (full-DB scores for exact, candidate re-score for rowwise),
    take the best Ashikhmin coherence candidate, apply the kappa rule
    (Hertzmann §3.2 eq. 2), and write (bp, s) at q.

    Returns (bp, s, use_coh)."""
    qvec = _exact_qvec(db, q, bp)
    p_app, d_app = d_app_fn(qvec, p_app)
    p_coh, d_coh, has_coh = _pixel_coherence(db, qvec, q, s)
    use_coh = has_coh & (d_coh <= d_app * kappa_mult)
    p = jnp.where(use_coh, p_coh, p_app).astype(jnp.int32)
    bp = bp.at[q].set(db.a_filt_flat[p])
    s = s.at[q].set(p)
    return bp, s, use_coh


def _batched_coherence(db: TpuLevelDB, s, queries, idx_c, ok, n_cand: int,
                       row_fn, q_live=None, s_r=None, p_app=None,
                       live_gather=None):
    """Batched Ashikhmin candidates for M pixels at once (Hertzmann §3.2):
    for each query m the candidates are {s(r) + (q - r)} over its first
    ``n_cand`` causal window positions r (idx_c (M, n_cand) flat positions,
    ``ok`` their base validity), scored in fp32 against ``row_fn(cand)`` —
    a gather of the scoring DB's rows (the rowsafe-masked DB for the batched
    strategy, the full DB for wavefront; a psum-gather of the SHARDED DB on
    the mesh — see parallel/step.py).

    With ``q_live`` (the queries' live columns, single-chip TPU wavefront)
    the score uses the live/dead split instead:
    d = sum_live (cf_live - q_live)^2 + dead_norm_col — exact up to
    summation order, ~2x less gather traffic (see TpuLevelDB.db_live).

    ``s_r`` optionally supplies the pre-gathered source-map window values
    (the wavefront step packs them into its B' gather — one gather serves
    both); otherwise they gather from ``s`` here.

    ``p_app`` (requires ``q_live``) appends the anchor pick as one more
    gathered-and-scored column, so the anchor's exact re-score rides THE
    SAME row gather as the candidates (TPU gathers cost per row; a
    separate M-row re-score fetch measured ~48 us/step at north-star
    plateau — experiments/coherence_parts_probe.py).  Same rows, same
    formula; XLA may order the (M, n+1, L+1) reduction differently than
    the standalone (M, L+1) one — an fp-band perturbation of d_app, the
    class the tie-audit adjudicates (kappa_boundary).

    ``live_gather`` overrides the row fetch (default ``db.db_live[idx]``)
    — the mesh step psum-gathers the SHARDED db_live here, shrinking the
    per-step ICI payload from full-F rows to L+2 columns.

    Returns (p_coh, d_coh, has_coh) — all (M,) — plus, when ``p_app`` is
    given, d_app (M,) and, when the gathered rows carry the round-5 A'
    column (width L+2), (af_coh, af_app): the A' values of the coherence
    pick and the anchor pick, making the step's separate A'-value fetch
    redundant."""
    if s_r is None:
        s_r = s[idx_c]  # (M, n_cand)
    ha, wa = db.a_dims()
    ci = s_r // wa - db.off[None, :n_cand, 0]
    cj = s_r % wa - db.off[None, :n_cand, 1]
    ok = ok & (ci >= 0) & (ci < ha) & (cj >= 0) & (cj < wa)
    cand = (jnp.clip(ci, 0, ha - 1) * wa
            + jnp.clip(cj, 0, wa - 1))
    if q_live is not None:
        lw = q_live.shape[-1]
        gidx = (cand if p_app is None
                else jnp.concatenate([cand, p_app[:, None]], axis=1))
        if live_gather is None:
            cf = db.db_live[gidx]  # (M, n_cand(+1), L+1 or L+2)
        else:
            cf = live_gather(gidx)
        dca = (jnp.sum((cf[..., :lw] - q_live[:, None, :]) ** 2, axis=-1)
               + cf[..., lw])
        dc = dca[:, :n_cand]
    else:
        assert p_app is None, "fused anchor re-score needs db_live"
        cf = row_fn(cand)  # (M, n_cand, F)
        dc = jnp.sum((cf - queries[:, None, :]) ** 2, axis=-1)
    dc = jnp.where(ok, dc, jnp.inf)
    k = jnp.argmin(dc, axis=1)
    d_coh = jnp.take_along_axis(dc, k[:, None], axis=1)[:, 0]
    p_coh = jnp.take_along_axis(cand, k[:, None], axis=1)[:, 0]
    if p_app is None:
        return p_coh, d_coh, ok.any(axis=1)
    out = (p_coh, d_coh, ok.any(axis=1), dca[:, n_cand])
    if cf.shape[-1] > lw + 1:  # A' value column present
        af = cf[..., lw + 1]
        af_coh = jnp.take_along_axis(af, k[:, None], axis=1)[:, 0]
        return out + (af_coh, af[:, n_cand])
    return out


def _pixel_coherence(db: TpuLevelDB, qvec, q, s):
    """Ashikhmin candidates for one pixel from the full causal window."""
    s_r = s[db.flat_idx[q]]
    ha, wa = db.a_dims()
    ci = s_r // wa - db.off[:, 0]
    cj = s_r % wa - db.off[:, 1]
    inb = ((ci >= 0) & (ci < ha) & (cj >= 0) & (cj < wa)
           & (db.valid[q] > 0))
    cand = (jnp.clip(ci, 0, ha - 1) * wa
            + jnp.clip(cj, 0, wa - 1))
    cf = db.db[cand]
    dc = jnp.sum((cf - qvec[None, :]) ** 2, axis=1)
    dc = jnp.where(inb, dc, jnp.inf)
    k = jnp.argmin(dc)
    return cand[k], dc[k], inb.any()


@jax.jit
def _run_exact(db: TpuLevelDB, kappa_mult):
    nb = db.hb * db.wb

    def d_app_fn(qvec, _):
        scores = db.db_sqnorm - 2.0 * jnp.dot(
            db.db, qvec, preferred_element_type=_F32, precision=_HIGHEST)
        p_app = jnp.argmin(scores)
        qn = jnp.dot(qvec, qvec, preferred_element_type=_F32,
                     precision=_HIGHEST)
        return p_app, jnp.maximum(scores[p_app] + qn, 0.0)

    def body(q, state):
        bp, s, n_coh = state
        bp, s, use_coh = _resolve_pixel(db, q, bp, s, None, d_app_fn,
                                        kappa_mult)
        return bp, s, n_coh + use_coh.astype(jnp.int32)

    bp0 = jnp.zeros((nb,), _F32)
    s0 = jnp.zeros((nb,), jnp.int32)
    return jax.lax.fori_loop(0, nb, body, (bp0, s0, jnp.int32(0)))


# -------------------------------------------------------------- rowwise scan


def _row_queries(db: TpuLevelDB, r, bp, mask):
    """Query features for all pixels of row r; `mask` picks which causal
    offsets contribute (rowsafe for batched, written-only for rowwise)."""
    nf = int(db.off.shape[0])
    q0 = r * db.wb
    idx = jax.lax.dynamic_slice(db.flat_idx, (q0, 0), (db.wb, nf))
    wr = jax.lax.dynamic_slice(db.written, (q0, 0), (db.wb, nf))
    dyn = bp[idx] * wr * mask[None, :] * db.fine_sqrtw[None, :]
    base = jax.lax.dynamic_slice(
        db.static_q, (q0, 0), (db.wb, db.static_q.shape[1]))
    return jax.lax.dynamic_update_slice(base, dyn, (0, db.fine_start))


@jax.jit
def _run_rowwise(db: TpuLevelDB, kappa_mult):
    wb, hb = db.wb, db.hb

    def approx_fn(queries):
        return argmin_l2(queries, db.db_rowsafe, db.db_rowsafe_sqnorm)

    def d_app_fn(qvec, p_app):
        return _rescore_d_app(db, qvec, p_app)

    def pixel_body(j, carry):
        bp, s, n_coh, r, p_apps = carry
        bp, s, use_coh = _resolve_pixel(db, r * wb + j, bp, s, p_apps[j],
                                        d_app_fn, kappa_mult)
        return bp, s, n_coh + use_coh.astype(jnp.int32), r, p_apps

    def row_body(r, state):
        bp, s, n_coh = state
        queries = _row_queries(db, r, bp, db.rowsafe)
        p_apps, _ = approx_fn(queries)
        bp, s, n_coh, _, _ = jax.lax.fori_loop(
            0, wb, pixel_body, (bp, s, n_coh, r, p_apps))
        return bp, s, n_coh

    bp0 = jnp.zeros((hb * wb,), _F32)
    s0 = jnp.zeros((hb * wb,), jnp.int32)
    return jax.lax.fori_loop(0, hb, row_body, (bp0, s0, jnp.int32(0)))


# -------------------------------------------------------------- batched scan


def _left_refine(db: TpuLevelDB, queries, p, d_pick, d_app, kappa_mult,
                 row_fn):
    """One vectorized left-propagation pass over a resolved row.

    Adds the same-row coherence candidates {s(j-d) + (0, d)} (d = 1..radius)
    computed from the CURRENT row estimate, and re-runs the kappa decision.
    `d_pick` is the distance of the currently-picked source (inf where the
    approx candidate was picked — the kappa rule only switches to a coherence
    candidate if it beats d_app * kappa_mult; among coherence candidates the
    closest wins).
    """
    wb = queries.shape[0]
    jcol = jnp.arange(wb)
    radius = int(round(int(db.off.shape[0]) ** 0.5)) // 2
    best_d, best_p = d_pick, p
    _, wa = db.a_dims()
    for d in range(1, radius + 1):
        pj = jnp.roll(p, d)  # p[j-d] aligned at j
        si = pj // wa
        sj = pj % wa + d
        ok = (jcol >= d) & (sj < wa)
        cand = si * wa + jnp.minimum(sj, wa - 1)
        cf = row_fn(cand)
        dc = jnp.sum((cf - queries) ** 2, axis=1)
        dc = jnp.where(ok, dc, jnp.inf)
        passes = dc <= d_app * kappa_mult
        better = passes & (dc < best_d)
        best_p = jnp.where(better, cand, best_p)
        best_d = jnp.where(better, dc, best_d)
    return best_p.astype(jnp.int32), best_d


def batched_scan_core(db: TpuLevelDB, kappa_mult, approx_fn,
                      row_fn=None, afilt_fn=None):
    """The batched level scan given an approximate-match function.

    `approx_fn(queries (W,F)) -> (idx, sqdist)` is the pluggable piece: the
    local fused Pallas kernel, or its mesh-sharded variant (local kernel +
    min/argmin all-reduce over the 'db' axis — parallel/step.py calls this
    core from inside shard_map for the multi-chip video step).  `row_fn` /
    `afilt_fn` gather scoring-DB rows / A' values by global index — direct
    gathers by default, psum-gathers of the SHARDED arrays on the mesh so no
    chip ever holds the whole DB (parallel/step.py).

    Returns (bp, s, counts) with counts = [n_coherence_picks (pre-refine,
    comparable with the CPU oracle's stat), n_refined_picks (picks the
    left-propagation refinement switched to a same-row candidate)].

    Query bucketing (TpuLevelDB.dims_b): the carry is sized by
    ``static_q``'s (possibly bucketed) row count while the row loop runs
    only to the REAL hb — padded query rows are never read, never
    scored, never written, so padded lanes cannot influence real lanes'
    argmins and the caller crops the trailing pad rows off bp/s.
    Unbucketed (dims_b None) the shapes and bounds are the ints they
    always were — the generated HLO is unchanged.
    """
    nf = int(db.off.shape[0])
    nrs = db.n_rowsafe
    wb = db.wb  # ALWAYS static: the dynamic_slice width in _row_queries
    hb = db.b_dims()[0]
    if row_fn is None:
        row_fn = lambda i: db.db_rowsafe[i]
    if afilt_fn is None:
        afilt_fn = lambda i: db.a_filt_flat[i]

    def row_body(r, state):
        bp, s, counts = state
        q0 = r * wb
        queries = _row_queries(db, r, bp, db.rowsafe)
        p_app, d_app = approx_fn(queries)

        # rows-above coherence candidates (positions known at row start)
        idx_c = jax.lax.dynamic_slice(
            db.flat_idx, (q0, 0), (wb, nf))[:, :nrs]
        ok = (jax.lax.dynamic_slice(db.valid, (q0, 0), (wb, nf))[:, :nrs]
              > 0)
        p_coh, d_coh, has_coh = _batched_coherence(
            db, s, queries, idx_c, ok, nrs, row_fn)

        use_coh = has_coh & (d_coh <= d_app * kappa_mult)
        p = jnp.where(use_coh, p_coh, p_app).astype(jnp.int32)
        d_pick = jnp.where(use_coh, d_coh, jnp.inf)

        # restore same-row left-propagation with cheap vectorized passes
        for _ in range(db.refine_passes):
            p, d_pick = _left_refine(db, queries, p, d_pick, d_app,
                                     kappa_mult, row_fn)

        bp = jax.lax.dynamic_update_slice(bp, afilt_fn(p), (q0,))
        s = jax.lax.dynamic_update_slice(s, p, (q0,))
        n_coh = use_coh.sum(dtype=jnp.int32)
        n_ref = (d_pick < jnp.inf).sum(dtype=jnp.int32) - n_coh
        return bp, s, counts + jnp.stack([n_coh, n_ref])

    nq = db.static_q.shape[0]  # == hb*wb unbucketed; the bucket otherwise
    bp0 = jnp.zeros((nq,), _F32)
    s0 = jnp.zeros((nq,), jnp.int32)
    return jax.lax.fori_loop(0, hb, row_body,
                             (bp0, s0, jnp.zeros((2,), jnp.int32)))


def make_approx_fn(db: TpuLevelDB):
    """The strategy's approximate-match fn (queries (M,F)) -> (idx, sqdist):
    pre-padded Pallas kernel > plain dispatch (the mesh-sharded case never
    reaches here — synthesize_level routes db.mesh through parallel/step.py,
    whose shard_map supplies its own all-reduced approx_fn).  Which DB it
    scores against (rowsafe-masked or full) was decided when the pre-padded
    arrays were built in `build_features`.

    Kernel precision: the wavefront strategy needs fp32-grade scores so its
    anchor picks match the oracle's argmin (HIGHEST, 3 bf16 MXU passes); the
    approximate batched/rowwise strategies keep the fast single-pass DEFAULT
    — their picks are heuristic anyway and tolerate ~1e-3 score error."""
    precision = (jax.lax.Precision.HIGHEST if db.strategy == "wavefront"
                 else jax.lax.Precision.DEFAULT)
    if db.ann_dbp is not None and db.strategy != "wavefront":
        # Two-stage ANN (ISSUE 13): rank ALL rows in the Kp-dim projected
        # space (one cheap matmul), exact-fp32 re-score only the top-m
        # slab against the SAME rowsafe DB the one-stage scan scores.
        # Only built when ann_prefilter passed the parity gate; slab
        # size resolves through tune (override > env > store > packaged
        # > default) at trace time like every other geometry knob.
        from image_analogies_tpu.ops.pallas_match import (
            ann_rescore_slab, ann_topm_candidates)

        top_m = tune.ann_top_m()

        def approx_fn(queries):
            na = db.a_rows()
            cand = ann_topm_candidates(queries, db.ann_proj, db.ann_mean,
                                       db.ann_dbp, db.ann_dbnh, na, top_m)
            return ann_rescore_slab(queries, db.db_rowsafe, cand, na)

        return approx_fn
    if db.db_pad is not None:
        def approx_fn(queries):
            tile = tune.tile_rows(
                queries.shape[1], strategy=db.strategy,
                dtype=str(db.db_pad.dtype), n_rows=db.db_pad.shape[0])
            return prepadded_argmin_queries(
                queries, db.db_pad, db.dbn_pad,
                tile_n=tune.snap_tile_to_divisor(tile, db.db_pad.shape[0]),
                precision=precision)
    elif db.strategy == "wavefront":
        def approx_fn(queries):
            return argmin_l2(queries, db.db, db.db_sqnorm,
                             precision=precision)
    else:
        def approx_fn(queries):
            return argmin_l2(queries, db.db_rowsafe, db.db_rowsafe_sqnorm,
                             precision=precision)
    return approx_fn


@jax.jit
def _run_batched(db: TpuLevelDB, kappa_mult):
    return batched_scan_core(db, kappa_mult, make_approx_fn(db))


# rescue breadth of the scan_rescue anchor: the exact fp32 re-score covers
# the top-T tile champions by scan score.
_RESCUE_T = 8

# match_mode="auto" DB-size crossover between the two parity scans: packed
# 2-pass (exact_hi2_2p) at or above this many DB rows, merged HIGHEST
# (exact_hi) below — measured round 3 (256^2 levels: exact_hi faster;
# 512^2 level 0: packed faster).  The ONE definition read by the
# single-chip auto resolution AND packed_scan_eligible (round-3 ADVICE:
# the two sites must not carry separate literals).
_PACKED_CROSSOVER_ROWS = 131072


def packed_scan_eligible(match_mode: str, na_rows: int) -> bool:
    """THE steering predicate for the packed 2-pass parity scan, shared by
    the single-chip auto resolution and BOTH sharded paths (image and
    video) so the eligible-mode set and the measured DB-size crossover
    (`_PACKED_CROSSOVER_ROWS`) can never drift between them: auto packs
    above the crossover; explicit exact_hi2_2p always packs; every other
    mode (including exact_hi2, whose 3-pass set has no mesh kernel) pins
    the HIGHEST merged scan on meshes."""
    return (match_mode in ("auto", "exact_hi2_2p")
            and (match_mode != "auto"
                 or na_rows >= _PACKED_CROSSOVER_ROWS))


# The champion-scan tile helpers (power-of-two snap to npad's divisors,
# >= 16-tile grids, the VMEM-aware packed cap) live in tune.geometry;
# call sites below resolve them through tune.resolve so a measured store
# entry or env override replaces the legacy numbers per device class.


def make_anchor_fn(db: TpuLevelDB, defer_rescore: bool = False):
    """The wavefront strategy's full-DB anchor: (queries (M,F)) ->
    (p_app (M,) int32, d_app (M,) fp32 EXACT squared distance).

    With ``defer_rescore`` (packed modes carrying ``db_live`` only) the
    anchor returns (p_app, None) and the caller computes d_app through
    the coherence block's fused row gather (`_batched_coherence(p_app=)`)
    — same value, one fewer per-step gather.

    Both modes end in an exact fp32 re-score against the fp32 DB, so d_app —
    the kappa rule's threshold — is always oracle-grade; the modes differ in
    how the candidate pick(s) come off the MXU:

    - "two_pass" (default): ONE bf16 MXU pass over the bf16-resident padded
      DB tracking the global top-2 (score, index) pairs, then fp32 re-score
      of BOTH candidates; the (val, idx)-lexicographic min wins, so a bf16
      rank-1/2 inversion never changes the pick and exact ties stay
      lowest-index (identical rows quantize identically, so their bf16
      scores still tie exactly).  ~3x less MXU work + half the HBM stream
      of exact_hi.
    - "exact_hi": fp32-grade scores inside the kernel (HIGHEST, 3 bf16
      passes), single candidate — round-2 behavior, the A/B baseline.

    The mesh-sharded step never comes here: parallel/step.py builds its own
    anchor over the all-reduced sharded argmin."""
    if db.match_mode == "ann_rescue" and db.ann_dbp is not None:
        # Two-stage ANN anchor (ISSUE 13): the prefilter ranks every DB
        # row in the Kp-dim PCA subspace (one (M, Na) matmul over Kp-wide
        # operands — ~F/Kp cheaper than the exact scan), the exact fp32
        # re-score covers only the top-m slab, and the winner keeps the
        # oracle's lowest-index tie rule within the slab.  A slab miss of
        # the true argmin is exactly what the parity gate's audited probe
        # bounds: the mode is only reachable after the audit came back
        # fully tie-explained on this device class + strategy.
        top_m = tune.ann_top_m()
        na = db.a_rows()

        def anchor(queries):
            from image_analogies_tpu.ops.pallas_match import (
                ann_rescore_slab, ann_topm_candidates)

            cand = ann_topm_candidates(queries, db.ann_proj, db.ann_mean,
                                       db.ann_dbp, db.ann_dbnh, na, top_m)
            return ann_rescore_slab(queries, db.db, cand, na)

        return anchor

    if (db.match_mode in ("scan_rescue", "scan_rescue_1p")
            and db.db_pad is not None
            and db.db_pad.dtype == jnp.bfloat16):
        # Per-tile champion scan + top-T rescue (round-3 VERDICT item 1):
        # ONE minimal-VPU kernel pass emits each DB tile's best (score, row)
        # under the bf16 centered metric; XLA takes the T best tiles per
        # query, re-scores those T rows in exact fp32 (elementwise — no
        # cancellation), and the (distance, index)-lexicographic min wins.
        # Beats two_pass's global top-2 on BOTH axes: ~2x less VPU
        # reduction work in the kernel, and a T-deep re-score set that
        # recovers the true argmin through a much wider scan-error band.
        q_split = db.match_mode == "scan_rescue"  # _1p: 1-pass probe mode
        npad, fp = db.db_pad.shape
        tile = tune.scan_tile(npad, fp, strategy=db.strategy, dtype="bf16")
        ntiles = npad // tile
        t_rescue = min(_RESCUE_T, ntiles)
        na = db.a_rows()

        def anchor(queries):
            qc = queries - db.feat_mean[None, :queries.shape[1]]
            vals, idx = pertile_champions_queries(
                qc, db.db_pad, db.dbnh_pad, tile_n=tile, q_split=q_split)
            if t_rescue < ntiles:
                vals, tsel = jax.lax.top_k(vals, t_rescue)
                cand = jnp.take_along_axis(idx, tsel, axis=1)
            else:
                cand = idx
            # champions of all-padding tiles carry out-of-range rows (score
            # -inf); clamp to the last real row — it can at worst TIE the
            # real champion of the final partial tile and then loses the
            # (d, idx) tie on its larger index.
            cand = jnp.minimum(cand, na - 1)
            cf = db.db[cand]  # (M, T, F) fp32 rows
            d = jnp.sum((cf - queries[:, None, :]) ** 2, axis=-1)
            bv, bi = d[:, 0], cand[:, 0]
            for k in range(1, int(cand.shape[1])):
                better = _lex_lt(d[:, k], cand[:, k], bv, bi)
                bv = jnp.where(better, d[:, k], bv)
                bi = jnp.where(better, cand[:, k], bi)
            return bi.astype(jnp.int32), bv

        return anchor

    if (db.match_mode in ("exact_hi2", "exact_hi2_2p")
            and db.db_pad is not None and db.dbnh_pad is not None
            and db.live_idx is not None
            and (db.db_pad2 is not None
                 or db.match_mode == "exact_hi2_2p")):
        # Packed fp32-grade scan (the fast PARITY kernel).  jax HIGHEST on
        # fp32 operands is bf16_6x — SIX MXU passes (measured: the
        # per-pass cost fit is 898 = 1x445 + 450 fixed, 3123 = 6x445 + 450
        # us at M=344/Na=1M, experiments/step_cost_probe.py).  Its product
        # set over 3-way bf16 splits q = q1+q2+q3, d = d1+d2+d3 keeps the
        # six products with coefficient > 2^-24.  Only L ~ 55 of the 128
        # padded lanes are query-LIVE (13 fine-filt positions are
        # identically zero in every query, the rest is padding), so those
        # six products fit in THREE stacked K=128 passes against two
        # packed weight arrays W1 = [d1|d2] (rows [q1|q1], [q2|q2]) and
        # W2 = [d3|d1] (row [q1|q3]) — 2x fewer passes than HIGHEST over
        # bf16 streams instead of fp32, at the same score-resolution
        # class.  Dead dims enter scores exactly via the norm term.
        #
        # exact_hi2_2p drops the set's two smallest members (q2.d2, q3.d1,
        # both ~2^-16 coefficient): rows [q1|q1].W1 + [q2|q1].[d1|d3] — 2
        # passes.  Its per-decision index drift vs HIGHEST is ~2x
        # exact_hi2's (8.6% vs 4.0% at 512^2 level 0, ALL value-equal
        # near-ties), end-to-end parity evidence in BENCH_r03.
        live_idx = db.live_idx  # the derivation the DB lanes were packed by
        npad, pk = db.db_pad.shape
        na = db.a_rows()
        two_pass = db.match_mode == "exact_hi2_2p"
        if two_pass:
            # round-5 tile raise, VMEM-bounded (tune.geometry
            # vmem_bounded_tile_cap, resolved through the store/env)
            tile = tune.scan_tile(
                npad, pk, strategy=db.strategy, dtype="packed2",
                cap_rows=tune.packed_tile_cap(
                    db.hb, db.wb, int(db.off.shape[0]),
                    strategy=db.strategy, dtype="packed2", fp=pk,
                    n_rows=npad))
            vmem_limit = tune.packed_vmem_limit(
                strategy=db.strategy, dtype="packed2", fp=pk, n_rows=npad)
        else:
            # exact_hi2's 3-pass kernel (packed3_best) has no vmem_limit
            # plumbing and streams THREE weight arrays per tile — keep
            # the round-4 4096-row cap it was sized for
            tile = tune.scan_tile(npad, pk, cap_rows=4096,
                                  strategy=db.strategy, dtype="packed")

        def anchor(queries):
            qc = queries - db.feat_mean[None, :queries.shape[1]]
            g1, g2, gr = bf16_split3(qc[:, live_idx])  # (M, L)
            q1 = g1.astype(jnp.bfloat16)
            q2 = g2.astype(jnp.bfloat16)
            # Round-4 fusions (step-cost decomposition in
            # experiments/step_decompose_probe.py; the scan is
            # VPU-reduction-bound, not HBM-bound):
            # - in-kernel champion: the kernel's running scratch resolves
            #   the global winner (strict improvement = earlier tile wins
            #   ties, bit-equal to the old per-tile-champions +
            #   XLA-argmax pipeline — locked by tests/test_pallas_kernel)
            # - 2p only: the K-wide single-array layout (packed2k_best) —
            #   norms ride W lanes, cross-block accumulation rides the
            #   MXU accumulator; VPU work is down to max + argmax.  Norm
            #   lanes perturb scores ~2^-24-relative — fp-band ties the
            #   audit explains.
            # A single-stream variant that also dropped the q1.d3 term
            # was measured and REJECTED: explained 0.999873 < 0.9999 and
            # first divergence not a tie at 256^2 (parity needs the full
            # 2p product set, full stop).
            if two_pass:
                p, _ = packed2k_best(q1, q2, db.db_pad, tile_n=tile,
                                     vmem_limit=vmem_limit)
            else:
                p, _ = packed3_best(
                    q1, q2, gr.astype(jnp.bfloat16), db.db_pad, db.db_pad2,
                    db.dbnh_pad, tile_n=tile)
            p = jnp.minimum(p, na - 1)
            if defer_rescore and db.db_live is not None:
                # the wavefront step re-scores p through the SAME db_live
                # row gather as its coherence candidates (d_app = None
                # signals the fused path) — one fewer M-row fetch/step
                return p, None
            if db.db_live is not None:
                # live/dead-split exact re-score (see TpuLevelDB.db_live;
                # column L is the dead norm — L+1, when present, is the
                # round-5 A' value, not a score term)
                lw = live_idx.shape[0]
                g = db.db_live[p]
                d = (jnp.sum((g[:, :lw] - queries[:, live_idx]) ** 2,
                             axis=1) + g[:, lw])
                return p, d
            return p, jnp.sum((db.db[p] - queries) ** 2, axis=1)

        return anchor

    if (db.match_mode in ("two_pass", "two_pass_1p")
            and db.db_pad is not None
            and db.db_pad.dtype == jnp.bfloat16):
        q_split = db.match_mode == "two_pass"  # _1p: single-pass probe mode
        # q_split doubles the kernel's query rows, so its (2M, tile_n)
        # score block needs half the tile to stay inside scoped VMEM
        tile = tune.tile_rows(
            db.static_q.shape[1], strategy=db.strategy, dtype="bf16",
            n_rows=db.db_pad.shape[0]) // (2 if q_split else 1)

        def anchor(queries):
            qc = queries - db.feat_mean[None, :queries.shape[1]]
            i1, i2, ok2 = prepadded_argmin2_queries(
                qc, db.db_pad, db.dbn_pad, tile_n=tile, q_split=q_split)
            d1 = jnp.sum((db.db[i1] - queries) ** 2, axis=1)
            d2 = jnp.where(ok2, jnp.sum((db.db[i2] - queries) ** 2, axis=1),
                           jnp.inf)
            use2 = _lex_lt(d2, i2, d1, i1)
            return (jnp.where(use2, i2, i1).astype(jnp.int32),
                    jnp.where(use2, d2, d1))

        return anchor

    approx = make_approx_fn(db)

    def anchor(queries):
        p, _ = approx(queries)
        return p, jnp.sum((db.db[p] - queries) ** 2, axis=1)

    return anchor


# ------------------------------------------------------------ wavefront scan


def _wavefront_rows_guard(db: TpuLevelDB) -> None:
    """Refuse A-row counts the packed carry cannot index exactly.

    Source-map indices ride an f32 lane of the packed (Nb, 2) carry
    (exact only below 2^24 — a 4096^2 exemplar; see the gather comment).
    Explicit raise, not assert: `python -O` must not strip the guard.
    Bucketed levels (static ha/wa = 0 sentinel) check the PADDED row
    count instead — conservative-safe: real indices are strictly below
    it, and the host guard cannot read a traced extent.  Called from
    `synthesize_level` (host side, EVERY dispatch — the in-core check
    alone only fires at trace time, so a jit cache hit would skip a
    freshly lowered tune bound) and from `wavefront_scan_core` itself
    for direct callers.
    """
    a_rows_bound = (db.ha * db.wa if db.dims_a is None else db.db.shape[0])
    max_rows = tune.wavefront_max_rows(
        dtype="f32", fp=db.db.shape[1], n_rows=a_rows_bound)
    if a_rows_bound > max_rows:
        raise ValueError(
            f"the wavefront strategy caps exemplars at "
            f"{max_rows} A rows (<= the 2^24 f32-exactness ceiling; a "
            f"4096x4096 A — tune knob wavefront_max_rows / env "
            f"IA_WAVEFRONT_ROWS can only lower it): this A is "
            f"{db.ha}x{db.wa} = {a_rows_bound}.  Why: the scan's packed "
            f"(Nb, 2) carry stores source-map indices as exact f32 VALUES "
            f"(exact only below 2^24; int bit patterns in f32 lanes are "
            f"denormal-flushed by real TPU data paths — measured round "
            f"4).  Workarounds: strategy='batched' (no packed carry; a "
            f"different but comparable synthesis), or downsample A/A' — "
            f"and note a >2^24-row DB also exceeds the HBM the scan "
            f"needs, so multi-chip db_shards with the batched strategy "
            f"is the supported route at that scale.")


def wavefront_scan_core(db: TpuLevelDB, kappa_mult, anchor_fn,
                        row_fn=None, afilt_fn=None, live_gather=None,
                        data_axis=None, data_axis_size: int = 1):
    """The parity fast path (VERDICT.md round-1 item 1): the oracle's exact
    algorithm on an anti-diagonal schedule.

    The raster scan's loop-carried dependency is bounded: pixel (i, j)'s
    causal feature window and coherence candidates read only pixels
    (i', j') with i' < i, j' <= j + r  or  i' == i, j' < j  (r = patch
    radius) — including every edge-CLAMPED window position, whose clamp
    target also satisfies the bound.  Skewing time as t(i, j) = j + (r+1)*i
    makes every dependency strictly earlier:

        same row   (i, j-d):    t' = t - d            < t
        rows above (i-k, j+d):  t' = t + d - (r+1)*k  <= t - (r+1-d) < t
                                                         (d <= r, k >= 1)

    so all pixels of one diagonal are independent given previous diagonals
    and resolve in ONE batch: the anchor (fused Pallas full-DB scan + exact
    fp32 re-score — `make_anchor_fn`), batched Ashikhmin coherence over the
    full causal window, kappa rule (Hertzmann §3.2 eq. 2).  Every per-pixel
    decision sees the same dependency values as the oracle's raster scan, so
    the output IS the oracle's up to fp tie-breaks — no Gauss-Seidel
    iteration, no sequential inner loop, ~(W + (r+1)H) batched steps per
    level.

    The per-pixel window indices and causal/written masks are iota math on
    the diagonal's pixel ids — NOT gathers of precomputed (Nb, p^2) maps
    (the maps cost ~300 MB HBM + a triple gather per step at 1024^2; the
    math is a handful of VPU ops).  Semantics are identical: flat indices
    clamp at the edges, `written` tests clamped-index < pixel-index,
    exactly as `_gather_maps_device` builds them.

    All scoring uses the oracle's metric: FULL A/A' DB rows against
    zero-masked causal queries (the cKDTree metric), not the batched
    strategy's symmetric rowsafe-masked one.
    """
    nb = db.hb * db.wb
    hb, wb = db.hb, db.wb
    _wavefront_rows_guard(db)
    if data_axis is not None and (
            data_axis_size & (data_axis_size - 1) or data_axis_size > 8):
        raise ValueError(
            f"query-parallel wavefront needs a power-of-two data axis "
            f"<= 8 (segment widths are 8-aligned); got {data_axis_size}")
    # live/dead-split coherence scoring: single-chip when the build
    # carries db_live; on the mesh when the step supplies `live_gather`
    # (a psum-gather of the SHARDED db_live — round-5 gather diet)
    use_live = (db.live_idx is not None
                and ((row_fn is None and db.db_live is not None)
                     or live_gather is not None))
    if row_fn is None:
        row_fn = lambda i: db.db[i]
    if afilt_fn is None:
        afilt_fn = lambda i: db.a_filt_flat[i]

    # causal-window invariants: window_offsets is raster-ordered, so the
    # causal positions (strictly before center) are EXACTLY the first
    # nc = (nf-1)/2 columns.  Row gathers on TPU cost per ROW (lane
    # padding makes 37 and 128 columns the same fetch — trace-verified,
    # BASELINE.md), so the bp window gather and the coherence candidate
    # gathers slice to the causal prefix instead of gathering all nf
    # positions and masking half of them to +inf: identical semantics
    # (non-causal candidates could never win), ~2x fewer gathered rows.
    nf = int(db.off.shape[0])
    nc = (nf - 1) // 2
    off_i = db.off[:, 0][None, :]  # (1, nf)
    off_j = db.off[:, 1][None, :]

    def make_step(seg):
        def step(t, state):
            bps, n_coh = state
            pix = seg[t]  # (M,) flat indices, -1 on short diagonals
            lane_ok = pix >= 0
            pixc = jnp.maximum(pix, 0)
            if data_axis is not None:
                # QUERY-PARALLEL single image (round-5, SURVEY §5.7): the
                # diagonal's M lanes split over the mesh's `data` axis
                # RIGHT HERE, so the window math, the bps/static_q
                # gathers, the query build, the anchor scan, and the
                # coherence block all run on an M/D slice; the final
                # (p, A', use_coh) all_gather back so every chip's
                # replicated carry advances identically.  Slicing is
                # semantically a no-op (per-query work never reads across
                # queries), so picks are bit-equal to the unsliced step
                # (locked by test_wavefront_query_parallel_...).  Segment
                # widths are 8-aligned, so any power-of-two D <= 8
                # divides M (checked at entry).  `pix`/`lane_ok` stay
                # full-width for the scatter.
                mq = int(pix.shape[0]) // data_axis_size
                me = jax.lax.axis_index(data_axis)
                pixc = jax.lax.dynamic_slice_in_dim(pixc, me * mq, mq, 0)
            qi = pixc // wb
            qj = pixc - qi * wb
            wi = qi[:, None] + off_i[:, :nc]
            wj = qj[:, None] + off_j[:, :nc]
            inb = (wi >= 0) & (wi < hb) & (wj >= 0) & (wj < wb)
            idx = (jnp.clip(wi, 0, hb - 1) * wb
                   + jnp.clip(wj, 0, wb - 1))  # (M, nc) edge-clamped
            written = (idx < pixc[:, None]).astype(_F32)
            # ONE gather serves both the query build (B' values, lane 0)
            # and the coherence candidates (source-map indices as exact
            # f32 VALUES in lane 1) — the window positions are the same
            # (M, nc) set, and TPU gathers cost per row.  Values, not a
            # bitcast: int bit patterns stored in f32 lanes are DENORMAL
            # for small ints and real TPU data paths flush them to zero
            # (measured round 4: bitcast packing scored SSIM 0.69 on-chip
            # while CPU stayed bit-exact); f32<->int conversion is exact
            # for indices < 2^24, guarded at build time by the resolved
            # wavefront_max_rows bound (clamped to that ceiling).
            g = bps[idx]  # (M, nc, 2)
            dyn = g[..., 0] * written * db.fine_sqrtw[None, :nc]
            s_r = g[..., 1].astype(jnp.int32)
            m = int(dyn.shape[0])
            dyn_full = jnp.zeros((m, nf), _F32).at[:, :nc].set(dyn)
            queries = jax.lax.dynamic_update_slice(
                db.static_q[pixc], dyn_full, (0, db.fine_start))
            p_app, d_app = anchor_fn(queries)

            # batched Ashikhmin coherence over the causal window, scored
            # against the FULL DB (the oracle's metric; live/dead split
            # on the single-chip TPU path and the live-gathering mesh
            # path — same metric, fewer gathered rows / smaller psum).
            # When the anchor deferred its re-score (d_app None), p_app
            # rides the same row gather as the candidates, and when the
            # rows carry the A' column the output value does too.
            af_pair = None
            if use_live and d_app is None:
                out = _batched_coherence(
                    db, None, queries, idx, inb, nc, row_fn,
                    q_live=queries[:, db.live_idx], s_r=s_r, p_app=p_app,
                    live_gather=live_gather)
                p_coh, d_coh, has_coh, d_app = out[:4]
                if len(out) > 4:
                    af_pair = out[4:]
            else:
                p_coh, d_coh, has_coh = _batched_coherence(
                    db, None, queries, idx, inb, nc, row_fn,
                    q_live=(queries[:, db.live_idx] if use_live else None),
                    s_r=s_r, live_gather=live_gather)

            use_coh = has_coh & (d_coh <= d_app * kappa_mult)
            p = jnp.where(use_coh, p_coh, p_app).astype(jnp.int32)
            if data_axis is not None:
                # reassemble the full diagonal: lane order is preserved
                # (tile k of the gather is data row k's slice)
                p = jax.lax.all_gather(p, data_axis, tiled=True)
                use_coh = jax.lax.all_gather(use_coh, data_axis,
                                             tiled=True)
                if af_pair is not None:
                    af_pair = tuple(
                        jax.lax.all_gather(x, data_axis, tiled=True)
                        for x in af_pair)
            # write only live lanes: -1 padding -> OOB sentinel, dropped.
            # Each pad lane gets a DISTINCT OOB sentinel (nb + lane) so the
            # index vector is fully unique (the schedule's live lanes are
            # strictly increasing flat indices, pads at the end), letting
            # the scatter lower with unique_indices=True: measured -0.35 s
            # on the north star.  indices_are_sorted=True — also true of
            # this vector — was tried and REJECTED: it lowers to a path
            # that cost +0.9 s end-to-end on this toolchain.
            wpix = jnp.where(lane_ok, pix,
                             nb + jax.lax.iota(jnp.int32, pix.shape[0]))
            if af_pair is not None:
                # A' value came back with the fused row gather — no
                # separate a_filt_flat fetch
                af = jnp.where(use_coh, af_pair[0], af_pair[1])
            else:
                af = afilt_fn(p)
            row = jnp.stack([af, p.astype(_F32)], axis=-1)
            bps = bps.at[wpix].set(row, mode="drop", unique_indices=True)
            return bps, n_coh + (use_coh & lane_ok).sum(dtype=jnp.int32)

        return step

    # the schedule comes in width-bucketed segments (see _diag_schedule):
    # one fori_loop per segment, chained in t order — identical semantics,
    # each segment's batch padded only to its own max diagonal width
    state = (jnp.zeros((nb, 2), _F32), jnp.int32(0))
    for seg in db.diag:
        state = jax.lax.fori_loop(0, int(seg.shape[0]), make_step(seg),
                                  state)
    bps, n_coh = state
    return bps[:, 0], bps[:, 1].astype(jnp.int32), n_coh


def _run_wavefront_impl(db: TpuLevelDB, kappa_mult):
    return wavefront_scan_core(db, kappa_mult,
                               make_anchor_fn(db, defer_rescore=True))


_run_wavefront = jax.jit(_run_wavefront_impl)

# Donated twins (perf PR 8, SNIPPETS [3] donate_argnums pattern): every
# array leaf of the level's TpuLevelDB — the DB panes, the packed pads,
# static queries, the chained-plane-derived buffers AND the wavefront
# step carry XLA allocates from them — may be reused in place for the
# level's outputs instead of allocating fresh HBM.  Safe because the
# single-chip build produces FRESH buffers for every leaf (prepare-jit
# outputs, per-call device_puts) and the driver only routes a level here
# when nothing else can read them (LevelJob.donate: no retries, no
# keep_levels/checkpoint/save-levels consumers — models/analogy.py).
# The batched twin keeps the lru-cached (Nb, p^2) gather maps OUT of the
# donated argument (donating a cached buffer would poison every later
# level/run that cache serves); the wavefront DB carries 1-row map
# placeholders, so its whole pytree donates.
_run_wavefront_donated = jax.jit(_run_wavefront_impl, donate_argnums=(0,))


@functools.partial(jax.jit, donate_argnums=(0,))
def _run_batched_donated(db: TpuLevelDB, maps, kappa_mult):
    import dataclasses
    db = dataclasses.replace(db, flat_idx=maps[0], valid=maps[1],
                             written=maps[2])
    return batched_scan_core(db, kappa_mult, make_approx_fn(db))


def _donation_safe_db(db: TpuLevelDB) -> TpuLevelDB:
    """Re-materialize any db leaf that shares a device buffer with an
    earlier leaf.  Donation requires every donated leaf to own its
    buffer: the template aliases placeholder zeros (valid/written are one
    array) and XLA CSE may alias identical prepare outputs — donating
    one buffer through two parameters is a runtime error on real TPUs.
    Copies only the aliased leaves (tiny placeholders in practice)."""
    leaves, treedef = jax.tree_util.tree_flatten(db)
    seen = set()
    out = []
    for leaf in leaves:
        if isinstance(leaf, jax.Array):
            try:
                key = leaf.unsafe_buffer_pointer()
            except Exception:  # multi-device/committed: object identity
                key = id(leaf)
            if key in seen:
                leaf = jnp.array(leaf)
            else:
                seen.add(key)
        out.append(leaf)
    return jax.tree_util.tree_unflatten(treedef, out)


# Whole-level scan programs: shimmed like the preparation jits (the
# TpuLevelDB pytree's static aux — strategy/match_mode/geometry — is part
# of the shim's program key, so a key hit is exactly a jit cache hit).
_run_exact = obs_device.instrument(_run_exact, "tpu.run_exact")
_run_rowwise = obs_device.instrument(_run_rowwise, "tpu.run_rowwise")
_run_batched = obs_device.instrument(_run_batched, "tpu.run_batched")
_run_wavefront = obs_device.instrument(_run_wavefront, "tpu.run_wavefront")
_run_wavefront_donated = obs_device.instrument(
    _run_wavefront_donated, "tpu.run_wavefront_donated")
_run_batched_donated = obs_device.instrument(
    _run_batched_donated, "tpu.run_batched_donated")


# Strategies with the uniform (db, kappa_mult) -> (bp, s, n_coh) signature;
# "batched" (counts vector) is dispatched explicitly in synthesize_level.
_RUNNERS = {
    "exact": _run_exact,
    "rowwise": _run_rowwise,
    "wavefront": _run_wavefront,
}


# ----------------------------------------------------- batched-lane runner


@jax.jit
def _run_lanes(db: TpuLevelDB, qsides, kappa_mult):
    """ONE device program synthesizing k B' lanes (batch/engine.py).

    ``db`` is lane 0's full TpuLevelDB — the A/A' scoring arrays are
    shared by construction (the engine preflights that every member
    preps the identical A planes); ``qsides`` is a dict of the QUERY-
    side leaves (static_q, flat_idx, valid, written, and dims_b when
    bucketed), each stacked on a leading lane axis — everything about a
    member that depends on its own B plane, so same-bucket members with
    DIFFERENT real row counts still share this one program (each lane's
    scan bound rides its own traced hb).  Each lane is the EXACT
    singleton scan (`batched_scan_core` / `wavefront_scan_core` with
    the same anchor machinery) vmapped over the query side only, so the
    compiled program is the batched twin of the singleton program: same
    contraction shapes, same gathers, same kappa rule — bit-identity
    per lane is locked by tests/test_batch.py and the loadgen selftest
    gate.  Returns (bp (k, Nq), s (k, Nq), counts (k, 2)).
    """
    import dataclasses

    def lane(qside):
        lane_db = dataclasses.replace(db, **qside)
        if db.strategy == "wavefront":
            bp, s, n_coh = wavefront_scan_core(
                lane_db, kappa_mult,
                make_anchor_fn(lane_db, defer_rescore=True))
            return bp, s, jnp.stack([n_coh, jnp.int32(0)])
        return batched_scan_core(lane_db, kappa_mult,
                                 make_approx_fn(lane_db))

    return jax.vmap(lane)(qsides)


_run_lanes = obs_device.instrument(_run_lanes, "tpu.run_lanes")


# ------------------------------------------------- bf16 scoring parity gate
#
# AnalogyParams.bf16_scoring routes the wavefront anchor through the
# scan_rescue machinery (bf16 per-tile champion scan + exact-f32 top-T
# re-score with the lowest-index tie-break).  Unlike the IA_EXPERIMENTAL
# probe modes it is a supported flag, and the support contract is this
# gate: the FIRST bf16-scored synthesis on a device class runs a small
# deterministic probe twice (exact parity engine vs bf16 engine) and
# audits the source maps with utils/parity.py.  Only a verdict whose
# mismatches are ALL tie-explained (unexplained == 0, first divergence a
# tie) enables the mode; anything else auto-disables it process-wide and
# the synthesis silently keeps the exact parity scan.  The verdict is
# cached per device kind, logged as a "bf16_gate" event, and counted
# (bf16.gate_ok / bf16.disabled_unexplained).

_BF16_GATE: Dict[str, Dict[str, Any]] = {}
_BF16_GATE_LOCK = threading.Lock()
_BF16_TLS = threading.local()  # .probing: True inside the gate's bf16 run


def reset_bf16_gate() -> None:
    """Forget cached gate verdicts (tests re-probe after monkeypatching)."""
    with _BF16_GATE_LOCK:
        _BF16_GATE.clear()


def _bf16_probe_pair(n: int = 32
                     ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Deterministic structured probe inputs: textured enough that fine
    levels carry real near-tie structure, small enough to audit in well
    under a second of device time.  Shared by the bf16 and ANN parity
    gates and the `ia tune --knob ann` sweep (which passes its own n)."""
    yy, xx = np.meshgrid(np.linspace(0.0, 1.0, n, dtype=np.float32),
                         np.linspace(0.0, 1.0, n, dtype=np.float32),
                         indexing="ij")
    a = (0.5 + 0.5 * np.sin(9.0 * xx) * np.cos(7.0 * yy)).astype(np.float32)
    ap = np.clip(0.8 * a + 0.2 * xx, 0.0, 1.0).astype(np.float32)
    b = (0.5 + 0.5 * np.sin(5.0 * xx + 1.3)
         * np.cos(11.0 * yy + 0.7)).astype(np.float32)
    return a, ap, b


def _probe_base_params(params=None, *, levels: int = 2,
                       strategy: str = "wavefront"):
    """The gates' hermetic EXACT baseline params: every approximate /
    resilience / IO knob forced off so a probe run is a pure synthesis
    of the probe pair.  Shared by the bf16 gate, the ANN gate, and the
    `ia tune --knob ann` sweep (which passes no ``params``)."""
    if params is None:
        from image_analogies_tpu.config import AnalogyParams

        params = AnalogyParams()
    return params.replace(
        levels=levels, backend="tpu", strategy=strategy, match_mode="auto",
        bf16_scoring=False, ann_prefilter=False, db_shards=1,
        data_shards=1, temporal_weight=0.0, level_retries=0,
        dispatch_timeout_s=0.0, level_sync=True, checkpoint_dir=None,
        resume_from_level=None, profile_dir=None, log_path=None,
        metrics=False, save_levels_dir=None, pipeline=False,
        donate_buffers=False)


def _bf16_probe_verdict(params) -> Dict[str, Any]:
    """Run the probe pair through both engines and audit (see gate note)."""
    from image_analogies_tpu.models.analogy import create_image_analogy
    from image_analogies_tpu.utils.parity import audit_source_map_mismatches

    base = _probe_base_params(params)
    a, ap, b = _bf16_probe_pair()
    exact = create_image_analogy(a, ap, b, base, keep_levels=True)
    _BF16_TLS.probing = True
    try:
        bf16 = create_image_analogy(a, ap, b,
                                    base.replace(bf16_scoring=True),
                                    keep_levels=True)
    finally:
        _BF16_TLS.probing = False
    audit = audit_source_map_mismatches(a, ap, b, base,
                                        bf16.levels, exact.levels)
    ok = (audit["unexplained"] == 0
          and audit["first_divergence_is_tie"] is not False)
    return {"ok": ok, "mismatches": audit["mismatches"],
            "unexplained": audit["unexplained"],
            "first_divergence_is_tie": audit["first_divergence_is_tie"]}


def _bf16_gate_allows(params) -> bool:
    if getattr(_BF16_TLS, "probing", False):
        return True  # the gate's own bf16 probe run must not recurse
    key = tune.device_kind()
    with _BF16_GATE_LOCK:
        verdict = _BF16_GATE.get(key)
    if verdict is None:
        fresh = _bf16_probe_verdict(params)
        with _BF16_GATE_LOCK:
            verdict = _BF16_GATE.setdefault(key, fresh)
        if verdict is fresh:  # first prober logs/counts the verdict once
            obs_metrics.inc("bf16.gate_ok" if verdict["ok"]
                            else "bf16.disabled_unexplained")
            ctx = obs_trace._CURRENT
            ia_logging.emit(
                {"event": "bf16_gate", "severity":
                 "info" if verdict["ok"] else "warning",
                 "device": key, **verdict},
                ctx.log_path if ctx is not None else None)
    return verdict["ok"]


# ------------------------------------------------- ANN prefilter parity gate
#
# AnalogyParams.ann_prefilter routes the wavefront anchor / batched approx
# scan through the two-stage matcher (PCA prefilter + exact-f32 slab
# re-score).  Same support contract as bf16_scoring, same machinery: the
# FIRST ann-prefiltered synthesis on a (device class, strategy) runs the
# deterministic probe pair through the exact engine and the two-stage
# engine and audits the source maps; only a fully tie-explained verdict
# (unexplained == 0, first divergence a tie) enables the mode — anything
# else caches a refusal (ann.disabled_unexplained) and every synthesis
# silently keeps the exact matcher.  Keyed per strategy too: the two
# strategies prefilter against different DBs (full vs rowsafe-masked),
# so one verdict must not vouch for the other.

_ANN_GATE: Dict[str, Dict[str, Any]] = {}
_ANN_GATE_LOCK = threading.Lock()
_ANN_TLS = threading.local()  # .probing: True inside the gate's ann run


def reset_ann_gate() -> None:
    """Forget cached gate verdicts (tests re-probe after monkeypatching)."""
    with _ANN_GATE_LOCK:
        _ANN_GATE.clear()


@contextlib.contextmanager
def ann_gate_bypass():
    """Run the body with the ANN gate forced open (the `ia tune --knob
    ann` sweep: it audits every candidate itself, and probing the gate
    per candidate would double every measurement)."""
    prev = getattr(_ANN_TLS, "probing", False)
    _ANN_TLS.probing = True
    try:
        yield
    finally:
        _ANN_TLS.probing = prev


def _ann_probe_verdict(params, strategy: str) -> Dict[str, Any]:
    """Probe pair through the exact and two-stage engines + audit."""
    from image_analogies_tpu.models.analogy import create_image_analogy
    from image_analogies_tpu.utils.parity import audit_source_map_mismatches

    base = _probe_base_params(params, strategy=strategy)
    a, ap, b = _bf16_probe_pair()
    exact = create_image_analogy(a, ap, b, base, keep_levels=True)
    _ANN_TLS.probing = True
    try:
        two = create_image_analogy(a, ap, b,
                                   base.replace(ann_prefilter=True),
                                   keep_levels=True)
    finally:
        _ANN_TLS.probing = False
    audit = audit_source_map_mismatches(a, ap, b, base,
                                        two.levels, exact.levels)
    ok = (audit["unexplained"] == 0
          and audit["first_divergence_is_tie"] is not False)
    return {"ok": ok, "mismatches": audit["mismatches"],
            "unexplained": audit["unexplained"],
            "first_divergence_is_tie": audit["first_divergence_is_tie"]}


def _ann_gate_allows(params, strategy: str) -> bool:
    if getattr(_ANN_TLS, "probing", False):
        return True  # the gate's own two-stage probe run must not recurse
    key = f"{tune.device_kind()}|{strategy}"
    with _ANN_GATE_LOCK:
        verdict = _ANN_GATE.get(key)
    if verdict is None:
        fresh = _ann_probe_verdict(params, strategy)
        with _ANN_GATE_LOCK:
            verdict = _ANN_GATE.setdefault(key, fresh)
        if verdict is fresh:  # first prober logs/counts the verdict once
            obs_metrics.inc("ann.gate_ok" if verdict["ok"]
                            else "ann.disabled_unexplained")
            ctx = obs_trace._CURRENT
            ia_logging.emit(
                {"event": "ann_gate", "severity":
                 "info" if verdict["ok"] else "warning",
                 "device": key, "strategy": strategy, **verdict},
                ctx.log_path if ctx is not None else None)
    return verdict["ok"]


# --------------------------------------------- ANN projection resolution


@functools.partial(jax.jit, static_argnames=("dims",))
def _ann_arrays_on_device(src, dims: int):
    """No-catalog fallback: PCA basis + projected DB computed on device
    in one program (no host round-trip of the DB — the PJRT tunnel moves
    ~9 MB/s).  The basis need not bit-match the catalog artifact's
    host-numpy build: ANY basis only steers candidate RANKING, the slab
    re-score is exact fp32 either way, and parity is owned by the gate's
    tie audit — so device eigh determinism is not load-bearing."""
    n, f = src.shape
    kp = max(1, min(int(dims), f, n))
    mean = jnp.mean(src, axis=0)
    xc = src - mean[None, :]
    cov = jnp.dot(xc.T, xc, preferred_element_type=_F32)
    _, vecs = jnp.linalg.eigh(cov)  # ascending eigenvalues
    proj = vecs[:, ::-1][:, :kp]
    dbp = jnp.dot(xc, proj, preferred_element_type=_F32)
    return mean, proj, dbp, 0.5 * jnp.sum(dbp * dbp, axis=1)


@jax.jit
def _ann_project_db(src, mean, proj):
    """Catalog-artifact path: project the scoring DB through the sealed
    basis (the basis itself came off disk, host-side)."""
    dbp = jnp.dot(src - mean[None, :], proj, preferred_element_type=_F32)
    return dbp, 0.5 * jnp.sum(dbp * dbp, axis=1)


def _resolve_ann_projection(job: LevelJob):
    """Resolve this level's ANN basis through the catalog's sealed
    artifacts.  Returns one of:

    - ``("artifact", mean, proj)`` — sealed artifact loaded and verified;
    - ``("fresh",)`` — no catalog / no artifact for this key: compute the
      basis on device (`_ann_arrays_on_device`);
    - ``("rebuild", root, key)`` — an artifact EXISTED but failed its
      seal and was quarantined (``.corrupt``): this request runs the
      exact matcher (bit-identical by construction) and the caller
      rebuilds + re-seals the artifact from the feature bytes so the
      next request recovers the fast path.

    The ``match.prefilter`` chaos site fires here; its ``"corrupt"``
    directive flips one byte of the sealed artifact BEFORE the load —
    the drill (chaos/drills ann_corrupt) then asserts the quarantine +
    exact-fallback + rebuild chain end to end."""
    import os

    from image_analogies_tpu import chaos
    from image_analogies_tpu.catalog import ann as catalog_ann
    from image_analogies_tpu.catalog import tiers as catalog_tiers

    if not catalog_tiers.active():
        return ("fresh",)
    root_dir = catalog_tiers.root()
    key = catalog_tiers.feature_key(job.spec, job.a_src, job.a_filt,
                                    job.a_src_coarse, job.a_filt_coarse,
                                    job.a_temporal)
    path = catalog_ann.artifact_path(root_dir, key)
    directive = chaos.site("match.prefilter", level=job.level)
    if directive == "corrupt":
        catalog_ann.damage_artifact(path, seed=chaos.plan_seed() or 0)
    existed = os.path.exists(path)
    got = catalog_ann.load_artifact(root_dir, key)
    if got is not None:
        obs_metrics.inc("ann.artifact_hits")
        return ("artifact", got[0], got[1])
    if existed:
        return ("rebuild", root_dir, key)
    return ("fresh",)


class TpuMatcher(Matcher):
    """JAX/XLA matcher.  Runs on TPU when one is attached; the same programs
    compile on the CPU backend for the virtual-mesh tests."""

    def build_features(self, job: LevelJob) -> TpuLevelDB:
        import dataclasses

        from image_analogies_tpu.utils.devcache import device_put_cached

        spec = job.spec
        # content-hash upload memoization: identical input planes (the
        # exemplar pair across frames/runs, the B pyramid across warm
        # reps) upload ONCE per process — this tunnel moves ~9 MB/s, so
        # re-uploading the north star's pyramids cost ~1.3 s/run
        # (utils/devcache.py; a changed array hashes to a new key)
        to_j = lambda x: device_put_cached(x, _F32)
        ha, wa = job.a_shape

        strategy = self.params.strategy
        if strategy == "auto":
            strategy = "wavefront"

        # wavefront scores against the FULL DB (the oracle's metric); batched
        # against the rowsafe-masked DB (its symmetric metric).
        pad_full = strategy == "wavefront"
        # single-image mesh forms: db_shards shards the patch DB;
        # data_shards > 1 (wavefront only — create_image_analogy gates)
        # additionally splits each anti-diagonal's queries over 'data'
        # (the round-5 query-parallel form, parallel/step.py)
        sharded = ((self.params.db_shards > 1
                    or (self.params.data_shards > 1
                        and strategy == "wavefront"))
                   and strategy in ("batched", "wavefront"))
        # anchor mode (wavefront only).  The sharded mesh step picks its
        # OWN scan via the `packed` gate below (packed 2-pass when
        # packed_scan_eligible, HIGHEST merged otherwise) — the template's
        # match_mode is forced to exact_hi there only so the single-chip
        # pad machinery stays off.
        mode = self.params.match_mode
        if mode == "auto":
            # (crossover constant: _PACKED_CROSSOVER_ROWS — shared with
            # packed_scan_eligible, the mesh paths' steering predicate)
            # Per-level choice between the two fp32-grade PARITY scans.
            # Only fp32-grade holds index-level oracle parity: measured
            # (experiments/rescue_probe.py), every bf16-resolution scheme
            # fails — the ~1e-5 scan band holds 5..50 near-tied rows per
            # fine-level query (separated by ~1e-6, below bf16 resolution,
            # above fp32-grade's ~7e-7), the picks are value-equal but the
            # index drift feeds different Ashikhmin candidates downstream
            # and the synthesis walks away from the oracle (value_match
            # 0.935 at 256^2).  Between the parity scans: exact_hi2's
            # 3-pass packed kernel wins on large DBs (1.38x end-to-end at
            # 1024^2) but carries more per-step fixed cost (query
            # splitting/packing, champion selection over ~256 tiles), so
            # small levels stay on the merged HIGHEST kernel — measured
            # crossover ~1e5 DB rows (256^2 levels: exact_hi faster;
            # 512^2 level 0: packed faster).  Large levels use the 2-pass
            # variant: its only delta vs exact_hi2 is dropping the two
            # ~2^-16-coefficient products, and the oracle audit stays
            # fully tie-explained (256^2: explained=1.0, unexplained=0,
            # max band 6.3e-7; 1024^2 evidence in BENCH_r03) at ~1.2x
            # less wall-clock.
            mode = ("exact_hi2_2p"
                    if ha * wa >= _PACKED_CROSSOVER_ROWS else "exact_hi")
        if sharded:
            mode = "exact_hi"
        if (self.params.bf16_scoring and strategy == "wavefront"
                and not sharded and _bf16_gate_allows(self.params)):
            # Opt-in fast scoring: bf16 champion scan + exact-f32 top-T
            # re-score.  Only reachable after the parity gate's probe
            # audit came back fully tie-explained on this device class.
            mode = "scan_rescue"
        # Opt-in two-stage ANN matcher (ISSUE 13), gated like bf16 —
        # per (device class, strategy).  When both flags are on, ANN wins
        # for the wavefront anchor (its prefilter already subsumes the
        # scan-rescue bandwidth saving).  Any refused/unsupported request
        # silently runs the exact matcher and counts ann.fallback_exact.
        ann_plan = None
        if (self.params.ann_prefilter
                and strategy in ("wavefront", "batched") and not sharded):
            if _ann_gate_allows(self.params, strategy):
                ann_plan = _resolve_ann_projection(job)
                if ann_plan[0] == "rebuild":
                    # quarantined artifact: THIS level runs exact
                    obs_metrics.inc("ann.fallback_exact")
            else:
                obs_metrics.inc("ann.fallback_exact")
        elif self.params.ann_prefilter:
            obs_metrics.inc("ann.fallback_exact")
        if (ann_plan is not None and ann_plan[0] != "rebuild"
                and strategy == "wavefront"):
            mode = "ann_rescue"
        if strategy != "wavefront":
            pad_mode = "f32"
        elif mode == "exact_hi2":
            pad_mode = "packed"
        elif mode == "exact_hi2_2p":
            pad_mode = "packed2"
        elif mode in ("two_pass", "two_pass_1p", "scan_rescue",
                      "scan_rescue_1p"):
            pad_mode = "bf16"
        else:
            pad_mode = "f32"

        # ONE construction of the query-side maps/schedule/weights for both
        # the sharded and single-chip paths (review round 2: the two paths
        # must not carry separate copies of the causal-mask invariants)
        template = make_level_template(self.params, job, strategy, mode)

        # data_shards > 1 means the multi-frame mesh step (parallel/step.py)
        # supplies its own sharded approx_fn — don't build the single-chip
        # prepadded DB copy it would never read.
        #
        # Shape bucketing (tune/buckets.py, opt-in): pad the DB rows to a
        # canonical bucket and carry the real A extent as the traced
        # dims_a leaf, so the level's jit programs cache on the bucket
        # instead of the exact exemplar size.  Single-chip only — the
        # sharded builders have their own pad geometry.
        db_rows_pad = 0
        q_rows_pad = 0
        hb, wb = job.b_shape
        if (not sharded and self.params.data_shards == 1
                and tune_buckets.buckets_enabled(self.params)):
            db_rows_pad = tune_buckets.bucket_rows(ha * wa)
            if strategy == "batched":
                # QUERY-side bucketing (ROADMAP direction 4 stepping
                # stone): only the batched scan can trace its query row
                # count — its carry is sized by static_q and its row
                # loop bound rides dims_b.  The wavefront scan cannot
                # (packed (Nb, 2) carry + diag schedule are program
                # structure), so it keeps exact-(hb, wb)-keyed programs.
                q_rows_pad = tune_buckets.bucket_rows(hb * wb)
        pad_tile = 0
        if strategy in ("batched", "wavefront") and not sharded \
                and self.params.data_shards == 1 \
                and jax.default_backend() == "tpu":
            n_goal = db_rows_pad or ha * wa
            # multiple of 256 so the champion-scan tile snap always finds
            # a >=256 power-of-2 divisor of the resulting npad
            pad_tile = min(tune.tile_rows(spec.total, strategy=strategy,
                                          dtype=pad_mode, n_rows=n_goal),
                           max((n_goal + 255) // 256 * 256, 256))
        if db_rows_pad:
            template = dataclasses.replace(
                template, ha=0, wa=0,
                dims_a=jnp.asarray([ha, wa], jnp.int32))
        if q_rows_pad:
            # pad the (Nb, nf) gather maps to the query bucket (zero
            # rows — the scan's row loop never reaches them) and carry
            # the real hb as the traced dims_b leaf; wb stays static
            # (the dynamic_slice width in _row_queries).  Fresh padded
            # arrays, so the donated twin's map split stays safe.
            qgrow = q_rows_pad - hb * wb
            template = dataclasses.replace(
                template,
                flat_idx=jnp.pad(template.flat_idx, ((0, qgrow), (0, 0))),
                valid=jnp.pad(template.valid, ((0, qgrow), (0, 0))),
                written=jnp.pad(template.written, ((0, qgrow), (0, 0))),
                hb=0, dims_b=jnp.asarray([hb], jnp.int32))

        if sharded:
            from image_analogies_tpu.parallel.mesh import make_mesh

            mesh = make_mesh(db_shards=self.params.db_shards,
                             data_shards=self.params.data_shards)
            on_tpu = jax.default_backend() == "tpu"
            tile = (tune.tile_rows(spec.total, strategy=strategy,
                                   dtype="f32") if on_tpu else 1)
            # real-TPU wavefront meshes scan with the packed 2-pass
            # kernel per shard (the same exact_hi2_2p parity scan as the
            # single chip); CPU/virtual meshes keep the exact XLA path.
            # One steering predicate shared with the video mesh path.
            packed = (on_tpu and strategy == "wavefront"
                      and packed_scan_eligible(self.params.match_mode,
                                               ha * wa))
            (db_sharded, dbn_sharded, afilt_sharded, wk, shift,
             dbl_sharded) = build_sharded_db(
                spec, to_j(job.a_src), to_j(job.a_filt),
                to_j(job.a_src_coarse), to_j(job.a_filt_coarse),
                to_j(job.a_temporal), template.rowsafe, mesh, pad_full,
                tile, packed=packed)
            # query side in its own program — the DB never materializes
            # unsharded anywhere
            static_q = _prepare_query_arrays(
                spec, to_j(job.b_src), to_j(job.b_src_coarse),
                to_j(job.b_filt_coarse), to_j(job.b_temporal))
            return dataclasses.replace(
                template, static_q=static_q, db_sharded=db_sharded,
                dbn_sharded=dbn_sharded, afilt_sharded=afilt_sharded,
                dblive_sharded=dbl_sharded, db_pad=wk, feat_mean=shift,
                mesh=mesh)

        arrs = _prepare_level_arrays(
            spec, to_j(job.a_src), to_j(job.a_filt),
            to_j(job.a_src_coarse), to_j(job.a_filt_coarse),
            to_j(job.a_temporal), to_j(job.b_src),
            to_j(job.b_src_coarse), to_j(job.b_filt_coarse),
            to_j(job.b_temporal), template.rowsafe, pad_tile, pad_full,
            pad_mode, db_rows_pad, q_rows_pad)
        ann_kw: Dict[str, Any] = {}
        if ann_plan is not None:
            # the prefilter ranks against the strategy's scoring DB —
            # full rows for wavefront (the oracle's metric), rowsafe-
            # masked for batched — mirroring the pad-copy choice above
            ann_src = (arrs["db"] if strategy == "wavefront"
                       else arrs["db_rowsafe"])
            if ann_plan[0] == "rebuild":
                # quarantined artifact: rebuild + re-seal from the
                # feature bytes so the NEXT request recovers the fast
                # path; this one already committed to the exact matcher
                from image_analogies_tpu.catalog import ann as catalog_ann

                mean_np, proj_np = catalog_ann.build_projection(
                    np.asarray(ann_src), tune.ann_proj_dims())
                catalog_ann.save_artifact(ann_plan[1], ann_plan[2],
                                          mean_np, proj_np)
                obs_metrics.inc("ann.artifacts_rebuilt")
            else:
                if ann_plan[0] == "artifact":
                    mean_j = jnp.asarray(ann_plan[1], _F32)
                    proj_j = jnp.asarray(ann_plan[2], _F32)
                    dbp, dbnh = _ann_project_db(ann_src, mean_j, proj_j)
                else:
                    mean_j, proj_j, dbp, dbnh = _ann_arrays_on_device(
                        ann_src, tune.ann_proj_dims())
                    obs_metrics.inc("ann.projection_built")
                top_m = tune.ann_top_m()
                obs_metrics.inc("ann.prefilter_used")
                obs_metrics.set_gauge("ann.top_m", top_m)
                obs_metrics.set_gauge("ann.proj_dims",
                                      int(proj_j.shape[1]))
                obs_trace.emit_record(
                    {"event": "ann_prefilter", "level": job.level,
                     "strategy": strategy, "source": ann_plan[0],
                     "top_m": top_m, "proj_dims": int(proj_j.shape[1]),
                     "db_rows": int(ann_src.shape[0])})
                ann_kw = dict(ann_proj=proj_j, ann_mean=mean_j,
                              ann_dbp=dbp, ann_dbnh=dbnh)
        return dataclasses.replace(
            template,
            **ann_kw,
            db=arrs["db"],
            db_sqnorm=arrs["db_sqnorm"],
            db_rowsafe=arrs["db_rowsafe"],
            db_rowsafe_sqnorm=arrs["db_rowsafe_sqnorm"],
            static_q=arrs["static_q"],
            a_filt_flat=arrs["a_filt_flat"],
            db_pad=arrs["db_pad"],
            db_pad2=arrs["db_pad2"],
            dbn_pad=arrs["dbn_pad"],
            dbnh_pad=arrs["dbnh_pad"],
            feat_mean=arrs["feat_mean"],
            live_idx=arrs["live_idx"],
            db_live=arrs["db_live"])

    def prefetch_level(self, job: LevelJob) -> None:
        """Warm the next level's host-side caches while the previous
        level's program is in flight (pipelined driver, perf PR 8).

        Strictly cache-warming: content-hashed device uploads of the
        host planes (utils/devcache.py) and the shape-keyed schedule /
        gather-map caches.  `build_features` consults the SAME caches on
        dispatch and recomputes on any miss, so a skipped, failed, or
        racing prefetch changes timing only — bit-identity with the
        sequential driver holds by construction.  `b_filt_coarse` is the
        chained device plane (nothing to warm) and is deliberately not
        touched here."""
        from image_analogies_tpu.utils.devcache import device_put_cached

        spec = job.spec
        strategy = self.params.strategy
        if strategy == "auto":
            strategy = "wavefront"
        for plane in (job.a_src, job.a_filt, job.a_src_coarse,
                      job.a_filt_coarse, job.a_temporal, job.b_src,
                      job.b_src_coarse, job.b_temporal):
            if isinstance(plane, np.ndarray):
                device_put_cached(plane, _F32)
        hb, wb = job.b_shape
        if strategy == "wavefront":
            # the numpy segment construction is the host-expensive part
            # (the device_put in _diag_schedule is per-call on purpose:
            # fresh buffers keep the donated runners safe)
            _diag_schedule_np(hb, wb, spec.fine_size // 2 + 1)
        else:
            _gather_maps_device(hb, wb, spec.fine_size)

    # ------------------------------------------------------------- protocol

    def best_match(self, db: TpuLevelDB, job: LevelJob, q: int,
                   bp_flat: np.ndarray, s_flat: np.ndarray
                   ) -> Tuple[int, float, bool]:
        """Single-pixel reference path (unit-test seam, not the fast path)."""
        import dataclasses

        if db.mesh is not None:
            raise ValueError(
                "best_match reads the per-chip DB arrays, which are 1-row "
                "placeholders when db_shards > 1; use synthesize_level "
                "(the mesh step) or build with db_shards=1")
        if db.flat_idx.shape[0] == 1 and db.hb * db.wb > 1:
            # wavefront LevelDBs carry placeholder gather maps (the scan
            # computes window indices from iota math); this seam is per-pixel
            # and cold, so materialize the cached maps here
            p = int(round(int(db.off.shape[0]) ** 0.5))
            flat_idx, valid, written = _gather_maps_device(db.hb, db.wb, p)
            db = dataclasses.replace(db, flat_idx=flat_idx, valid=valid,
                                     written=written)
        bp = jnp.asarray(bp_flat, _F32)
        s = jnp.asarray(s_flat, jnp.int32)
        qvec = _exact_qvec(db, q, bp)
        scores = db.db_sqnorm - 2.0 * jnp.dot(
            db.db, qvec, preferred_element_type=_F32, precision=_HIGHEST)
        p_app = int(jnp.argmin(scores))
        d_app = max(float(scores[p_app] + jnp.dot(qvec, qvec)), 0.0)
        p_coh, d_coh, has_coh = _pixel_coherence(db, qvec, q, s)
        if bool(has_coh) and float(d_coh) <= d_app * job.kappa_mult:
            return int(p_coh), float(d_coh), True
        return p_app, d_app, False

    def synthesize_level(self, db: TpuLevelDB, job: LevelJob
                         ) -> Tuple[jax.Array, jax.Array, Dict[str, Any]]:
        """Returns DEVICE-RESIDENT (bp (hb, wb), s (hb, wb)) plus stats.

        Device residency matters on this box: the PJRT tunnel moves ~9 MB/s
        with ~0.1 s per-fetch latency (measured round 3), so the old
        per-level np.asarray of bp+s cost ~1.3 s of the 1024^2 north star
        and each stats scalar another ~0.1 s.  The driver
        (models/analogy.py) chains levels through the device arrays
        (b_filt_coarse consumes bp directly) and fetches host copies only
        where a host consumer exists (final output, checkpoints,
        save-levels, keep_levels) — stats carry the coherence count as a
        device scalar under "_n_coh" for the driver's single batched fetch.
        """
        t0 = time.perf_counter()
        n_ref = None
        if db.mesh is not None:
            from image_analogies_tpu.parallel.step import multichip_level_step

            bp, s, n_coh = multichip_level_step(
                db.mesh, db.static_q[None], db.db_sharded, db.dbn_sharded,
                db.afilt_sharded, slim_for_mesh(db), job.kappa_mult,
                force_xla=jax.default_backend() != "tpu",
                wk_shard=db.db_pad, dbl_shard=db.dblive_sharded)
            bp, s, n_coh = bp[0], s[0], n_coh[0]
        elif db.strategy == "batched":
            if job.donate:
                import dataclasses

                # maps come from the _gather_maps_device cache — split
                # them out of the donated argument (see the twin's note)
                maps = (db.flat_idx, db.valid, db.written)
                nf = int(db.off.shape[0])
                slim = dataclasses.replace(
                    db, flat_idx=jnp.zeros((1, nf), jnp.int32),
                    valid=jnp.zeros((1, nf), _F32),
                    written=jnp.zeros((1, nf), _F32))
                bp, s, counts = _run_batched_donated(
                    _donation_safe_db(slim), maps,
                    jnp.float32(job.kappa_mult))
            else:
                bp, s, counts = _run_batched(db, jnp.float32(job.kappa_mult))
            n_coh, n_ref = counts[0], counts[1]
        elif job.donate and db.strategy == "wavefront":
            _wavefront_rows_guard(db)  # host side: jit cache skips traces
            bp, s, n_coh = _run_wavefront_donated(
                _donation_safe_db(db), jnp.float32(job.kappa_mult))
        else:
            if db.strategy == "wavefront":
                _wavefront_rows_guard(db)
            runner = _RUNNERS[db.strategy]
            bp, s, n_coh = runner(db, jnp.float32(job.kappa_mult))
        hb, wb = job.b_shape
        if bp.shape[0] != hb * wb:
            # query-bucketed batched level: crop the pad rows (never
            # written — the scan loop stops at the real hb) off the
            # bucket-sized planes before the (hb, wb) reshape
            bp = bp[:hb * wb]
            s = s[:hb * wb]
        bp = bp.reshape(hb, wb)
        s = s.reshape(hb, wb)
        n = hb * wb
        stats = {
            "level": job.level,
            "db_rows": job.a_shape[0] * job.a_shape[1],
            "pixels": n,
            "_n_coh": n_coh,  # device scalar; driver batch-fetches
            "backend": "tpu",
            "strategy": db.strategy,
        }
        if self.params.level_sync or self.params.level_retries > 0:
            # (level retries require the sync: a fault must surface
            # INSIDE the retry wrapper, not at the final fetch)
            jax.block_until_ready((bp, s))  # completion, no host fetch
            dt = time.perf_counter() - t0
            stats["pixels_per_s"] = n / max(dt, 1e-9)
            stats["ms"] = dt * 1e3
        else:
            # pipelined mode: the work is ENQUEUED; device compute of
            # this level overlaps the host prep + dispatch of the next
            # (config.AnalogyParams.level_sync) — the timing recorded
            # here is only the enqueue cost, named so honestly
            stats["enqueue_ms"] = (time.perf_counter() - t0) * 1e3
        if n_ref is not None:
            # picks the left-propagation refinement switched to a same-row
            # coherence candidate — reported separately so coherence_ratio
            # stays comparable with the CPU oracle's.
            stats["_n_ref"] = n_ref
        return bp, s, stats

    def synthesize_level_lanes(self, dbs, jobs):
        """Batched-lane twin of `synthesize_level` (batch/engine.py):
        k same-bucket members share ONE compiled program and ONE launch.

        ``dbs``/``jobs`` are the members' per-level TpuLevelDBs (from
        `build_features`) and LevelJobs — bit-identical A/A' arrays
        (engine-preflighted), differing only in the query side.  Lane
        0's DB rides whole; the other lanes contribute ONLY their
        query-side leaves (static_q plus the per-pixel gather maps,
        and, when bucketed, their traced ``dims_b`` row counts),
        stacked on a leading axis for the vmapped `_run_lanes` core.
        Returns a list of per-lane (bp (hb, wb), s (hb, wb), stats) in
        member order, cropped to each member's REAL shape.

        Per-lane timing is the LAUNCH wall-clock (one program ran), with
        ``lanes`` in each stats dict so obs/report can attribute the
        marginal cost / k — mirroring serve's one-observe-per-launch
        cost accounting (serve/worker.py)."""
        t0 = time.perf_counter()
        db0 = dbs[0]
        if db0.strategy == "wavefront":
            _wavefront_rows_guard(db0)  # host side: jit cache skips traces
        qnames = ["static_q", "flat_idx", "valid", "written"]
        if db0.dims_b is not None:
            qnames.append("dims_b")
        qsides = {nm: jnp.stack([getattr(d, nm) for d in dbs])
                  for nm in qnames}
        bp, s, counts = _run_lanes(db0, qsides,
                                   jnp.float32(jobs[0].kappa_mult))
        sync = self.params.level_sync or self.params.level_retries > 0
        if sync:
            jax.block_until_ready((bp, s))
        dt = time.perf_counter() - t0
        outs = []
        for i, job in enumerate(jobs):
            hb, wb = job.b_shape
            n = hb * wb
            bpi, si = bp[i], s[i]
            if bpi.shape[0] != n:  # query-bucketed: crop the pad rows
                bpi, si = bpi[:n], si[:n]
            stats = {
                "level": job.level,
                "db_rows": job.a_shape[0] * job.a_shape[1],
                "pixels": n,
                "_n_coh": counts[i, 0],
                "backend": "tpu",
                "strategy": db0.strategy,
                "lanes": len(dbs),
            }
            if sync:
                stats["pixels_per_s"] = n / max(dt, 1e-9)
                stats["ms"] = dt * 1e3
            else:
                stats["enqueue_ms"] = dt * 1e3
            if db0.strategy == "batched":
                stats["_n_ref"] = counts[i, 1]
            outs.append((bpi.reshape(hb, wb), si.reshape(hb, wb), stats))
        return outs
