"""TPU backend: JAX/XLA on-device synthesis (BASELINE.json:5 north star).

Design (SURVEY.md §7 steps 4-6):

- Feature building is the JAX twin of the shared spec (`build_features_jax`),
  one fused XLA program per level — no host round-trips.
- The within-level raster scan runs ON DEVICE as a single jitted
  `lax.fori_loop` carrying (B' plane, source map): 10^6 host dispatches at
  ~100us each would cost >100s alone (SURVEY.md §7 step 5), so only the
  coarse-to-fine level loop stays in Python.
- Strategy "exact": every pixel does brute-force approximate search over the
  full DB via the matmul trick ||a-q||^2 = ||a||^2 - 2 a.q + ||q||^2 (MXU),
  plus the Ashikhmin coherence candidates and the kappa blend — semantically
  identical to the CPU oracle's per-pixel decision.
- Strategy "rowwise": batched approximate search for a whole scan row using a
  rows-above-only causal mask (one (W,F)x(F,N) MXU matmul / Pallas fused
  argmin per row), then a sequential within-row pass that computes the EXACT
  query features for the kappa/coherence resolution.  This is the sanctioned
  fast path of SURVEY.md §7 hard part 1; candidate selection is approximate,
  the final decision is exact, parity is validated by SSIM.

The sharded-DB variant (patch DB over the ICI mesh, `lax.pmin`+index
all-reduce) lives in `parallel/sharded_match.py` and slots into the rowwise
strategy's approximate search.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from image_analogies_tpu.backends.base import LevelJob, Matcher
from image_analogies_tpu.ops.features import (
    build_features_jax,
    causal_mask,
    fine_gather_maps,
    window_offsets,
)

_F32 = jnp.float32
_HIGHEST = jax.lax.Precision.HIGHEST

# "auto" strategy: exact per-pixel scan while the DB (fp32) stays within this
# budget (it then lives happily in VMEM ~ 16-128 MB); rowwise beyond.
_AUTO_EXACT_MAX_DB_BYTES = 8 * 1024 * 1024


@dataclass
class TpuLevelDB:
    """Device-resident per-level state."""

    db: jax.Array  # (Na, F)
    db_sqnorm: jax.Array  # (Na,)
    static_q: jax.Array  # (Nb, F) fine_filt block zero
    static_q_row: jax.Array  # (Nb, F) rows-above-only causal variant
    flat_idx: jax.Array  # (Nb, nf) int32
    valid: jax.Array  # (Nb, nf) f32
    written: jax.Array  # (Nb, nf) f32
    rowsafe: jax.Array  # (nf,) f32: causal offsets with di < 0 only
    a_filt_flat: jax.Array  # (Na,)
    fine_sqrtw: jax.Array  # (nf,)
    off: jax.Array  # (nf, 2) int32 window offsets
    ha: int
    wa: int
    hb: int
    wb: int
    fine_start: int  # start of fine_filt block in the feature vector
    strategy: str


class TpuMatcher(Matcher):
    """JAX/XLA matcher.  Runs on TPU when one is attached; the same program
    compiles on the CPU backend for the virtual-mesh tests."""

    def build_features(self, job: LevelJob) -> TpuLevelDB:
        spec = job.spec
        to_j = lambda x: None if x is None else jnp.asarray(x, _F32)
        db = build_features_jax(
            spec, to_j(job.a_src), to_j(job.a_filt), to_j(job.a_src_coarse),
            to_j(job.a_filt_coarse), temporal_fine=to_j(job.a_temporal))
        static_q = build_features_jax(
            spec, to_j(job.b_src), None, to_j(job.b_src_coarse),
            to_j(job.b_filt_coarse), temporal_fine=to_j(job.b_temporal))
        hb, wb = job.b_shape
        ha, wa = job.a_shape
        flat_idx, valid, written = fine_gather_maps(hb, wb, spec.fine_size)
        off = window_offsets(spec.fine_size)
        # rows-above-only mask: the subset of the causal window that is known
        # at the START of a scan row (di < 0) — used by the rowwise batched
        # approximate search.
        rowsafe = ((off[:, 0] < 0).astype(np.float32)
                   * causal_mask(spec.fine_size))

        n_db = int(db.shape[0]) * int(db.shape[1]) * 4
        strategy = self.params.strategy
        if strategy == "auto":
            strategy = "exact" if n_db <= _AUTO_EXACT_MAX_DB_BYTES else "rowwise"

        return TpuLevelDB(
            db=db,
            db_sqnorm=jnp.sum(db * db, axis=1),
            static_q=static_q,
            static_q_row=static_q,  # fine_filt block is zero in both
            flat_idx=jnp.asarray(flat_idx),
            valid=jnp.asarray(valid),
            written=jnp.asarray(written),
            rowsafe=jnp.asarray(rowsafe),
            a_filt_flat=jnp.asarray(job.a_filt, _F32).reshape(-1),
            fine_sqrtw=jnp.asarray(spec.sqrt_weights()[spec.fine_filt_slice]),
            off=jnp.asarray(off),
            ha=ha,
            wa=wa,
            hb=hb,
            wb=wb,
            fine_start=spec.fine_filt_slice.start,
            strategy=strategy,
        )

    # ------------------------------------------------------------ exact scan

    def _exact_level_fn(self, db: TpuLevelDB, kappa_mult: float):
        """Jitted whole-level scan, one fori_loop iteration per pixel."""
        nf = int(db.off.shape[0])
        nb = db.hb * db.wb
        fine_start = db.fine_start

        def qvec_at(q, bp):
            idxq = db.flat_idx[q]  # (nf,)
            dyn = bp[idxq] * db.written[q] * db.fine_sqrtw
            base = db.static_q[q]
            return jax.lax.dynamic_update_slice(base, dyn, (fine_start,))

        def coherence(qvec, q, s):
            s_r = s[db.flat_idx[q]]  # (nf,)
            ci = s_r // db.wa - db.off[:, 0]
            cj = s_r % db.wa - db.off[:, 1]
            inb = ((ci >= 0) & (ci < db.ha) & (cj >= 0) & (cj < db.wa)
                   & (db.valid[q] > 0))
            cand = (jnp.clip(ci, 0, db.ha - 1) * db.wa
                    + jnp.clip(cj, 0, db.wa - 1))
            cf = db.db[cand]  # (nf, F) gather
            dc = jnp.sum((cf - qvec[None, :]) ** 2, axis=1)
            dc = jnp.where(inb, dc, jnp.inf)
            k = jnp.argmin(dc)
            return cand[k], dc[k], inb.any()

        def body(q, state):
            bp, s, n_coh = state
            qvec = qvec_at(q, bp)
            scores = db.db_sqnorm - 2.0 * jnp.dot(
                db.db, qvec, preferred_element_type=_F32,
                precision=_HIGHEST)
            p_app = jnp.argmin(scores)
            qn = jnp.dot(qvec, qvec, preferred_element_type=_F32,
                         precision=_HIGHEST)
            d_app = jnp.maximum(scores[p_app] + qn, 0.0)
            p_coh, d_coh, has_coh = coherence(qvec, q, s)
            use_coh = has_coh & (d_coh <= d_app * kappa_mult)
            p = jnp.where(use_coh, p_coh, p_app).astype(jnp.int32)
            bp = bp.at[q].set(db.a_filt_flat[p])
            s = s.at[q].set(p)
            return bp, s, n_coh + use_coh.astype(jnp.int32)

        def run():
            bp0 = jnp.zeros((nb,), _F32)
            s0 = jnp.zeros((nb,), jnp.int32)
            return jax.lax.fori_loop(0, nb, body, (bp0, s0, jnp.int32(0)))

        return jax.jit(run)

    # ------------------------------------------------------- rowwise scan

    def _rowwise_level_fn(self, db: TpuLevelDB, kappa_mult: float,
                          approx_fn=None):
        """Batched approximate search per scan row + sequential resolution.

        approx_fn(queries (W,F)) -> (idx (W,), sqdist (W,)) may be overridden
        (the Pallas kernel / sharded variant plug in here); default is the
        XLA matmul + argmin.
        """
        nf = int(db.off.shape[0])
        wb, hb = db.wb, db.hb
        fine_start = db.fine_start

        if approx_fn is None:
            def approx_fn(queries):
                scores = (db.db_sqnorm[None, :] - 2.0 * jnp.dot(
                    queries, db.db.T, preferred_element_type=_F32,
                    precision=_HIGHEST))
                idx = jnp.argmin(scores, axis=1)
                qn = jnp.sum(queries * queries, axis=1)
                d = jnp.take_along_axis(scores, idx[:, None], axis=1)[:, 0]
                return idx.astype(jnp.int32), jnp.maximum(d + qn, 0.0)

        def row_queries(r, bp):
            """Query features for all pixels of row r using the rows-above
            causal subset (exact at row start)."""
            q0 = r * wb
            idx = jax.lax.dynamic_slice(db.flat_idx, (q0, 0), (wb, nf))
            wr = jax.lax.dynamic_slice(db.written, (q0, 0), (wb, nf))
            dyn = bp[idx] * wr * db.rowsafe[None, :] * db.fine_sqrtw[None, :]
            base = jax.lax.dynamic_slice(
                db.static_q, (q0, 0), (wb, db.static_q.shape[1]))
            return jax.lax.dynamic_update_slice(base, dyn, (0, fine_start))

        def exact_qvec(q, bp):
            idxq = db.flat_idx[q]
            dyn = bp[idxq] * db.written[q] * db.fine_sqrtw
            return jax.lax.dynamic_update_slice(
                db.static_q[q], dyn, (fine_start,))

        def coherence(qvec, q, s):
            s_r = s[db.flat_idx[q]]
            ci = s_r // db.wa - db.off[:, 0]
            cj = s_r % db.wa - db.off[:, 1]
            inb = ((ci >= 0) & (ci < db.ha) & (cj >= 0) & (cj < db.wa)
                   & (db.valid[q] > 0))
            cand = (jnp.clip(ci, 0, db.ha - 1) * db.wa
                    + jnp.clip(cj, 0, db.wa - 1))
            cf = db.db[cand]
            dc = jnp.sum((cf - qvec[None, :]) ** 2, axis=1)
            dc = jnp.where(inb, dc, jnp.inf)
            k = jnp.argmin(dc)
            return cand[k], dc[k], inb.any()

        def pixel_body(j, carry):
            bp, s, n_coh, r, p_apps = carry
            q = r * wb + j
            qvec = exact_qvec(q, bp)
            p_app = p_apps[j]
            # exact d_app for the kappa test (candidate from the batched pass)
            d_app = jnp.sum((db.db[p_app] - qvec) ** 2)
            p_coh, d_coh, has_coh = coherence(qvec, q, s)
            use_coh = has_coh & (d_coh <= d_app * kappa_mult)
            p = jnp.where(use_coh, p_coh, p_app).astype(jnp.int32)
            bp = bp.at[q].set(db.a_filt_flat[p])
            s = s.at[q].set(p)
            return bp, s, n_coh + use_coh.astype(jnp.int32), r, p_apps

        def row_body(r, state):
            bp, s, n_coh = state
            queries = row_queries(r, bp)
            p_apps, _ = approx_fn(queries)
            bp, s, n_coh, _, _ = jax.lax.fori_loop(
                0, wb, pixel_body, (bp, s, n_coh, r, p_apps))
            return bp, s, n_coh

        def run():
            bp0 = jnp.zeros((hb * wb,), _F32)
            s0 = jnp.zeros((hb * wb,), jnp.int32)
            return jax.lax.fori_loop(0, hb, row_body,
                                     (bp0, s0, jnp.int32(0)))

        return jax.jit(run)

    # ------------------------------------------------------------- protocol

    def best_match(self, db: TpuLevelDB, job: LevelJob, q: int,
                   bp_flat: np.ndarray, s_flat: np.ndarray
                   ) -> Tuple[int, float, bool]:
        """Single-pixel reference path (unit-test seam, not the fast path)."""
        bp = jnp.asarray(bp_flat, _F32)
        s = jnp.asarray(s_flat, jnp.int32)
        dyn = bp[db.flat_idx[q]] * db.written[q] * db.fine_sqrtw
        qvec = db.static_q[q].at[
            db.fine_start : db.fine_start + dyn.shape[0]].set(dyn)
        scores = db.db_sqnorm - 2.0 * jnp.dot(
            db.db, qvec, preferred_element_type=_F32, precision=_HIGHEST)
        p_app = int(jnp.argmin(scores))
        d_app = max(float(scores[p_app] + jnp.dot(qvec, qvec)), 0.0)
        # coherence
        s_r = np.asarray(s)[np.asarray(db.flat_idx[q])]
        off = np.asarray(db.off)
        ci = s_r // db.wa - off[:, 0]
        cj = s_r % db.wa - off[:, 1]
        inb = ((ci >= 0) & (ci < db.ha) & (cj >= 0) & (cj < db.wa)
               & (np.asarray(db.valid[q]) > 0))
        if inb.any():
            cand = (ci[inb] * db.wa + cj[inb]).astype(np.int64)
            dmat = np.asarray(db.db)[cand] - np.asarray(qvec)[None, :]
            dc = (dmat * dmat).sum(axis=1)
            k = int(np.argmin(dc))
            if float(dc[k]) <= d_app * job.kappa_mult:
                return int(cand[k]), float(dc[k]), True
        return p_app, d_app, False

    def synthesize_level(self, db: TpuLevelDB, job: LevelJob
                         ) -> Tuple[np.ndarray, np.ndarray, Dict[str, Any]]:
        t0 = time.perf_counter()
        if db.strategy == "exact":
            fn = self._exact_level_fn(db, job.kappa_mult)
        else:
            fn = self._rowwise_level_fn(db, job.kappa_mult)
        bp, s, n_coh = fn()
        bp, s = jax.block_until_ready((bp, s))
        dt = time.perf_counter() - t0
        hb, wb = job.b_shape
        stats = {
            "level": job.level,
            "db_rows": int(db.db.shape[0]),
            "pixels": hb * wb,
            "coherence_ratio": float(n_coh) / max(hb * wb, 1),
            "ms": dt * 1e3,
            "backend": "tpu",
            "strategy": db.strategy,
        }
        return (np.asarray(bp, np.float32).reshape(hb, wb),
                np.asarray(s, np.int32).reshape(hb, wb), stats)
