"""CPU oracle backend: NumPy + scipy.spatial.cKDTree (SURVEY.md §2 P6-P8).

This is the faithful reimplementation of the reference's "NumPy/cKDTree path"
(BASELINE.json:5) and serves three roles (SURVEY.md §4.1): the reference
semantics spec, the SSIM-parity oracle for the TPU backend, and a fallback
backend.  The per-pixel raster scan is deliberately literal — clarity over
speed; the optional native C++ brute-force matcher (`native/`) accelerates the
approximate match when ANN is off.
"""

from __future__ import annotations

import hashlib
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import numpy as np

from image_analogies_tpu.backends.base import LevelJob, Matcher
from image_analogies_tpu.ops.features import (
    build_features_np,
    fine_gather_maps,
    window_offsets,
)

try:
    from scipy.spatial import cKDTree
except Exception:  # pragma: no cover - scipy is baked into this image
    cKDTree = None


@dataclass
class CpuLevelDB:
    """Per-level database + precomputed query-side state."""

    db: np.ndarray  # (Na, F) weighted features over A/A'
    tree: Optional["cKDTree"]
    a_filt_flat: np.ndarray  # (Na,) A' luminance, flat
    wa: int  # A width (for flat<->2d index math)
    ha: int
    static_q: np.ndarray  # (Nb, F) query features, fine_filt block zero
    flat_idx: np.ndarray  # (Nb, n_fine) clipped gather map into B' plane
    valid: np.ndarray  # (Nb, n_fine) causal & in-bounds mask (coherence)
    written: np.ndarray  # (Nb, n_fine) causal & already-synthesized mask
    fine_sqrtw: np.ndarray  # (n_fine,) sqrt-weights of the fine_filt block
    offsets: np.ndarray  # (n_fine, 2) window offsets


def _a_side_key(spec, job: LevelJob, use_ann: bool) -> str:
    """Content digest of everything the A-side build consumes."""
    h = hashlib.sha1()
    h.update(repr((spec, job.a_shape, use_ann)).encode())
    for arr in (job.a_src, job.a_filt, job.a_src_coarse, job.a_filt_coarse,
                job.a_temporal):
        if arr is None:
            h.update(b"-")
        else:
            a = np.ascontiguousarray(np.asarray(arr))
            h.update(str((a.shape, a.dtype)).encode())
            h.update(a.tobytes())
    return h.hexdigest()


class CpuMatcher(Matcher):
    # A-side memo: (db, tree, a_filt_flat) keyed by exemplar content.
    # Per-INSTANCE, so the default engine path (fresh matcher per
    # create_image_analogy call) is untouched; the win appears when
    # serve/ shares one backend across a batch with identical exemplars —
    # the expensive feature build + KD-tree construction then runs once
    # per level instead of once per request.  Bounded LRU; lock because
    # serve workers may share an instance across threads.
    _A_MEMO_CAP = 16

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._a_memo: "OrderedDict[str, tuple]" = OrderedDict()
        self._a_memo_lock = threading.Lock()

    def _a_side(self, spec, job: LevelJob):
        use_ann = bool(self.params.use_ann and cKDTree is not None)
        # Catalog tier hit: the driver already resolved this level's
        # A-side (catalog/tiers.py — the stored bytes ARE a
        # build_features_np output, so this is the same db a cold build
        # would produce).  The KD-tree is consumer scratch parked on the
        # entry, so a resident hit skips index construction too.
        ref = job.a_features
        if ref is not None and ref.entry is not None:
            ent = ref.entry
            tree = None
            if use_ann:
                tree = ent.state.get("tree")
                if tree is None:
                    tree = cKDTree(ent.db)
                    ent.state["tree"] = tree
            return ent.db, tree, ent.a_filt_flat
        key = _a_side_key(spec, job, use_ann)
        with self._a_memo_lock:
            hit = self._a_memo.get(key)
            if hit is not None:
                self._a_memo.move_to_end(key)
                return hit
        t0 = time.perf_counter()
        db = build_features_np(
            spec, job.a_src, job.a_filt, job.a_src_coarse, job.a_filt_coarse,
            temporal_fine=job.a_temporal,
        )
        tree = cKDTree(db) if use_ann else None
        a_filt_flat = np.asarray(job.a_filt, np.float32).reshape(-1)
        if ref is not None:
            # cold build under an active catalog: fill every tier (and
            # the sealed disk artifact) so the NEXT request for this
            # style skips the build, then park the tree on the entry
            ent = ref.record(db, a_filt_flat,
                             build_ms=(time.perf_counter() - t0) * 1e3)
            if tree is not None:
                ent.state["tree"] = tree
        entry = (db, tree, a_filt_flat)
        with self._a_memo_lock:
            self._a_memo[key] = entry
            while len(self._a_memo) > self._A_MEMO_CAP:
                self._a_memo.popitem(last=False)
        return entry

    def build_features(self, job: LevelJob) -> CpuLevelDB:
        spec = job.spec
        db, tree, a_filt_flat = self._a_side(spec, job)
        static_q = build_features_np(
            spec, job.b_src, None, job.b_src_coarse, job.b_filt_coarse,
            temporal_fine=job.b_temporal,
        )
        hb, wb = job.b_shape
        ha, wa = job.a_shape
        flat_idx, valid, written = fine_gather_maps(hb, wb, spec.fine_size)
        return CpuLevelDB(
            db=db,
            tree=tree,
            a_filt_flat=a_filt_flat,
            wa=wa,
            ha=ha,
            static_q=static_q,
            flat_idx=flat_idx,
            valid=valid,
            written=written,
            fine_sqrtw=spec.sqrt_weights()[spec.fine_filt_slice].copy(),
            offsets=window_offsets(spec.fine_size),
        )

    # -- the three canonical pieces of the matcher (SURVEY.md §3.3) ---------

    def query_vector(self, db: CpuLevelDB, job: LevelJob, q: int,
                     bp_flat: np.ndarray) -> np.ndarray:
        """Full feature vector of query pixel q given B'-so-far: the static
        part (B / coarse planes) plus the causal gather from the evolving B'."""
        vec = db.static_q[q].copy()
        vec[job.spec.fine_filt_slice] = (
            bp_flat[db.flat_idx[q]] * db.written[q] * db.fine_sqrtw)
        return vec

    def best_approximate_match(self, db: CpuLevelDB,
                               qvec: np.ndarray) -> Tuple[int, float]:
        """L2 nearest DB row: cKDTree when ANN on, else brute force."""
        if db.tree is not None:
            d, p = db.tree.query(qvec)
            return int(p), float(d) ** 2
        from image_analogies_tpu.backends import native_match

        return native_match.brute_argmin(db.db, qvec)

    def best_coherence_match(
        self, db: CpuLevelDB, job: LevelJob, q: int, qvec: np.ndarray,
        s_flat: np.ndarray,
    ) -> Tuple[int, float]:
        """Ashikhmin candidate: argmin over {s(r) + (q - r)} for causal r.

        Returns (-1, inf) when no candidate is valid (e.g. the first pixel).
        """
        valid = db.valid[q] > 0
        if not valid.any():
            return -1, np.inf
        r_flat = db.flat_idx[q][valid]
        off = db.offsets[valid]
        # p_c = s(r) + (q - r) = s(r) - offset, in A 2-D coords.
        si = s_flat[r_flat] // db.wa - off[:, 0]
        sj = s_flat[r_flat] % db.wa - off[:, 1]
        inb = (si >= 0) & (si < db.ha) & (sj >= 0) & (sj < db.wa)
        if not inb.any():
            return -1, np.inf
        cand = (si[inb] * db.wa + sj[inb]).astype(np.int64)
        d = ((db.db[cand] - qvec[None, :]) ** 2).sum(axis=1)
        k = int(np.argmin(d))  # first-lowest tie-break
        return int(cand[k]), float(d[k])

    def best_match(self, db: CpuLevelDB, job: LevelJob, q: int,
                   bp_flat: np.ndarray, s_flat: np.ndarray
                   ) -> Tuple[int, float, bool]:
        qvec = self.query_vector(db, job, q, bp_flat)
        p_app, d_app = self.best_approximate_match(db, qvec)
        p_coh, d_coh = self.best_coherence_match(db, job, q, qvec, s_flat)
        # kappa rule (Hertzmann §3.2 eq. 2, squared distances).
        if p_coh >= 0 and d_coh <= d_app * job.kappa_mult:
            return p_coh, d_coh, True
        return p_app, d_app, False

    # -- level scan ---------------------------------------------------------

    def synthesize_level(self, db: CpuLevelDB, job: LevelJob
                         ) -> Tuple[np.ndarray, np.ndarray, Dict[str, Any]]:
        hb, wb = job.b_shape
        n = hb * wb
        bp = np.zeros(n, dtype=np.float32)
        s = np.zeros(n, dtype=np.int32)
        t0 = time.perf_counter()
        n_coh = 0
        for q in range(n):
            p, _, used_coh = self.best_match(db, job, q, bp, s)
            n_coh += used_coh
            bp[q] = db.a_filt_flat[p]
            s[q] = p
        dt = time.perf_counter() - t0
        stats = {
            "level": job.level,
            "db_rows": int(db.db.shape[0]),
            "pixels": n,
            "coherence_ratio": n_coh / max(n, 1),
            "ms": dt * 1e3,
            "backend": "cpu",
        }
        return bp.reshape(hb, wb), s.reshape(hb, wb), stats
