"""Pluggable Matcher backends (BASELINE.json:5 — the `--backend` seam)."""

from image_analogies_tpu.backends.base import LevelJob, Matcher


def get_backend(params) -> "Matcher":
    if params.backend == "cpu":
        from image_analogies_tpu.backends.cpu import CpuMatcher

        return CpuMatcher(params)
    if params.backend == "tpu":
        try:
            from image_analogies_tpu.backends.tpu import TpuMatcher
        except ImportError as e:
            raise ImportError(
                "the TPU backend requires jax; underlying error: "
                f"{e}") from e

        return TpuMatcher(params)
    raise ValueError(f"unknown backend {params.backend!r}")


__all__ = ["LevelJob", "Matcher", "get_backend"]
