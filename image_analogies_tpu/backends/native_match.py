"""Brute-force L2 argmin for the CPU path, with an optional native C++ core.

The reference leans on SciPy's C/Cython cKDTree for its hot path (SURVEY.md
§2.2 N1).  When ANN is toggled off, the brute-force search runs here: a C++
OpenMP kernel (``native/match.cpp``, loaded via ctypes) when built, else a
NumPy fallback.  Build with ``make -C native`` (see native/README.md).
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional, Tuple

import numpy as np

_LIB: Optional[ctypes.CDLL] = None
_TRIED = False


def _load() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(__file__))),
        "native", "libia_match.so",
    )
    try:
        lib = ctypes.CDLL(path)
        lib.ia_brute_argmin.restype = None
        lib.ia_brute_argmin.argtypes = [
            ctypes.POINTER(ctypes.c_float),  # db (n, f)
            ctypes.c_int64,  # n
            ctypes.c_int64,  # f
            ctypes.POINTER(ctypes.c_float),  # queries (m, f)
            ctypes.c_int64,  # m
            ctypes.POINTER(ctypes.c_int64),  # out idx (m,)
            ctypes.POINTER(ctypes.c_float),  # out dist (m,)
        ]
        _LIB = lib
    except OSError:
        _LIB = None
    return _LIB


def have_native() -> bool:
    return _load() is not None


def brute_argmin_batch(db: np.ndarray, queries: np.ndarray
                       ) -> Tuple[np.ndarray, np.ndarray]:
    """Exact L2 argmin of each query row against the DB.

    Returns (idx (m,) int64, squared_dist (m,) float32); ties -> lowest index.
    """
    db = np.ascontiguousarray(db, dtype=np.float32)
    queries = np.ascontiguousarray(queries, dtype=np.float32)
    n, f = db.shape
    m = queries.shape[0]
    lib = _load()
    if lib is not None:
        idx = np.empty(m, dtype=np.int64)
        dist = np.empty(m, dtype=np.float32)
        lib.ia_brute_argmin(
            db.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), n, f,
            queries.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), m,
            idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            dist.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        )
        return idx, dist
    # NumPy fallback: ||a-b||^2 = ||a||^2 - 2ab + ||b||^2, blocked over queries.
    dbn = (db * db).sum(axis=1)
    idx = np.empty(m, dtype=np.int64)
    dist = np.empty(m, dtype=np.float32)
    step = max(1, int(2e7 // max(n, 1)))
    for s0 in range(0, m, step):
        q = queries[s0 : s0 + step]
        d = dbn[None, :] - 2.0 * (q @ db.T)
        k = np.argmin(d, axis=1)
        idx[s0 : s0 + step] = k
        qn = (q * q).sum(axis=1)
        dist[s0 : s0 + step] = d[np.arange(len(k)), k] + qn
    np.maximum(dist, 0.0, out=dist)
    return idx, dist


def brute_argmin(db: np.ndarray, query: np.ndarray) -> Tuple[int, float]:
    idx, dist = brute_argmin_batch(db, query[None, :])
    return int(idx[0]), float(dist[0])
