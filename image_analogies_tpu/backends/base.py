"""The backend boundary (SURVEY.md §1: drawn between L3/L4 and L5).

Per BASELINE.json:5, only feature building and best-match cross the backend
boundary; the coarse-to-fine level loop stays in the Python driver
(`models/analogy.py`).  A backend additionally owns the *within-level* scan
(`synthesize_level`) so the TPU implementation can keep the raster scan on
device inside one jitted `lax.fori_loop` instead of 10^6 host round-trips
(SURVEY.md §7 step 5).
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

import numpy as np

from image_analogies_tpu.ops.features import FeatureSpec


@dataclass
class LevelJob:
    """Everything a backend needs to synthesize one pyramid level.

    Planes are host NumPy float32; `level` counts from the finest (0).
    `a_src`/`b_src` may be (H,W) or (H,W,C_s) — label maps keep channels.
    `*_coarse` planes are the next-coarser level (None at the coarsest level);
    `b_filt_coarse` is the already-synthesized coarser B'.
    """

    level: int
    spec: FeatureSpec
    kappa_mult: float  # (1 + 2^-level * kappa)^2, threshold on squared dists

    a_src: np.ndarray
    a_filt: np.ndarray
    b_src: np.ndarray
    a_src_coarse: Optional[np.ndarray] = None
    a_filt_coarse: Optional[np.ndarray] = None
    b_src_coarse: Optional[np.ndarray] = None
    b_filt_coarse: Optional[np.ndarray] = None
    # Video mode: previous frame's planes at this level (temporal term).
    a_temporal: Optional[np.ndarray] = None
    b_temporal: Optional[np.ndarray] = None
    # Catalog resolution (catalog/tiers.CatalogRef), attached by the
    # driver when the exemplar catalog is active.  `a_features.entry`
    # holds this level's precomputed A-side features (a stored
    # build_features_np output — bit-identical to a cold build by
    # construction); entry=None asks the backend to build cold and
    # record the result back through `a_features.record(...)`.  The CPU
    # backend consumes it; the TPU backend ignores it (its A-side is
    # fused on device and its HBM warmth is the devcache).
    a_features: Optional[Any] = None
    # Buffer-donation consent, set by the DRIVER (it alone knows whether
    # anything else still reads this level's chained planes — retries,
    # keep_levels, checkpoints).  True lets the backend route this level
    # through its donate_argnums twins; the driver must treat the donated
    # b_filt_coarse buffer as dead afterwards.
    donate: bool = False

    @property
    def a_shape(self) -> Tuple[int, int]:
        return self.a_src.shape[:2]

    @property
    def b_shape(self) -> Tuple[int, int]:
        return self.b_src.shape[:2]


class Matcher(abc.ABC):
    """A matching backend.  Stateless across levels except via returned values."""

    def __init__(self, params):
        self.params = params

    @abc.abstractmethod
    def build_features(self, job: LevelJob) -> Any:
        """Build the per-level feature database over A/A' (opaque handle).

        The handle also carries whatever precomputed query-side state the
        backend wants (static query features, index maps, ...).
        """

    @abc.abstractmethod
    def best_match(
        self,
        db: Any,
        job: LevelJob,
        q: int,
        bp_flat: np.ndarray,
        s_flat: np.ndarray,
    ) -> Tuple[int, float, bool]:
        """Best source pixel for query pixel q given the evolving (B', s).

        Returns (p, squared_distance, used_coherence).  This is the
        unit-testable seam; `synthesize_level` may fuse it for speed but must
        agree with it.
        """

    @abc.abstractmethod
    def synthesize_level(
        self, db: Any, job: LevelJob
    ) -> Tuple[Any, Any, Dict[str, Any]]:
        """Raster-scan synthesis of one level.

        Returns (bp (H,W) float32, s (H,W) int32 flat indices into A, stats).

        Residency contract: bp/s may be HOST np.ndarrays (CPU backend) or
        DEVICE-RESIDENT jax.Arrays (TPU backend — the driver chains levels
        through them to avoid per-level PJRT transfers; see
        TpuMatcher.synthesize_level).  Consumers must treat them as
        read-only array-likes and call np.asarray() where a host copy is
        required.  Stats may defer device scalars under "_n_coh"/"_n_ref";
        models.analogy._finalize_stats resolves them."""

    def prefetch_level(self, job: LevelJob) -> None:
        """Warm host-side caches for a FUTURE level (pipelined driver).

        Called from a helper thread while the previous level's program is
        in flight.  Implementations may only populate content/shape-keyed
        caches (device-upload cache, schedule caches) — never produce the
        level's results — so a prefetch that is skipped, fails, or races
        the dispatch changes nothing but timing.  Default: no-op (the CPU
        backend has no device uploads to hide)."""
