"""The synthesis driver (SURVEY.md §1 L5, §3.1): coarse-to-fine over pyramid
levels, delegating feature building + matching to the pluggable backend.

Per BASELINE.json:5 the coarse-to-fine loop and color plumbing stay host-side;
only `build_features()` / `best_match()` / the fused `synthesize_level()`
cross the backend boundary.
"""

from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

from image_analogies_tpu import chaos
from image_analogies_tpu.backends import get_backend
from image_analogies_tpu.backends.base import LevelJob
from image_analogies_tpu.catalog import tiers as catalog_tiers
from image_analogies_tpu.config import AnalogyParams
from image_analogies_tpu.obs import device as obs_device
from image_analogies_tpu.obs import metrics as obs_metrics
from image_analogies_tpu.obs import trace as obs_trace
from image_analogies_tpu.ops import color
from image_analogies_tpu.ops.features import spec_for_level
from image_analogies_tpu.ops.pyramid import build_pyramid_np, num_feasible_levels
from image_analogies_tpu.utils import checkpoint as ckpt
from image_analogies_tpu.utils import failure
from image_analogies_tpu.utils import logging as ialog


@dataclass
class AnalogyResult:
    bp: np.ndarray  # (H,W,3) or (H,W) final B'
    bp_y: np.ndarray  # (H,W) synthesized filtered plane (luminance)
    # (H,W) int32 flat indices into A (finest level).  Stored raw (device
    # array on the TPU path unless a host consumer already forced it):
    # the map is introspection metadata, not the synthesized image, and
    # its eager fetch cost ~0.2 s/run over this box's tunnel — access
    # through the `source_map` property, which fetches once on demand.
    source_map_raw: Any = None
    stats: List[Dict[str, Any]] = field(default_factory=list)
    # with keep_levels=True: every level's (bp, s), finest first — the
    # tie-audit (utils/parity.py) re-scores mismatched picks against the
    # exact per-level decision context
    levels: Optional[List] = None
    # Run-level wall-clock accounting (ms), filled by the driver:
    # host_gap_ms   — host time between successive level dispatches (the
    #                 window the pipeline tries to hide under the device)
    # prep_ms / wait_ms / host_hidden_ms — pipeline prefetch worker time,
    #                 time the driver blocked joining it, and the
    #                 difference (host work actually overlapped)
    # donated_levels / prepped_levels — level counts for the two modes
    timing: Dict[str, float] = field(default_factory=dict)

    @property
    def source_map(self) -> np.ndarray:
        sm = self.source_map_raw
        if not isinstance(sm, np.ndarray):
            sm = np.asarray(sm, np.int32)
            self.source_map_raw = sm
        return sm


def _prep_planes(a, ap, b, params, remap_anchor=None):
    """Build the src/filt planes per color mode.

    Returns (a_src, b_src, a_filt, ap_rgb, b_yiq) where a_src/b_src are the
    matching planes ((H,W) or (H,W,C)), a_filt is A' luminance (possibly
    remapped), ap_rgb is A' as float RGB (for source_rgb reconstruction), and
    b_yiq is B in YIQ (None when B is grayscale).

    ``remap_anchor``: optional image whose luminance stats drive the
    Hertzmann §3.4 remap INSTEAD of b's — video mode anchors every frame of
    a clip on frame 0 so the A mapping stays consistent across frames
    (round-2 ADVICE item 3; both the serial and mesh paths use it).
    """
    a = color.as_float(np.asarray(a))
    ap = color.as_float(np.asarray(ap))
    b = color.as_float(np.asarray(b))
    if a.shape[:2] != ap.shape[:2]:
        raise ValueError(f"A {a.shape} and A' {ap.shape} must share H,W")

    a_filt = color.luminance(ap)
    b_yiq = color.rgb2yiq(b) if (b.ndim == 3 and b.shape[-1] == 3) else None

    def _remap_target(b_src):
        if remap_anchor is None:
            return b_src
        return color.luminance(color.as_float(np.asarray(remap_anchor)))

    if params.color_mode == "yiq_transfer":
        a_src = color.luminance(a)
        b_src = b_yiq[..., 0] if b_yiq is not None else color.luminance(b)
        if params.remap_luminance:
            # ONE affine transform (A's stats -> B's stats) applied to both A
            # and A' (Hertzmann §3.4); per-plane remapping would cancel any
            # affine filter A -> A'.
            a_src, a_filt = color.remap_pair(a_src, a_filt,
                                             _remap_target(b_src))
    else:  # source_rgb: keep label/source channels as-is
        a_src = a
        b_src = b
        a_nc = 1 if a_src.ndim == 2 else a_src.shape[-1]
        b_nc = 1 if b_src.ndim == 2 else b_src.shape[-1]
        if a_nc != b_nc:
            raise ValueError(
                f"A ({a_nc}ch) and B ({b_nc}ch) must have matching channels")
        if params.remap_luminance and a_src.ndim == 2:
            # the SAME affine transform must hit both planes (remap_pair's
            # invariant) or an affine filter A -> A' would be cancelled
            a_src, a_filt = color.remap_pair(a_src, a_filt,
                                             _remap_target(b_src))
    return a_src, b_src, a_filt, ap, b_yiq


def _finalize_stats(st: Dict[str, Any]) -> Dict[str, Any]:
    """Resolve deferred device scalars in a level-stats record.

    The TPU backend reports the coherence count as a device scalar
    (`_n_coh`) so the hot loop never blocks on a ~0.1 s PJRT tunnel fetch;
    this converts it (and `_n_ref`) into the documented
    coherence_ratio/refined_ratio fields.  CPU-backend records pass through
    untouched."""
    if "_n_coh" in st:
        n = max(st.get("pixels", 1), 1)
        st["coherence_ratio"] = float(st.pop("_n_coh")) / n
        if "_n_ref" in st:
            st["refined_ratio"] = float(st.pop("_n_ref")) / n
    return st


def create_image_analogy(
    a: np.ndarray,
    ap: np.ndarray,
    b: np.ndarray,
    params: AnalogyParams = AnalogyParams(),
    backend=None,
    temporal_prev: Optional[np.ndarray] = None,
    remap_anchor: Optional[np.ndarray] = None,
    keep_levels: bool = False,
) -> AnalogyResult:
    """Synthesize B' such that A : A' :: B : B' (Hertzmann §3 pseudocode).

    `temporal_prev` is the previous output frame's synthesized luminance
    (B'_{t-1}, same shape as B) for video mode: with
    ``params.temporal_weight > 0`` its windows join the feature vector and
    are matched against A' windows on the DB side (BASELINE.json:12).

    `remap_anchor` pins the §3.4 luminance remap to another image's stats
    (video clips anchor on frame 0 — see `_prep_planes`).
    """
    # Runtime wiring (tune/): persistent compile cache + devcache budget
    # when configured; no-ops on default params.
    from image_analogies_tpu.tune import warmup as tune_warmup
    from image_analogies_tpu.tune import resolve as tune_resolve

    tune_warmup.apply_runtime_config(params)
    # Observability run scope (obs/): inert unless params.metrics or a
    # log_path is set; joins the enclosing run when video already opened
    # one (single run_id per clip).  The manifest records the tune-store
    # provenance so a report ties results to the geometry they ran with.
    # Geometry is pinned per INVOCATION (tune pin_scope, reentrant: a
    # clip's outer per-clip pin wins): every level and retry of this run
    # bakes the same resolved ints, and a serve/ worker re-dispatching
    # the same shapes never re-reads the store mid-request.
    with obs_trace.run_scope(params,
                             manifest_extra=tune_resolve.manifest_info()):
        with tune_resolve.pin_scope():
            return _create_image_analogy(a, ap, b, params, backend,
                                         temporal_prev, remap_anchor,
                                         keep_levels)


def _create_image_analogy(a, ap, b, params, backend, temporal_prev,
                          remap_anchor, keep_levels) -> AnalogyResult:
    if params.data_shards > 1 and params.strategy not in ("wavefront",
                                                          "auto"):
        raise ValueError(
            "data_shards > 1 on a single image is the query-parallel "
            "wavefront (anti-diagonals split over the mesh 'data' axis) "
            "and exists only for strategy='wavefront'/'auto'; for video "
            "frame sharding use models.video.video_analogy")
    backend = backend or get_backend(params)
    # Exemplar catalog (catalog/): consulted per level BEFORE
    # build_features.  The style key is the raw exemplar bytes — the
    # same sha1 the serve batcher/router use — computed once per run.
    # CPU/oracle path only: the TPU backend's A-side is fused on device
    # and its HBM warmth is the devcache, so it ignores a_features.
    catalog_style = None
    if params.backend == "cpu" and catalog_tiers.active():
        catalog_style = catalog_tiers.style_key(a, ap)
    a_src, b_src, a_filt, ap_rgb, b_yiq = _prep_planes(
        a, ap, b, params, remap_anchor=remap_anchor)

    min_shape = (min(a_src.shape[0], b_src.shape[0]),
                 min(a_src.shape[1], b_src.shape[1]))
    levels = num_feasible_levels(min_shape, params.levels, params.patch_size)

    a_src_pyr = build_pyramid_np(a_src, levels)
    a_filt_pyr = build_pyramid_np(a_filt, levels)
    b_src_pyr = build_pyramid_np(b_src, levels)
    src_channels = 1 if a_src.ndim == 2 else a_src.shape[-1]
    temporal = params.temporal_weight > 0 and temporal_prev is not None
    # DB-side temporal plane is A' (same remapped plane the features use);
    # query side is the previous output frame's pyramid.
    b_temporal_pyr = (build_pyramid_np(
        np.asarray(temporal_prev, np.float32), levels) if temporal else None)

    bp_pyr: List[Optional[np.ndarray]] = [None] * levels
    s_pyr: List[Optional[np.ndarray]] = [None] * levels
    stats: List[Dict[str, Any]] = []
    digest = ckpt.run_digest(params, a_src.shape[:2], b_src.shape[:2])

    # --- async pipeline + donation consent (perf round 8) -------------
    # Donation frees each level's chained B' plane the moment the next
    # level's scan consumes it — but ONLY when the driver can prove no
    # other reader exists.  The hard disables win over an explicit
    # donate_buffers=True: retries rebuild from the chained plane
    # (§5.3), keep_levels/checkpoints/save_levels all re-read it.
    donate_levels = False
    if (params.level_retries == 0 and not keep_levels
            and not params.checkpoint_dir and not params.save_levels_dir):
        if params.donate_buffers is not None:
            donate_levels = params.donate_buffers
        elif params.backend == "tpu":
            import jax

            # auto: only where donation actually reuses memory (real
            # TPU); the CPU backend ignores donate_argnums with a
            # warning, so auto stays quiet there.
            donate_levels = jax.default_backend() == "tpu"
    # Pipelining overlaps NEXT-level host prep (upload/schedule cache
    # warming via Matcher.prefetch_level) with the in-flight device
    # program.  Auto = on exactly when dispatches are async
    # (level_sync=False); level_retries>0 always disables it so chaos
    # faults keep firing inside the retry envelope.
    pipeline_on = params.pipeline_active()
    prefetch_pool = None
    pending_prefetch = None
    timing: Dict[str, float] = {"host_gap_ms": 0.0}
    if pipeline_on:
        timing.update(prep_ms=0.0, wait_ms=0.0, host_hidden_ms=0.0,
                      prepped_levels=0.0)
    if donate_levels:
        timing["donated_levels"] = 0.0

    def _prefetch_worker(pf_job):
        # Cache-warming only; ANY failure is swallowed — the main-path
        # dispatch redoes the work (and hits chaos sites) on a cold
        # cache, changing timing but never results.
        t0 = time.perf_counter()
        try:
            backend.prefetch_level(pf_job)
        except Exception:
            obs_metrics.inc("pipeline.prefetch_errors")
        return (time.perf_counter() - t0) * 1e3

    prof = contextlib.nullcontext()
    if params.profile_dir:
        import jax

        prof = jax.profiler.trace(params.profile_dir)

    gap_t0 = None  # perf_counter at the previous level's dispatch return
    try:
        with prof:
            for level in range(levels - 1, -1, -1):  # coarsest -> finest
                if pending_prefetch is not None:
                    # join the helper BEFORE touching this level: from
                    # here on the caches it warms are read on this thread
                    twait = time.perf_counter()
                    with obs_trace.span("pipeline.wait", level=level):
                        prep_ms = pending_prefetch.result()
                    wait_ms = (time.perf_counter() - twait) * 1e3
                    pending_prefetch = None
                    timing["prep_ms"] += prep_ms
                    timing["wait_ms"] += wait_ms
                    timing["host_hidden_ms"] += max(prep_ms - wait_ms, 0.0)
                    timing["prepped_levels"] += 1.0
                if (params.checkpoint_dir
                        and params.resume_from_level is not None
                        and level > params.resume_from_level):
                    loaded = ckpt.load_level(params.checkpoint_dir, level,
                                             digest=digest)
                    if loaded is not None:
                        bp_pyr[level], s_pyr[level] = loaded
                        ialog.emit({"event": "resume_level", "level": level},
                                   params.log_path)
                        continue
                with obs_trace.span("level", level=level):
                    spec = spec_for_level(params, level, levels,
                                          src_channels, temporal=temporal)
                    job = LevelJob(
                        level=level,
                        spec=spec,
                        kappa_mult=params.kappa_factor(level) ** 2,
                        a_src=a_src_pyr[level],
                        a_filt=a_filt_pyr[level],
                        b_src=b_src_pyr[level],
                        a_src_coarse=(a_src_pyr[level + 1]
                                      if level + 1 < levels else None),
                        a_filt_coarse=(a_filt_pyr[level + 1]
                                       if level + 1 < levels else None),
                        b_src_coarse=(b_src_pyr[level + 1]
                                      if level + 1 < levels else None),
                        b_filt_coarse=(bp_pyr[level + 1]
                                       if level + 1 < levels else None),
                        a_temporal=(a_filt_pyr[level] if temporal else None),
                        b_temporal=(b_temporal_pyr[level]
                                    if temporal else None),
                        donate=donate_levels,
                    )
                    if catalog_style is not None:
                        # tier-by-tier A-side resolution (resident →
                        # host → disk); a full miss leaves entry=None
                        # and the backend builds cold, recording back
                        # through the ref so every tier above fills
                        job.a_features = catalog_tiers.lookup(
                            catalog_style, job)
                    t0 = time.perf_counter()
                    if gap_t0 is not None:
                        timing["host_gap_ms"] += (t0 - gap_t0) * 1e3

                    def _level():
                        chaos.site("level.dispatch", level=level)
                        db = backend.build_features(job)
                        return backend.synthesize_level(db, job)

                    def _dispatch():
                        # watchdog wraps the whole dispatch INSIDE the
                        # retry body: a wedged op raises WatchdogTimeout
                        # (transient) and the retry wrapper re-runs the
                        # level instead of the process hanging.  timeout
                        # 0 = inline, no thread.
                        return failure.run_with_watchdog(
                            _level, params.dispatch_timeout_s,
                            context={"level": level},
                            log_path=params.log_path)

                    # §5.3: transient device faults retry at level
                    # granularity
                    bp, s, st = failure.run_with_retry(
                        _dispatch, retries=params.level_retries,
                        context={"level": level}, log_path=params.log_path)
                    gap_t0 = time.perf_counter()
                    st["total_ms"] = (gap_t0 - t0) * 1e3
                    if donate_levels and level + 1 < levels:
                        # the scan consumed (donated) the coarser B'
                        # buffer — drop the dead reference so nothing can
                        # read it; the coarser s is merely unreferenced
                        bp_pyr[level + 1] = None
                        s_pyr[level + 1] = None
                        timing["donated_levels"] += 1.0
                        obs_metrics.inc("pipeline.donated_levels")
                    if pipeline_on and level > 0:
                        # the device program for `level` is (at most
                        # enqueue-deep) in flight: warm the NEXT level's
                        # host-side caches under it
                        nxt = level - 1
                        pf_job = LevelJob(
                            level=nxt,
                            spec=spec_for_level(params, nxt, levels,
                                                src_channels,
                                                temporal=temporal),
                            kappa_mult=params.kappa_factor(nxt) ** 2,
                            a_src=a_src_pyr[nxt],
                            a_filt=a_filt_pyr[nxt],
                            b_src=b_src_pyr[nxt],
                            a_src_coarse=a_src_pyr[level],
                            a_filt_coarse=a_filt_pyr[level],
                            b_src_coarse=b_src_pyr[level],
                            b_filt_coarse=None,  # in flight — never touched
                            a_temporal=(a_filt_pyr[nxt]
                                        if temporal else None),
                            b_temporal=(b_temporal_pyr[nxt]
                                        if temporal else None),
                        )
                        if prefetch_pool is None:
                            from concurrent.futures import \
                                ThreadPoolExecutor

                            prefetch_pool = ThreadPoolExecutor(
                                max_workers=1,
                                thread_name_prefix="ia-prefetch")
                        pending_prefetch = prefetch_pool.submit(
                            _prefetch_worker, pf_job)
                    # bp/s may be DEVICE arrays (TPU backend): levels
                    # chain through them without host round-trips (the
                    # tunnel moves ~9 MB/s); host copies are fetched only
                    # for opt-in host consumers below and for the final
                    # result.  EXCEPT with level retries armed: the §5.3
                    # fault model promises a retried level rebuilds from
                    # buffers that survive a device reset, and the
                    # coarser plane chained on-device could be
                    # invalidated by the very fault being retried — so
                    # fault-recovery runs keep the pre-chaining host
                    # copies (round-3 ADVICE item 1).
                    if params.level_retries > 0:
                        bp, s = (np.asarray(bp, np.float32),
                                 np.asarray(s, np.int32))
                    bp_pyr[level], s_pyr[level] = bp, s
                    if params.log_path or "_n_coh" not in st:
                        # stream the record now: always when a log file
                        # is configured (observability opt-in pays the
                        # ~0.1 s scalar fetch), and always for records
                        # with no deferred device scalars (CPU backend —
                        # deferral would only delay logs)
                        ialog.emit(_finalize_stats(st), params.log_path)
                        st["_emitted"] = True
                    stats.append(st)
                    if params.checkpoint_dir:
                        ckpt.save_level(params.checkpoint_dir, level,
                                        np.asarray(bp, np.float32),
                                        np.asarray(s, np.int32),
                                        digest=digest)
                    if params.save_levels_dir:
                        from image_analogies_tpu.utils.imageio import \
                            save_image
                        import os

                        os.makedirs(params.save_levels_dir, exist_ok=True)
                        save_image(os.path.join(params.save_levels_dir,
                                                f"level_{level:02d}.png"),
                                   np.clip(np.asarray(bp, np.float32),
                                           0.0, 1.0))
                    # per-level HBM watermark (hbm.peak_bytes.d<N> peak
                    # gauges): one bool check when metrics are off, and a
                    # silent no-op on backends with no allocator stats
                    # (CPU)
                    obs_device.record_hbm(level, params.log_path)
    finally:
        if prefetch_pool is not None:
            prefetch_pool.shutdown(wait=True)

    # pipeline-overlap accounting: `ia report` renders these gauges as
    # the "how much host prep the device hid" section; host_gap_ms is
    # recorded unconditionally so `ia bench --check` can gate it even on
    # non-pipelined baselines
    obs_metrics.set_gauge("pipeline.host_gap_ms", timing["host_gap_ms"])
    if pipeline_on:
        for k in ("prep_ms", "wait_ms", "host_hidden_ms"):
            obs_metrics.set_gauge(f"pipeline.{k}", timing[k])
        obs_metrics.inc("pipeline.levels_prepped",
                        int(timing["prepped_levels"]))

    # ONE fetch call for the deferred device scalars AND the finest B'
    # plane: `jax.device_get` on the pair starts both transfers before
    # blocking, so the stats' scalar round-trip (~0.1 s of tunnel
    # latency) hides under the 4 MB plane transfer instead of preceding
    # it serially (round-5; each np.asarray is its own blocking
    # round-trip).  When a host copy of the finest source map is needed
    # anyway (source_rgb gather, keep_levels), its transfer joins the
    # same bundle instead of a separate blocking np.asarray afterwards.
    need_s_host = params.color_mode == "source_rgb" or keep_levels
    dev = [(st, k) for st in stats for k in ("_n_coh", "_n_ref")
           if k in st and not isinstance(st[k], (int, float, np.number))]
    if dev:
        import jax
        import jax.numpy as jnp

        with obs_trace.span("fetch"):
            bundle = (jnp.stack([st[k] for st, k in dev]), bp_pyr[0]) + (
                (s_pyr[0],) if need_s_host else ())
            got = jax.device_get(bundle)
        vals, bp_fetched = got[0], got[1]
        for (st, k), v in zip(dev, vals):
            st[k] = float(v)
        bp_y = np.asarray(bp_fetched, np.float32)
        s_raw = np.asarray(got[2], np.int32) if need_s_host else s_pyr[0]
        obs_metrics.inc("fetch.bytes", int(vals.nbytes) + int(bp_y.nbytes))
    else:
        bp_y = np.asarray(bp_pyr[0], np.float32)
        s_raw = (np.asarray(s_pyr[0], np.int32) if need_s_host
                 else s_pyr[0])
    for st in stats:
        _finalize_stats(st)  # no-op where the streaming path already did
        if not st.pop("_emitted", False):
            ialog.emit(st, params.log_path)
    if obs_metrics._ACTIVE:
        # kappa coherence-vs-approx pick totals, weighted by pixel count
        for st in stats:
            cr, px = st.get("coherence_ratio"), st.get("pixels", 0)
            if cr is not None and px:
                obs_metrics.inc("kappa.coherence_px", cr * px)
                obs_metrics.inc("kappa.total_px", px)
    # the source map stays a DEVICE array unless a host consumer needed
    # it above (source_rgb's color gather, keep_levels' audit planes —
    # fetched in the fused bundle) — it is introspection metadata,
    # fetched lazily by AnalogyResult.source_map
    if params.color_mode == "source_rgb":
        ap_flat = ap_rgb.reshape(-1, ap_rgb.shape[-1]) if ap_rgb.ndim == 3 \
            else ap_rgb.reshape(-1)
        out = ap_flat[s_raw.reshape(-1)].reshape(
            bp_y.shape + (() if ap_rgb.ndim == 2 else (ap_rgb.shape[-1],)))
    elif b_yiq is not None:
        out = color.yiq2rgb(
            np.stack([bp_y, b_yiq[..., 1], b_yiq[..., 2]], axis=-1))
    else:
        out = np.clip(bp_y, 0.0, 1.0)
    if keep_levels:
        # reuse the already-fetched finest planes; only the coarser levels
        # (a quarter of the data, shrinking geometrically) transfer here
        levels_np = [(bp_y, s_raw)] + [
            (np.asarray(bp_pyr[lv], np.float32),
             np.asarray(s_pyr[lv], np.int32))
            for lv in range(1, levels)]
    return AnalogyResult(
        bp=out, bp_y=bp_y, source_map_raw=s_raw, stats=stats,
        levels=(levels_np if keep_levels else None), timing=timing)
