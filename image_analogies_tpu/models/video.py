"""Batched video analogies (SURVEY.md §2.3 T3, BASELINE.json:12).

Applies one training pair A -> A' to a sequence of B frames with a
temporal-coherence term: each frame's feature vectors carry windows of the
PREVIOUS OUTPUT frame (matched against A' windows on the DB side), weighted by
``params.temporal_weight``, so the synthesis prefers sources consistent with
where it looked last frame — suppressing frame-to-frame flicker.

Two execution schemes:

- ``scheme="sequential"``: frame t consumes frame t-1's actual output.
  Highest temporal fidelity, strictly serial.
- ``scheme="two_phase"`` (default): phase 1 synthesizes ALL frames
  independently (embarrassingly parallel — this is the axis that shards over
  the mesh 'data' axis); phase 2 re-synthesizes every frame with the temporal
  term fed by phase 1's neighbor output.  Both phases are data-parallel over
  frames, trading one extra pass for a pod-width speedup (a Jacobi iteration
  of the sequential recurrence).

The per-frame engine is the full pluggable-backend pipeline, so video mode
composes with db-sharding: a (data, db) mesh shards frames x patch-DB.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from image_analogies_tpu.config import AnalogyParams
from image_analogies_tpu.models.analogy import AnalogyResult, create_image_analogy


@dataclass
class VideoResult:
    frames: List[np.ndarray]  # synthesized B' frames
    frames_y: List[np.ndarray]  # synthesized luminance planes
    stats: List[Dict[str, Any]] = field(default_factory=list)


def video_analogy(
    a: np.ndarray,
    ap: np.ndarray,
    frames: Sequence[np.ndarray],
    params: AnalogyParams = AnalogyParams(temporal_weight=1.0),
    scheme: str = "two_phase",
    backend=None,
) -> VideoResult:
    if scheme not in ("sequential", "two_phase"):
        raise ValueError(f"unknown scheme {scheme!r}")
    frames = list(frames)
    if not frames:
        return VideoResult(frames=[], frames_y=[])

    stats: List[Dict[str, Any]] = []

    def synth(b, prev_y, tag, idx):
        res = create_image_analogy(a, ap, b, params, backend=backend,
                                   temporal_prev=prev_y)
        for st in res.stats:
            st.update(frame=idx, phase=tag)
            stats.append(st)
        return res

    if scheme == "sequential":
        outs, prev_y = [], None
        for t, b in enumerate(frames):
            res = synth(b, prev_y, "seq", t)
            prev_y = res.bp_y
            outs.append(res)
        return VideoResult(frames=[r.bp for r in outs],
                           frames_y=[r.bp_y for r in outs], stats=stats)

    # two_phase: phase 1 frames are independent (shardable over 'data')
    phase1 = [synth(b, None, "phase1", t) for t, b in enumerate(frames)]
    outs = [phase1[0]]
    for t in range(1, len(frames)):
        outs.append(synth(frames[t], phase1[t - 1].bp_y, "phase2", t))
    return VideoResult(frames=[r.bp for r in outs],
                       frames_y=[r.bp_y for r in outs], stats=stats)
