"""Batched video analogies (SURVEY.md §2.3 T3, BASELINE.json:12).

Applies one training pair A -> A' to a sequence of B frames with a
temporal-coherence term: each frame's feature vectors carry windows of the
PREVIOUS OUTPUT frame (matched against A' windows on the DB side), weighted by
``params.temporal_weight``, so the synthesis prefers sources consistent with
where it looked last frame — suppressing frame-to-frame flicker.

Two execution schemes:

- ``scheme="sequential"``: frame t consumes frame t-1's actual output.
  Highest temporal fidelity, strictly serial.
- ``scheme="two_phase"`` (default): phase 1 synthesizes ALL frames
  independently (embarrassingly parallel); phase 2 re-synthesizes every frame
  with the temporal term fed by phase 1's neighbor output.  Both phases are
  data-parallel over frames (a Jacobi iteration of the sequential
  recurrence).

**Multi-chip execution** (the production path for BASELINE.json:12): with
``params.data_shards > 1`` the two_phase scheme dispatches each pyramid
level of ALL frames through ONE `shard_map` program on a ('data','db') mesh
(`parallel/step.py`): frames shard over 'data' and vmap within a chip, the
patch DB shards over 'db' with the min+argmin all-reduce.  Semantics note:
BOTH paths compute the luminance remap (Hertzmann §3.4) against the clip's
FIRST frame and reuse it for every frame of both phases — one consistent A
mapping per clip (less flicker), and sharded == serial frame-for-frame with
remapping on or off (locked by tests/test_video_sharded.py; round-2 ADVICE
item 3).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from image_analogies_tpu.config import AnalogyParams
from image_analogies_tpu.models.analogy import (
    AnalogyResult,
    _prep_planes,
    create_image_analogy,
)
from image_analogies_tpu.obs import device as obs_device
from image_analogies_tpu.obs import metrics as obs_metrics
from image_analogies_tpu.obs import trace as obs_trace
from image_analogies_tpu.ops import color
from image_analogies_tpu.tune import resolve as tune_resolve
from image_analogies_tpu.utils import failure
from image_analogies_tpu.utils import logging as ialog


@dataclass
class VideoResult:
    frames: List[np.ndarray]  # synthesized B' frames
    frames_y: List[np.ndarray]  # synthesized luminance planes
    stats: List[Dict[str, Any]] = field(default_factory=list)

    def flicker(self) -> List[float]:
        """Temporal-stability metric: SSIM between consecutive output
        frames (higher = less flicker — the quantity the temporal term
        exists to raise, BASELINE.json:12).  len == n_frames - 1."""
        from image_analogies_tpu.utils.ssim import ssim

        return [float(ssim(self.frames_y[t], self.frames_y[t + 1]))
                for t in range(len(self.frames_y) - 1)]


def _sharded_phase(a, ap, frames, params: AnalogyParams, mesh,
                   temporal_prevs: Optional[Sequence[np.ndarray]],
                   stats: List[Dict[str, Any]], tag: str,
                   remap_anchor: np.ndarray, frame_offset: int = 0
                   ) -> List[AnalogyResult]:
    """Synthesize a batch of frames level-lockstep on the ('data','db') mesh.

    All frames advance one pyramid level per `multichip_level_step` call; the
    A/A' DB is built once per level — its luminance remap is computed against
    ``remap_anchor`` (the CLIP's first frame, for both phases — see module
    docstring) — and only the per-frame query-side features differ.
    ``frame_offset`` maps batch indices back to clip frame numbers in stats.
    """
    import jax
    import jax.numpy as jnp

    from image_analogies_tpu.backends.base import LevelJob
    from image_analogies_tpu.backends.tpu import (
        _prepare_query_arrays_batch,
        build_sharded_db,
        make_level_template,
    )
    from image_analogies_tpu.tune import resolve as tune
    from image_analogies_tpu.ops.features import spec_for_level
    from image_analogies_tpu.ops.pyramid import build_pyramid_np, \
        num_feasible_levels
    from image_analogies_tpu.parallel.step import multichip_level_step

    t_real = len(frames)
    data_shards = mesh.shape["data"]
    # pad the frame batch to the mesh width by repeating the last frame;
    # padded outputs are dropped
    t_pad = (t_real + data_shards - 1) // data_shards * data_shards
    idx = list(range(t_real)) + [t_real - 1] * (t_pad - t_real)

    a_src, _, a_filt, ap_rgb, _ = _prep_planes(a, ap, remap_anchor, params)
    a_nc = 1 if a_src.ndim == 2 else a_src.shape[-1]

    def b_planes(frame):
        """B-side of _prep_planes only — the A-side (luminance + anchor
        remap) is shared by the whole batch, no need to recompute per
        frame."""
        b = color.as_float(np.asarray(frame))
        b_yiq = (color.rgb2yiq(b)
                 if b.ndim == 3 and b.shape[-1] == 3 else None)
        if params.color_mode == "yiq_transfer":
            b_src = b_yiq[..., 0] if b_yiq is not None else color.luminance(b)
        else:
            b_src = b
            b_nc = 1 if b_src.ndim == 2 else b_src.shape[-1]
            if a_nc != b_nc:
                raise ValueError(f"A ({a_nc}ch) and B ({b_nc}ch) must have "
                                 "matching channels")
        return b_src, b_yiq

    preps = [b_planes(f) for f in frames]  # once per REAL frame
    b_srcs = [preps[i][0] for i in idx]
    b_yiqs = [preps[i][1] for i in idx]

    min_shape = (min(a_src.shape[0], min(b.shape[0] for b in b_srcs)),
                 min(a_src.shape[1], min(b.shape[1] for b in b_srcs)))
    levels = num_feasible_levels(min_shape, params.levels, params.patch_size)
    src_channels = 1 if a_src.ndim == 2 else a_src.shape[-1]
    temporal = params.temporal_weight > 0 and temporal_prevs is not None

    a_src_pyr = build_pyramid_np(a_src, levels)
    a_filt_pyr = build_pyramid_np(a_filt, levels)
    b_src_pyrs = [build_pyramid_np(b, levels) for b in b_srcs]
    b_temp_pyrs = None
    if temporal:
        prevs = [np.asarray(temporal_prevs[i], np.float32) for i in idx]
        b_temp_pyrs = [build_pyramid_np(p, levels) for p in prevs]

    force_xla = jax.default_backend() != "tpu"
    strategy = params.strategy
    if strategy == "auto":
        strategy = "wavefront"

    # per-level STACKED state: bp_stacks[lv] / s_stacks[lv] are (t_pad, Nb)
    # DEVICE arrays between levels (round-4 VERDICT item 2 — the old
    # per-level np.asarray round-trips cost ~1.3 s/level-set over the
    # ~9 MB/s tunnel, exactly the cost the single-chip driver already
    # eliminated); host copies are fetched ONCE at phase end.  With level
    # retries armed the stacks are host copies instead, so a retried level
    # rebuilds from buffers that survive a device reset (same §5.3 policy
    # as models/analogy.py).
    bp_stacks = [None] * levels
    s_stacks = [None] * levels
    n_cohs = []  # deferred (t_pad,) device scalars, one batched fetch
    recs = []
    # static query-side inputs as (T, H, W) per-level stacks: ONE shipped
    # array per level instead of per-frame transfers
    b_src_stacks = [np.stack([b_src_pyrs[i][lv] for i in range(t_pad)])
                    for lv in range(levels)]
    b_temp_stacks = ([np.stack([b_temp_pyrs[i][lv] for i in range(t_pad)])
                      for lv in range(levels)] if temporal else None)

    # §5.4 on the mesh path (round-3 VERDICT weak item 4): one stacked
    # (t_pad, Nb) npz per (phase, level), under a clip-aware digest, so a
    # preempted pod-scale video run resumes at level granularity instead
    # of restarting the clip.  Phase subdirectories keep phase-1 and
    # phase-2 planes apart; the save costs one host fetch per level —
    # the opt-in price the single-chip path pays too.
    ck_dir = None
    if params.checkpoint_dir:
        import os as _os

        from image_analogies_tpu.utils import checkpoint as ckpt

        ck_dir = _os.path.join(params.checkpoint_dir, tag)
        digest = ckpt.clip_digest(params, a_src.shape[:2],
                                  b_srcs[0].shape[:2], t_real, tag)

    for level in range(levels - 1, -1, -1):
        spec = spec_for_level(params, level, levels, src_channels,
                              temporal=temporal)
        coarse = level + 1 < levels

        if (ck_dir and params.resume_from_level is not None
                and level > params.resume_from_level):
            loaded = ckpt.load_level(ck_dir, level, digest=digest)
            if loaded is not None:
                # host copies chain into the next level's query build the
                # same way device stacks do
                bp_stacks[level] = loaded[0]
                s_stacks[level] = loaded[1]
                ialog.emit({"event": "resume_level", "level": level,
                            "phase": tag}, params.log_path)
                continue

        job0 = LevelJob(
            level=level,
            spec=spec,
            kappa_mult=params.kappa_factor(level) ** 2,
            a_src=a_src_pyr[level],
            a_filt=a_filt_pyr[level],
            b_src=b_src_pyrs[0][level],
            a_src_coarse=a_src_pyr[level + 1] if coarse else None,
            a_filt_coarse=a_filt_pyr[level + 1] if coarse else None,
            b_src_coarse=b_src_pyrs[0][level + 1] if coarse else None,
            a_temporal=a_filt_pyr[level] if temporal else None,
        )

        def _level():
            """The whole level's DEVICE work — features, sharded layout, and
            the mesh scan — so a transient-fault retry re-materializes every
            device buffer from host-side pyramids (stale captured buffers
            would just fail again after a real device reset).  The DB builds
            DIRECTLY sharded (build_sharded_db): no chip ever holds the full
            exemplar DB, during the build or the scan."""
            from image_analogies_tpu.utils.devcache import \
                device_put_cached

            # content-hash upload memoization (utils/devcache.py): the
            # A-side planes repeat across levels' retries, phases, and
            # clips; the B stacks repeat across phase 1 and phase 2
            to_j = lambda x: device_put_cached(x, jnp.float32)
            template = make_level_template(params, job0, strategy)
            tile = (tune.tile_rows(spec.total, strategy=strategy,
                                   dtype="f32") if not force_xla else 1)
            # real-TPU wavefront meshes scan with the packed kernel per
            # shard (the same exact_hi2_2p parity scan as the single
            # chip); CPU/virtual meshes keep the exact XLA path.  ONE
            # steering predicate shared with the sharded image path.
            from image_analogies_tpu.backends.tpu import \
                packed_scan_eligible

            packed = (strategy == "wavefront" and not force_xla
                      and packed_scan_eligible(
                          params.match_mode,
                          job0.a_shape[0] * job0.a_shape[1]))
            dbp, dbnp, afp, wk, _shift, dbl = build_sharded_db(
                spec, to_j(job0.a_src), to_j(job0.a_filt),
                to_j(job0.a_src_coarse), to_j(job0.a_filt_coarse),
                to_j(job0.a_temporal), template.rowsafe, mesh,
                strategy == "wavefront", tile, packed=packed)
            if packed:
                import dataclasses

                template = dataclasses.replace(template, feat_mean=_shift)
            # ONE batched jit builds every frame's query features; the
            # coarser B' planes chain in DEVICE-resident (reshaped from
            # the previous level's stacked output)
            bfc = None
            if coarse:
                h2, w2_ = b_src_pyrs[0][level + 1].shape[:2]
                bfc = jnp.reshape(
                    jnp.asarray(bp_stacks[level + 1]), (t_pad, h2, w2_))
            frame_static_q = _prepare_query_arrays_batch(
                spec, to_j(b_src_stacks[level]),
                to_j(b_src_stacks[level + 1]) if coarse else None,
                bfc,
                to_j(b_temp_stacks[level]) if temporal else None)
            out = multichip_level_step(
                mesh, frame_static_q, dbp, dbnp, afp, template,
                job0.kappa_mult, force_xla=force_xla, wk_shard=wk,
                dbl_shard=dbl)
            if params.level_retries > 0:
                # a transient device fault must surface INSIDE the retry
                # wrapper, not at the post-wrapper host fetch (same §5.3
                # invariant the single-chip path enforces)
                jax.block_until_ready(out)
            return out

        # the level span is the sharded path's only timing record: the
        # streamed per-frame stats below carry no ms fields (their device
        # scalars are deferred), so `ia report` reads mesh wall here
        with obs_trace.span("level", level=level, phase=tag):
            bp, s, n_coh = failure.run_with_retry(
                _level, retries=params.level_retries,
                context={"level": level, "phase": tag},
                log_path=params.log_path)
            obs_device.record_hbm(level, params.log_path)
        if params.level_retries > 0:
            # §5.3: retried levels must rebuild from host-resident state
            bp, s = np.asarray(bp, np.float32), np.asarray(s, np.int32)
        bp_stacks[level], s_stacks[level] = bp, s
        if level + 1 < levels:
            # this level's query build was the coarser stacks' last
            # reader (retries of THIS level already resolved above):
            # drop the references so the t_pad-wide (t, Nb) planes free
            # now instead of at phase end — on the mesh path the stacks
            # are the dominant per-level HBM residue
            bp_stacks[level + 1] = None
            s_stacks[level + 1] = None
        if ck_dir:
            ckpt.save_level(ck_dir, level, np.asarray(bp, np.float32),
                            np.asarray(s, np.int32), digest=digest)
        n_cohs.append(n_coh)
        hb, wb = job0.b_shape
        for i in range(t_real):
            rec = {
                "level": level, "frame": frame_offset + i, "phase": tag,
                "db_rows": job0.a_shape[0] * job0.a_shape[1],
                "pixels": hb * wb,
                "_n_coh_slot": (len(n_cohs) - 1, i),
                "backend": "tpu", "strategy": strategy,
                "mesh": dict(mesh.shape),
            }
            recs.append(rec)
            # STREAM the record now (a preempted run must not lose the
            # completed levels' telemetry); only coherence_ratio is
            # deferred — its device-scalar fetch costs ~0.1 s of tunnel
            # latency each, so all levels' counts fetch ONCE at phase
            # end and a compact summary record carries them
            ialog.emit({k: v for k, v in rec.items()
                        if k != "_n_coh_slot"}, params.log_path)

    # ONE fused fetch for everything the host consumes at phase end: the
    # deferred per-level coherence counts AND the finest level's stacked
    # planes — `jax.device_get` on the triple starts all three transfers
    # before blocking, so the scalar round-trip hides under the plane
    # payload (the same round-5 fusion the single-chip driver uses)
    with obs_trace.span("fetch", phase=tag):
        n_coh_all, bp0, s0 = jax.device_get(
            (jnp.stack([jnp.asarray(c) for c in n_cohs]),
             bp_stacks[0], s_stacks[0]))
    n_coh_all = np.asarray(n_coh_all)
    bp0 = np.asarray(bp0, np.float32)
    s0 = np.asarray(s0, np.int32)
    obs_metrics.inc("fetch.bytes", int(bp0.nbytes) + int(s0.nbytes))
    ratios = {}
    for rec in recs:
        lv_slot, i = rec.pop("_n_coh_slot")
        rec["coherence_ratio"] = (float(n_coh_all[lv_slot, i])
                                  / max(rec["pixels"], 1))
        ratios[f"l{rec['level']}_f{rec['frame']}"] = round(
            rec["coherence_ratio"], 4)
        stats.append(rec)
    ialog.emit({"event": "coherence_ratios", "phase": tag,
                "ratios": ratios}, params.log_path)
    if obs_metrics._ACTIVE:
        for rec in recs:
            obs_metrics.inc("kappa.coherence_px",
                            rec["coherence_ratio"] * rec["pixels"])
            obs_metrics.inc("kappa.total_px", rec["pixels"])

    hb, wb = b_src_pyrs[0][0].shape[:2]
    results = []
    for i in range(t_real):
        bp_y = bp0[i].reshape(hb, wb)
        s_map = s0[i].reshape(hb, wb)
        if params.color_mode == "source_rgb":
            ap_flat = (ap_rgb.reshape(-1, ap_rgb.shape[-1])
                       if ap_rgb.ndim == 3 else ap_rgb.reshape(-1))
            out = ap_flat[s_map.reshape(-1)].reshape(
                bp_y.shape + (() if ap_rgb.ndim == 2
                              else (ap_rgb.shape[-1],)))
        elif b_yiqs[i] is not None:
            out = color.yiq2rgb(np.stack(
                [bp_y, b_yiqs[i][..., 1], b_yiqs[i][..., 2]], axis=-1))
        else:
            out = np.clip(bp_y, 0.0, 1.0)
        results.append(AnalogyResult(bp=out, bp_y=bp_y,
                                     source_map_raw=s_map))
    return results


def video_analogy(
    a: np.ndarray,
    ap: np.ndarray,
    frames: Sequence[np.ndarray],
    params: AnalogyParams = AnalogyParams(temporal_weight=1.0),
    scheme: str = "two_phase",
    backend=None,
) -> VideoResult:
    # one observability run per CLIP: the per-frame engine calls below
    # join this scope (reentrant run_scope) instead of minting their own
    # run_ids.  Likewise one TUNE resolution per clip: pin_scope caches
    # the first consult of each geometry key, so every frame batch bakes
    # identical kernel ints (byte-comparable frame timings) and the
    # provenance counters record one consult per clip, not per frame.
    with obs_trace.run_scope(params):
        with tune_resolve.pin_scope():
            if len(frames) > 0:
                _pin_clip_geometry(a, frames, params)
            return _video_analogy(a, ap, frames, params, scheme, backend)


def _pin_clip_geometry(a, frames, params: AnalogyParams) -> None:
    """Resolve the clip's finest-level kernel geometry up front, inside
    the clip's pin scope: later per-level/per-frame consults of the same
    key (the mesh path's ``tune.tile_rows`` calls) return this pinned
    config without touching the store again."""
    from image_analogies_tpu.ops.features import spec_for_level
    from image_analogies_tpu.ops.pyramid import num_feasible_levels

    a_np = np.asarray(a)
    strategy = "wavefront" if params.strategy == "auto" else params.strategy
    shapes = [np.asarray(f).shape for f in frames]
    min_shape = (min([a_np.shape[0]] + [s[0] for s in shapes]),
                 min([a_np.shape[1]] + [s[1] for s in shapes]))
    levels = num_feasible_levels(min_shape, params.levels, params.patch_size)
    src_channels = (1 if params.color_mode == "yiq_transfer"
                    or a_np.ndim == 2 else a_np.shape[-1])
    spec = spec_for_level(params, 0, levels, src_channels,
                          temporal=params.temporal_weight > 0)
    tune_resolve.tile_rows(spec.total, strategy=strategy, dtype="f32")


def _video_analogy(a, ap, frames, params, scheme, backend) -> VideoResult:
    if scheme not in ("sequential", "two_phase"):
        raise ValueError(f"unknown scheme {scheme!r}")
    frames = list(frames)
    if not frames:
        return VideoResult(frames=[], frames_y=[])

    stats: List[Dict[str, Any]] = []

    if params.data_shards > 1:
        if scheme != "two_phase":
            raise ValueError(
                "frame sharding (data_shards > 1) requires the data-parallel "
                "two_phase scheme; the sequential recurrence cannot shard")
        if backend is not None:
            raise ValueError("data_shards > 1 uses the mesh TPU path; a "
                             "custom backend cannot be injected")
        if params.backend != "tpu":
            raise ValueError(
                f"data_shards > 1 requires backend='tpu' (the mesh path); "
                f"got backend={params.backend!r}")
        if params.strategy in ("exact", "rowwise"):
            raise ValueError(
                f"strategy {params.strategy!r} has no mesh scan core; frame "
                "sharding supports 'wavefront' (oracle parity), 'batched', "
                "or 'auto'")
        import contextlib

        from image_analogies_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(db_shards=params.db_shards,
                         data_shards=params.data_shards)
        prof = contextlib.nullcontext()
        if params.profile_dir:
            import jax

            prof = jax.profiler.trace(params.profile_dir)
        with prof:
            with obs_trace.span("phase", phase="phase1"):
                phase1 = _sharded_phase(a, ap, frames, params, mesh, None,
                                        stats, "phase1",
                                        remap_anchor=frames[0])
            if len(frames) == 1:
                outs = phase1
            else:
                prevs = [phase1[t - 1].bp_y for t in range(1, len(frames))]
                with obs_trace.span("phase", phase="phase2"):
                    phase2 = _sharded_phase(a, ap, frames[1:], params, mesh,
                                            prevs, stats, "phase2",
                                            remap_anchor=frames[0],
                                            frame_offset=1)
                outs = [phase1[0]] + phase2
        return VideoResult(frames=[r.bp for r in outs],
                           frames_y=[r.bp_y for r in outs], stats=stats)

    def synth(b, prev_y, tag, idx):
        # remap anchored on the clip's FIRST frame — the same consistent
        # per-clip A mapping the mesh path uses (round-2 ADVICE item 3), so
        # serial and sharded runs agree with remap_luminance=True too
        res = create_image_analogy(a, ap, b, params, backend=backend,
                                   temporal_prev=prev_y,
                                   remap_anchor=frames[0])
        for st in res.stats:
            st.update(frame=idx, phase=tag)
            stats.append(st)
        return res

    if scheme == "sequential":
        outs, prev_y = [], None
        for t, b in enumerate(frames):
            res = synth(b, prev_y, "seq", t)
            prev_y = res.bp_y
            outs.append(res)
        return VideoResult(frames=[r.bp for r in outs],
                           frames_y=[r.bp_y for r in outs], stats=stats)

    # two_phase: phase 1 frames are independent (shardable over 'data')
    phase1 = [synth(b, None, "phase1", t) for t, b in enumerate(frames)]
    outs = [phase1[0]]
    for t in range(1, len(frames)):
        outs.append(synth(frames[t], phase1[t - 1].bp_y, "phase2", t))
    return VideoResult(frames=[r.bp for r in outs],
                       frames_y=[r.bp_y for r in outs], stats=stats)
