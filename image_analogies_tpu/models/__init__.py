"""Synthesis driver and application modes."""

from image_analogies_tpu.models.analogy import AnalogyResult, create_image_analogy

__all__ = ["AnalogyResult", "create_image_analogy"]
