"""Application modes (SURVEY.md §2 P9, §3.4-3.5, BASELINE.json:6-12).

One engine, several applications by varying inputs (Hertzmann §6):

- ``artistic_filter``     A : A' :: B : B' with a filtered training pair
                          (oil paint, watercolor, line art, blur pairs).
- ``texture_by_numbers``  A = label map, A' = real texture; paint a new label
                          map B and get a plausible B' texture.
- ``super_resolution``    A = downgraded A', so the analogy learns
                          low-res -> high-res detail; apply to a low-res B.
- ``texture_synthesis``   degenerate analogy with the unfiltered planes
                          ignored (src_weight = 0): plain patch-based
                          synthesis of more texture like A'.
- ``video``               batched B-frames with a temporal-coherence term —
                          see models/video.py.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from image_analogies_tpu.config import PRESETS, AnalogyParams
from image_analogies_tpu.models.analogy import AnalogyResult, create_image_analogy
from image_analogies_tpu.ops import color, pyramid


def artistic_filter(a, ap, b, params: Optional[AnalogyParams] = None,
                    **overrides) -> AnalogyResult:
    """Classic A : A' :: B : B' filter transfer (BASELINE config 2/4)."""
    params = (params or PRESETS["oil_filter"]).replace(**overrides)
    return create_image_analogy(a, ap, b, params)


def texture_by_numbers(labels_a, texture_a, labels_b,
                       params: Optional[AnalogyParams] = None,
                       **overrides) -> AnalogyResult:
    """A = label map, A' = texture, B = new label map (BASELINE config 1)."""
    params = (params or PRESETS["texture_by_numbers"]).replace(**overrides)
    return create_image_analogy(labels_a, texture_a, labels_b, params)


def blur_for_superres(img: np.ndarray, passes: int = 2) -> np.ndarray:
    """The degradation used to build the super-res training pair: repeated
    binomial blur (matching the pyramid stencil, so the coarse statistics
    of A and B agree)."""
    out = color.as_float(img)
    for _ in range(passes):
        out = pyramid.blur_np(out)
    return out


def super_resolution(sharp_a: np.ndarray, low_b: np.ndarray,
                     params: Optional[AnalogyParams] = None,
                     blur_passes: int = 2, **overrides) -> AnalogyResult:
    """Sharpen `low_b` by analogy with a sharp exemplar (BASELINE config 3).

    A = blur(A'), A' = sharp_a; B = low_b (blurred the same way so its
    statistics match A's).
    """
    params = (params or PRESETS["super_resolution"]).replace(**overrides)
    a = blur_for_superres(sharp_a, blur_passes)
    b = blur_for_superres(low_b, 0)
    return create_image_analogy(a, sharp_a, b, params)


def texture_synthesis(texture: np.ndarray, out_shape,
                      params: Optional[AnalogyParams] = None,
                      seed: Optional[int] = None, seed_weight: float = 0.1,
                      **overrides) -> AnalogyResult:
    """Synthesize an out_shape patch of more `texture` (src_weight = 0: only
    the causal B' windows drive matching — Ashikhmin-style synthesis).

    With ``seed`` set, repeated syntheses DIFFER: A and B become noise
    planes resampled from the exemplar's values with a small feature weight
    (``src_weight = seed_weight``), randomizing the early approximate picks
    while coherence still dominates the texture structure.  (Noise in B
    alone would be inert — with A all-zero it shifts every DB row's distance
    equally.)  ``seed=None`` keeps the fully deterministic degenerate
    analogy.  An explicit ``src_weight`` override wins over ``seed_weight``."""
    params = (params or PRESETS["texture_synthesis"]).replace(**overrides)
    tex = color.as_float(texture)
    if seed is None:
        if params.src_weight != 0.0:
            params = params.replace(src_weight=0.0)
        a = np.zeros(tex.shape[:2], np.float32)
        b = np.zeros(tuple(out_shape), np.float32)
    else:
        rng = np.random.default_rng(seed)
        vals = (tex if tex.ndim == 2 else color.luminance(tex)).reshape(-1)
        a = rng.choice(vals, size=tex.shape[:2]).astype(np.float32)
        b = rng.choice(vals, size=tuple(out_shape)).astype(np.float32)
        if "src_weight" not in overrides:
            params = params.replace(src_weight=seed_weight)
    return create_image_analogy(a, tex, b, params)
