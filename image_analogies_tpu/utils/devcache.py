"""Content-addressed device-upload cache.

This box's PJRT tunnel moves ~9 MB/s host->device, so re-uploading the SAME
immutable input planes on every synthesis call costs ~1.3 s of the 1024^2
north star per run (measured round 4: wall 8.5 s vs 6.3 s device time, the
gap being input uploads + the result fetch).  Real TPU hosts move these in
milliseconds, but the principle stands everywhere: a warm engine should not
re-pay data movement for bit-identical inputs (the exemplar pair A/A' is
reused across every frame/run in practice).

`device_put_cached` keys on the CONTENT (sha1 of bytes + shape/dtype), not
object identity, so mutation can never serve a stale buffer — a changed
array hashes to a new key.  Hashing costs ~5 ms per 4 MB plane, ~100x
cheaper than this tunnel's upload.  The cache is process-local and
byte-bounded (LRU); `clear()` drops it (failure-retry paths call this via
jax.clear_caches anyway producing fresh uploads).
"""

from __future__ import annotations

import hashlib
import os
from collections import OrderedDict
from typing import Optional

import numpy as np

from image_analogies_tpu import chaos
from image_analogies_tpu.obs import metrics as obs_metrics

_DEFAULT_MAX_BYTES = 1 << 30  # 1 GiB of cached device inputs
_configured_max: Optional[int] = None
_cache: "OrderedDict[tuple, object]" = OrderedDict()
_bytes = 0


def max_bytes() -> int:
    """Effective byte budget: env IA_DEVCACHE_BYTES > configured > 1 GiB.
    Read at call time so tests/operators can flip it on a live process."""
    env = os.environ.get("IA_DEVCACHE_BYTES", "").strip()
    if env:
        try:
            n = int(env)
            if n > 0:
                return n
        except ValueError:
            pass
    if _configured_max:
        return _configured_max
    return _DEFAULT_MAX_BYTES


def set_max_bytes(n: Optional[int]) -> None:
    """Configure the budget (AnalogyParams.devcache_max_bytes plumbs
    here); None restores the default.  Env still wins."""
    global _configured_max
    _configured_max = int(n) if n else None


def clear() -> None:
    global _bytes
    _cache.clear()
    _bytes = 0
    obs_metrics.set_gauge("devcache.bytes", 0)


def device_put_cached(x, dtype=None):
    """jnp.asarray(x, dtype) memoized by content hash.

    Only plain host ndarrays are cached; device arrays and non-arrays pass
    through (already resident / trivial).  Returns a device array that MUST
    be treated as immutable (all engine consumers are)."""
    import jax
    import jax.numpy as jnp

    if x is None:
        return None
    if isinstance(x, jax.Array):
        return x if dtype is None else jnp.asarray(x, dtype)
    arr = np.asarray(x, dtype)
    if arr.nbytes < (1 << 16):  # tiny arrays: hashing gains nothing
        return jnp.asarray(arr)
    global _bytes
    h = hashlib.sha1(arr.tobytes()).hexdigest()
    key = (h, arr.shape, str(arr.dtype), str(jax.default_backend()))
    # Entries carry their upload size so eviction releases EXACTLY the
    # bytes the insert charged (recomputing from the device array could
    # silently fail and drift the gauge) and the per-entry churn is
    # reportable (devcache.evicted_bytes — the catalog tier report).
    hit_entry = _cache.get(key)
    if hit_entry is not None:
        hit, hit_nbytes = hit_entry
        deleted = True
        try:
            deleted = hit.is_deleted()
        except Exception:  # pragma: no cover - treat unknown as dead
            pass
        if not deleted:
            _cache.move_to_end(key)
            obs_metrics.inc("devcache.hits")
            return hit
        _bytes -= hit_nbytes
        _cache.pop(key, None)
        obs_metrics.inc("devcache.dead_evictions")
        obs_metrics.inc("devcache.evicted_bytes", hit_nbytes)
        obs_metrics.set_gauge("devcache.bytes", _bytes)
    chaos.site("devcache.upload", nbytes=arr.nbytes)
    dev = jax.device_put(jnp.asarray(arr))
    _cache[key] = (dev, arr.nbytes)
    _bytes += arr.nbytes
    obs_metrics.inc("devcache.misses")
    obs_metrics.inc("devcache.upload_bytes", arr.nbytes)
    limit = max_bytes()
    while _bytes > limit and _cache:
        _, (_, old_nbytes) = _cache.popitem(last=False)
        _bytes -= old_nbytes
        obs_metrics.inc("devcache.evictions")
        obs_metrics.inc("devcache.evicted_bytes", old_nbytes)
    obs_metrics.set_gauge("devcache.bytes", _bytes)
    return dev
