"""Tie-audit: mechanically explain source-map mismatches vs the oracle
(round-2 VERDICT missing item 4 / next-round item 2).

The parity claim behind `value_match` is: where the TPU wavefront's source
map differs from the CPU/cKDTree oracle's, the cause is an EXACT-COST TIE
(thousands of identical/equal-cost patches in posterized regions; cKDTree
breaks those in traversal order, the TPU kernel lowest-index) or the
deterministic downstream consequence of an earlier tie.  This module turns
that narrative into a checked theorem over a pair of runs:

For every level (coarsest first) and every pixel q with s_x[q] != s_y[q]:

1. rebuild BOTH runs' exact decision context at q — the full query vector
   (static B features + that run's coarse-level B' windows + the causal
   window of that run's evolving B' plane; every causal value is final at
   decision time, so the FINAL planes reconstruct it exactly) and the
   causal source-map window (which generates the Ashikhmin candidates);
2. if the contexts differ in ANY input, the mismatch is `ctx_diverged`:
   the deterministic consequence of an earlier divergence (itself rooted,
   recursively, in a tie — the FIRST mismatch in scan order at the
   coarsest mismatching level necessarily has a clean context, which the
   audit asserts);
3. if the contexts are IDENTICAL, both runs faced the same deterministic
   decision problem, so differing picks are only legal inside the engines'
   arithmetic resolution.  Re-score both picks' squared distances in
   float64 and classify:
   - `tie_exact`: bit-equal cost (duplicate patches — the dominant case);
   - `tie_fp`: cost gap within ``tol`` of the SCORE magnitude
     (||q||^2 + ||db_pick||^2) — the resolution band of the kernel's
     HIGHEST (3x bf16) arithmetic, where distances are differences of
     O(1) numbers and a ~1e-7-absolute score error legitimately reorders
     near-equal rows (measured: the observed band is ~7e-7 relative);
   - `kappa_boundary`: the picks sit on DIFFERENT branches of the kappa
     rule (one coherence, one approximate) because d_coh sits within the
     resolution band of d_app * kappa_mult — verified by recomputing the
     full float64 decision (full-DB argmin + Ashikhmin candidates) from
     the shared context;
   - `unexplained`: anything else — a REAL disparity, target count 0.

Used by bench.py (reports `mismatch_explained_by_ties` per oracle seed) and
tests/test_parity_audit.py.
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np

from image_analogies_tpu.ops.features import (
    build_features_np,
    fine_gather_maps,
    spec_for_level,
)
from image_analogies_tpu.ops.pyramid import build_pyramid_np


def audit_source_map_mismatches(
    a: np.ndarray,
    ap: np.ndarray,
    b: np.ndarray,
    params,
    levels_x: Sequence[Tuple[np.ndarray, np.ndarray]],
    levels_y: Sequence[Tuple[np.ndarray, np.ndarray]],
    tol: float = 2e-6,
) -> Dict:
    """Audit run X (e.g. TPU wavefront) against run Y (oracle).

    ``levels_*``: per-level (bp, s) planes, FINEST FIRST (the
    `create_image_analogy(..., keep_levels=True)` layout; the cached oracle
    npz stores them as bp_l{i}/s_l{i}).  Inputs a/ap/b and params must be
    exactly those of the two runs.

    Returns a dict with per-level records and aggregate fractions; see
    module docstring for the classification."""
    from image_analogies_tpu.models.analogy import _prep_planes

    a_src, b_src, a_filt, _, _ = _prep_planes(a, ap, b, params)
    levels = len(levels_x)
    if len(levels_y) != levels:
        raise ValueError(f"level count mismatch: {levels} vs {len(levels_y)}")

    a_src_pyr = build_pyramid_np(a_src, levels)
    a_filt_pyr = build_pyramid_np(a_filt, levels)
    b_src_pyr = build_pyramid_np(b_src, levels)
    src_channels = 1 if a_src.ndim == 2 else a_src.shape[-1]

    per_level: List[Dict] = []
    total = {"mismatches": 0, "ctx_diverged": 0, "tie_exact": 0,
             "tie_fp": 0, "kappa_boundary": 0, "unexplained": 0}
    first_divergence_is_tie = None  # set at the coarsest mismatching level
    max_fp_band = 0.0  # worst observed relative score gap among fp ties

    for level in range(levels - 1, -1, -1):  # coarsest -> finest (scan order)
        bp_x, s_x = levels_x[level]
        bp_y, s_y = levels_y[level]
        sx = np.asarray(s_x, np.int64).reshape(-1)
        sy = np.asarray(s_y, np.int64).reshape(-1)
        bx = np.asarray(bp_x, np.float32).reshape(-1)
        by = np.asarray(bp_y, np.float32).reshape(-1)
        hb, wb = np.asarray(bp_x).shape
        mism = np.nonzero(sx != sy)[0]
        rec = {"level": level, "pixels": hb * wb,
               "mismatches": int(mism.size)}
        if mism.size == 0:
            rec.update(ctx_diverged=0, tie_exact=0, tie_fp=0,
                       kappa_boundary=0, unexplained=0)
            per_level.append(rec)
            continue

        spec = spec_for_level(params, level, levels, src_channels)
        coarse = level + 1 < levels
        db = build_features_np(
            spec, a_src_pyr[level], a_filt_pyr[level],
            a_src_pyr[level + 1] if coarse else None,
            a_filt_pyr[level + 1] if coarse else None)

        def static_q_for(levels_run):
            return build_features_np(
                spec, b_src_pyr[level], None,
                b_src_pyr[level + 1] if coarse else None,
                np.asarray(levels_run[level + 1][0], np.float32)
                if coarse else None)

        stat_x = static_q_for(levels_x)
        stat_y = static_q_for(levels_y)
        flat_idx, valid, written = fine_gather_maps(hb, wb, spec.fine_size)
        fsl = spec.fine_filt_slice
        sqrtw = spec.sqrt_weights()[fsl]

        win = flat_idx[mism]  # (M, nf) clipped causal window positions
        wr = written[mism] * sqrtw[None, :]
        qx = stat_x[mism].copy()
        qx[:, fsl] = bx[win] * wr
        qy = stat_y[mism].copy()
        qy[:, fsl] = by[win] * wr

        v = valid[mism] > 0
        s_ctx_eq = np.all((sx[win] == sy[win]) | ~v, axis=1)
        q_eq = np.all(qx == qy, axis=1)
        clean = q_eq & s_ctx_eq

        db64 = db.astype(np.float64)
        dbn64 = np.sum(db64 * db64, axis=1)
        dx = np.sum((db64[sx[mism]] - qx.astype(np.float64)) ** 2, axis=1)
        dy = np.sum((db64[sy[mism]] - qy.astype(np.float64)) ** 2, axis=1)
        dd = np.abs(dx - dy)
        # the engines' score-arithmetic resolution: scores are
        # dbn - 2 q.db, O(||q||^2 + ||db||^2) numbers whose DIFFERENCE is
        # the tiny distance — fp32/HIGHEST granularity is relative to the
        # big terms, not to the distance
        qn = np.sum(qx.astype(np.float64) ** 2, axis=1)
        scale = qn + np.maximum(dbn64[sx[mism]], dbn64[sy[mism]])
        tie_exact = clean & (dd == 0.0)
        tie_fp = clean & (dd > 0.0) & (dd <= tol * np.maximum(scale, 1e-12))
        hard = np.nonzero(clean & ~tie_exact & ~tie_fp)[0]

        band = dd[tie_fp] / np.maximum(scale[tie_fp], 1e-12)
        if band.size:
            max_fp_band = max(max_fp_band, float(band.max()))

        # remaining clean mismatches: recompute the full float64 decision
        # from the shared context — a branch flip at the kappa boundary is
        # legal when d_coh sits within resolution of d_app * kappa_mult
        kappa_boundary = np.zeros(mism.size, bool)
        kappa_mult = params.kappa_factor(level) ** 2
        ha, wa = a_filt_pyr[level].shape[:2]
        if hard.size:
            from image_analogies_tpu.ops.features import window_offsets

            off = window_offsets(spec.fine_size)
        for k in hard:
            qv = qx[k].astype(np.float64)
            d_all = dbn64 - 2.0 * (db64 @ qv)  # + ||q||^2, argmin-invariant
            d_app = float(d_all.min() + qn[k])
            vk = v[k]
            rf = win[k][vk]
            o = off[vk]
            si = sx[rf] // wa - o[:, 0]
            sj = sx[rf] % wa - o[:, 1]
            inb = (si >= 0) & (si < ha) & (sj >= 0) & (sj < wa)
            if not inb.any():
                continue
            cand = (si[inb] * wa + sj[inb]).astype(np.int64)
            d_coh = float(np.min(np.sum(
                (db64[cand] - qv[None, :]) ** 2, axis=1)))
            # boundary: the branch condition d_coh <= d_app * mult is
            # decided by quantities the engines only know to ~tol * scale
            if abs(d_coh - d_app * kappa_mult) <= tol * scale[k] * max(
                    kappa_mult, 1.0):
                kappa_boundary[k] = True
        unexplained = clean & ~tie_exact & ~tie_fp & ~kappa_boundary

        if first_divergence_is_tie is None:
            # scan-order-first mismatch at the coarsest mismatching level:
            # nothing can have diverged before it, so it MUST be explained
            # by the engines' resolution (tie or boundary), never ctx
            k = int(np.argmin(mism))
            first_divergence_is_tie = bool(tie_exact[k] or tie_fp[k]
                                           or kappa_boundary[k])

        rec.update(
            ctx_diverged=int((~clean).sum()),
            tie_exact=int(tie_exact.sum()),
            tie_fp=int(tie_fp.sum()),
            kappa_boundary=int(kappa_boundary.sum()),
            unexplained=int(unexplained.sum()),
        )
        per_level.append(rec)
        for k in total:
            total[k] += rec[k]

    m = max(total["mismatches"], 1)
    clean_n = (total["tie_exact"] + total["tie_fp"]
               + total["kappa_boundary"] + total["unexplained"])
    return {
        "per_level": per_level,
        **total,
        "mismatch_explained_by_ties": round(1.0 - total["unexplained"] / m,
                                            6),
        "clean_ctx_tie_fraction": round(
            (clean_n - total["unexplained"]) / max(clean_n, 1), 6),
        "first_divergence_is_tie": first_divergence_is_tie,
        "max_fp_band": max_fp_band,
        "tol": tol,
    }
