"""Structured observability (SURVEY.md §5.5).

Per-level synthesis emits one record: level, db_rows, pixels, coherence pick
ratio, wall-clock ms, backend — appended as JSON lines when a log path is
configured and mirrored to the standard `logging` module.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any, Dict, Optional, TextIO

logger = logging.getLogger("image_analogies_tpu")

# Optional per-record stamper (obs.trace registers one at import to add
# run_id/seq while a run is active).  Kept as a hook so this module stays
# import-cycle-free: obs imports utils.logging, never the reverse.
_STAMPER: Optional[Any] = None


def set_record_stamper(fn) -> None:
    global _STAMPER
    _STAMPER = fn


# Per-path append-handle cache, active only between begin_handle_cache /
# end_handle_cache (obs.trace.run_scope brackets a run with them): the
# hot level loop streams one JSONL record per level/frame, and one
# open+close per record was pure syscall overhead.  Outside a run the
# historic open-append-close per record is preserved (no handle held
# across unrelated emit() calls).
_HANDLE_LOCK = threading.Lock()
_HANDLES: Dict[str, TextIO] = {}
_CACHING = 0  # nesting count of active cache scopes


def begin_handle_cache() -> None:
    global _CACHING
    with _HANDLE_LOCK:
        _CACHING += 1


def end_handle_cache() -> None:
    """Flush + close every cached handle when the outermost scope ends."""
    global _CACHING
    with _HANDLE_LOCK:
        _CACHING = max(_CACHING - 1, 0)
        if _CACHING:
            return
        for f in _HANDLES.values():
            try:
                f.flush()
                f.close()
            except OSError:
                pass
        _HANDLES.clear()


def _write_line(path: str, line: str) -> None:
    if _CACHING:
        with _HANDLE_LOCK:
            if _CACHING:  # re-check under the lock
                f = _HANDLES.get(path)
                if f is None:
                    os.makedirs(os.path.dirname(os.path.abspath(path)),
                                exist_ok=True)
                    f = _HANDLES[path] = open(path, "a")
                f.write(line + "\n")
                return
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "a") as f:
        f.write(line + "\n")


def emit(record: Dict[str, Any], path: Optional[str] = None) -> None:
    record = dict(record)
    record.setdefault("ts", time.time())
    if _STAMPER is not None:
        _STAMPER(record)
    logger.info("%s", json.dumps(record, sort_keys=True))
    if path:
        _write_line(path, json.dumps(record, sort_keys=True))
