"""Structured observability (SURVEY.md §5.5).

Per-level synthesis emits one record: level, db_rows, pixels, coherence pick
ratio, wall-clock ms, backend — appended as JSON lines when a log path is
configured and mirrored to the standard `logging` module.
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Any, Dict, Optional

logger = logging.getLogger("image_analogies_tpu")

# Optional per-record stamper (obs.trace registers one at import to add
# run_id/seq while a run is active).  Kept as a hook so this module stays
# import-cycle-free: obs imports utils.logging, never the reverse.
_STAMPER: Optional[Any] = None


def set_record_stamper(fn) -> None:
    global _STAMPER
    _STAMPER = fn


def emit(record: Dict[str, Any], path: Optional[str] = None) -> None:
    record = dict(record)
    record.setdefault("ts", time.time())
    if _STAMPER is not None:
        _STAMPER(record)
    logger.info("%s", json.dumps(record, sort_keys=True))
    if path:
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(path, "a") as f:
            f.write(json.dumps(record, sort_keys=True) + "\n")
