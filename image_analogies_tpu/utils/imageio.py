"""Image load/save (SURVEY.md §2 P2).

Decode/encode stays host-side (SURVEY.md §2.2 N3 — not performance-relevant);
arrays ship to the device once per level.  PIL when available, with a NumPy
``.npy`` fallback so the framework has zero hard I/O dependencies.
"""

from __future__ import annotations

import os

import numpy as np


def load_image(path: str) -> np.ndarray:
    """Load an image as float32 in [0,1], (H,W) gray or (H,W,3) RGB."""
    if path.endswith(".npy"):
        arr = np.load(path)
        return _to_float(arr)
    from PIL import Image

    with Image.open(path) as im:
        if im.mode not in ("L", "RGB"):
            im = im.convert("RGB")
        arr = np.asarray(im)
    return _to_float(arr)


def _to_float(arr: np.ndarray) -> np.ndarray:
    from image_analogies_tpu.ops.color import as_float

    arr = as_float(arr)
    if arr.ndim == 3 and arr.shape[-1] == 4:
        arr = arr[..., :3]  # strip alpha
    return arr


def save_image(path: str, img: np.ndarray) -> None:
    """Save float [0,1] (H,W) or (H,W,3) as PNG/JPG (or .npy)."""
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    img = np.clip(np.asarray(img, np.float32), 0.0, 1.0)
    if path.endswith(".npy"):
        np.save(path, img)
        return
    from PIL import Image

    u8 = (img * 255.0 + 0.5).astype(np.uint8)
    Image.fromarray(u8).save(path)
