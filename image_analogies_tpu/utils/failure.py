"""Failure detection + level-granular recovery (SURVEY.md §5.3).

The reference has nothing here; the natural recovery unit in this framework
is the pyramid LEVEL: all cross-level state is exactly {B' plane, source
map} (Hertzmann §3), already checkpointable (utils/checkpoint.py).  The
driver therefore wraps each level's device work in `run_with_retry`:

- transient device/runtime faults (PJRT resets, preemption-style errors,
  OOM after fragmentation) surface in JAX as `JaxRuntimeError` /
  `XlaRuntimeError`; the wrapper detects them, emits a structured
  `level_retry` record, clears JAX's live-array caches so retries
  re-materialize inputs, and re-runs the level;
- programming errors (TypeError, ValueError, shape mismatches ...) are NOT
  retried — retrying those only hides bugs;
- with `checkpoint_dir` set, completed coarser levels resume from disk, so
  a process-level restart after exhausted retries loses at most one level.

`inject_failures` is the fault-injection hook (SURVEY.md §5.3's test story):
it makes the NEXT `n` wrapped calls raise a synthetic transient error, so
recovery paths are exercised deterministically in CI without real faults.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Callable, Optional

from image_analogies_tpu.obs import metrics as obs_metrics
from image_analogies_tpu.obs import recorder as obs_recorder
from image_analogies_tpu.utils import logging as ialog

# Synthetic-fault state (fault injection for tests/drills).
_INJECT = {"n": 0}


class InjectedFailure(RuntimeError):
    """Synthetic transient fault raised by `inject_failures`."""


class WatchdogTimeout(RuntimeError):
    """A watchdogged dispatch exceeded its deadline: the op is presumed
    wedged and the timeout surfaces as a TRANSIENT fault (retryable) —
    a hang becomes a retry instead of a stuck process."""


def inject_failures(n: int) -> None:
    """Arm the fault injector: the next `n` `run_with_retry` bodies raise
    `InjectedFailure` before running their real work."""
    _INJECT["n"] = int(n)


# XLA status-code substrings that mark a runtime error as a PROGRAM bug
# surfacing at execution time (bad shapes, donated-buffer misuse, ...):
# retrying those only hides bugs.  Anything else in the runtime-error
# classes (UNAVAILABLE, INTERNAL, RESOURCE_EXHAUSTED, DATA_LOSS, connection
# resets...) is treated as device-side and worth a retry.
_NON_TRANSIENT_CODES = ("INVALID_ARGUMENT", "FAILED_PRECONDITION",
                        "UNIMPLEMENTED")


def _is_transient(exc: BaseException) -> bool:
    """Transient == worth retrying: device/runtime faults, not bugs.

    Walks ``__cause__``/``__context__`` chains: jax re-raises device
    faults wrapped in tracing-layer exceptions (and callers sometimes
    wrap them again), so the transient signal may sit several links deep.
    A non-transient runtime code anywhere in the chain wins — an
    INVALID_ARGUMENT stays a bug no matter what it was wrapped in.
    """
    seen = set()
    while exc is not None and id(exc) not in seen:
        seen.add(id(exc))
        if isinstance(exc, (InjectedFailure, WatchdogTimeout)):
            return True
        # jax.errors.JaxRuntimeError wraps XLA/PJRT runtime failures; keep
        # the check name-based so this works across jax versions without
        # importing private exception types.
        for klass in type(exc).__mro__:
            if klass.__name__ in ("JaxRuntimeError", "XlaRuntimeError"):
                msg = str(exc)
                return not any(code in msg for code in _NON_TRANSIENT_CODES)
        exc = exc.__cause__ or exc.__context__
    return False


def backoff_delay(attempt: int, *, backoff_s: float = 0.5,
                  backoff_cap_s: float = 8.0,
                  jitter_seed: Optional[int] = None) -> float:
    """Delay before retry ``attempt`` (1-based): capped exponential with
    deterministic seeded jitter.

    Base doubles per attempt (``backoff_s * 2**(attempt-1)``) and is
    capped at ``backoff_cap_s`` — the old linear, unjittered
    ``backoff_s * attempt`` both hammered a struggling device early and
    synchronized every retrying caller into lockstep thundering herds.
    Jitter multiplies by [0.5, 1.0) drawn from ``Random((jitter_seed,
    attempt))``: the same (seed, attempt) always sleeps the same time,
    so drills and tests stay reproducible while distinct seeds (serve
    workers pass their request id) de-correlate.
    """
    base = min(backoff_s * (2.0 ** max(attempt - 1, 0)), backoff_cap_s)
    if base <= 0:
        return 0.0
    # arithmetic combine (not a tuple): tuple seeding goes through
    # hash() — deprecated, and unstable across processes for str parts
    frac = random.Random((jitter_seed or 0) * 1000003 + attempt).random()
    return base * (0.5 + 0.5 * frac)


def run_with_retry(
    fn: Callable[[], Any],
    *,
    retries: int = 0,
    context: Optional[dict] = None,
    log_path: Optional[str] = None,
    backoff_s: float = 0.5,
    backoff_cap_s: float = 8.0,
    jitter_seed: Optional[int] = None,
) -> Any:
    """Run `fn()`, retrying up to `retries` times on transient faults.

    Each detected fault emits a `level_retry` JSONL record (utils/logging)
    with the error type and attempt number; retry delays follow
    :func:`backoff_delay` (capped exponential, seeded jitter).
    Non-transient exceptions propagate unchanged; a fault beyond the
    budget bumps ``retry.exhausted`` and propagates the ORIGINAL
    exception (callers keep their type checks).
    """
    attempt = 0
    while True:
        try:
            if _INJECT["n"] > 0:
                _INJECT["n"] -= 1
                raise InjectedFailure("synthetic fault (inject_failures)")
            return fn()
        except BaseException as exc:  # noqa: BLE001 - filtered below
            if not _is_transient(exc):
                raise
            if attempt >= retries:
                if retries > 0:
                    # only a real exhausted BUDGET counts: retries=0
                    # callers never opted into recovery at all
                    obs_metrics.inc("retry.exhausted")
                    ialog.emit({
                        "event": "retry_exhausted",
                        "attempts": attempt + 1,
                        "error": type(exc).__name__,
                        **(context or {}),
                    }, log_path)
                raise
            attempt += 1
            obs_metrics.inc("level_retry")
            ialog.emit({
                "event": "level_retry",
                "attempt": attempt,
                "error": type(exc).__name__,
                "detail": str(exc)[:200],
                **(context or {}),
            }, log_path)
            try:
                import jax

                jax.clear_caches()  # drop live executables/buffers that may
                # reference poisoned device state before re-running
                from image_analogies_tpu.utils import devcache

                devcache.clear()  # cached input uploads may reference the
                # same poisoned device state; retries must re-upload
            except Exception:  # pragma: no cover - cache clear is best-effort
                pass
            time.sleep(backoff_delay(attempt, backoff_s=backoff_s,
                                     backoff_cap_s=backoff_cap_s,
                                     jitter_seed=jitter_seed))


def run_with_watchdog(
    fn: Callable[[], Any],
    timeout_s: float,
    *,
    context: Optional[dict] = None,
    log_path: Optional[str] = None,
) -> Any:
    """Run ``fn()`` with a wall-clock watchdog.

    The body runs on a daemon thread; if it has not finished within
    ``timeout_s`` the caller raises :class:`WatchdogTimeout` — which
    `_is_transient` treats as retryable, so a wedged device op surfaces
    inside `run_with_retry` as one more transient fault instead of
    hanging the process.  Python threads cannot be killed: the wedged
    body is ABANDONED (its eventual result or error is swallowed and
    counted as ``watchdog.abandoned``), which is safe here because the
    retry path already re-materializes inputs (cache clears) before
    re-running.  With ``timeout_s <= 0`` the body runs inline —
    zero-thread, zero-cost passthrough.
    """
    if timeout_s <= 0:
        return fn()
    box: dict = {}
    done = threading.Event()

    def _body():
        try:
            box["result"] = fn()
        except BaseException as exc:  # noqa: BLE001 - forwarded or swallowed
            box["error"] = exc
        finally:
            if done.is_set():  # already timed out: late completion
                obs_metrics.inc("watchdog.abandoned")
            done.set()

    t = threading.Thread(target=_body, name="ia-watchdog-body", daemon=True)
    t.start()
    if not done.wait(timeout_s):
        done.set()  # mark abandoned BEFORE the body finishes
        obs_metrics.inc("watchdog.timeouts")
        ialog.emit({
            "event": "watchdog_timeout",
            "timeout_s": timeout_s,
            **(context or {}),
        }, log_path)
        # A wedge is exactly when post-mortem context matters: dump the
        # current scope's flight ring (records the watchdog_timeout
        # record just emitted) before surfacing the transient.
        obs_recorder.dump_current("watchdog_timeout",
                                  extra={"timeout_s": timeout_s,
                                         **(context or {})})
        raise WatchdogTimeout(
            f"dispatch exceeded watchdog timeout {timeout_s:g}s "
            "(op presumed wedged; surfacing as transient)")
    if "error" in box:
        raise box["error"]
    return box["result"]
