"""Failure detection + level-granular recovery (SURVEY.md §5.3).

The reference has nothing here; the natural recovery unit in this framework
is the pyramid LEVEL: all cross-level state is exactly {B' plane, source
map} (Hertzmann §3), already checkpointable (utils/checkpoint.py).  The
driver therefore wraps each level's device work in `run_with_retry`:

- transient device/runtime faults (PJRT resets, preemption-style errors,
  OOM after fragmentation) surface in JAX as `JaxRuntimeError` /
  `XlaRuntimeError`; the wrapper detects them, emits a structured
  `level_retry` record, clears JAX's live-array caches so retries
  re-materialize inputs, and re-runs the level;
- programming errors (TypeError, ValueError, shape mismatches ...) are NOT
  retried — retrying those only hides bugs;
- with `checkpoint_dir` set, completed coarser levels resume from disk, so
  a process-level restart after exhausted retries loses at most one level.

`inject_failures` is the fault-injection hook (SURVEY.md §5.3's test story):
it makes the NEXT `n` wrapped calls raise a synthetic transient error, so
recovery paths are exercised deterministically in CI without real faults.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Optional

from image_analogies_tpu.obs import metrics as obs_metrics
from image_analogies_tpu.utils import logging as ialog

# Synthetic-fault state (fault injection for tests/drills).
_INJECT = {"n": 0}


class InjectedFailure(RuntimeError):
    """Synthetic transient fault raised by `inject_failures`."""


def inject_failures(n: int) -> None:
    """Arm the fault injector: the next `n` `run_with_retry` bodies raise
    `InjectedFailure` before running their real work."""
    _INJECT["n"] = int(n)


# XLA status-code substrings that mark a runtime error as a PROGRAM bug
# surfacing at execution time (bad shapes, donated-buffer misuse, ...):
# retrying those only hides bugs.  Anything else in the runtime-error
# classes (UNAVAILABLE, INTERNAL, RESOURCE_EXHAUSTED, DATA_LOSS, connection
# resets...) is treated as device-side and worth a retry.
_NON_TRANSIENT_CODES = ("INVALID_ARGUMENT", "FAILED_PRECONDITION",
                        "UNIMPLEMENTED")


def _is_transient(exc: BaseException) -> bool:
    """Transient == worth retrying: device/runtime faults, not bugs.

    Walks ``__cause__``/``__context__`` chains: jax re-raises device
    faults wrapped in tracing-layer exceptions (and callers sometimes
    wrap them again), so the transient signal may sit several links deep.
    A non-transient runtime code anywhere in the chain wins — an
    INVALID_ARGUMENT stays a bug no matter what it was wrapped in.
    """
    seen = set()
    while exc is not None and id(exc) not in seen:
        seen.add(id(exc))
        if isinstance(exc, InjectedFailure):
            return True
        # jax.errors.JaxRuntimeError wraps XLA/PJRT runtime failures; keep
        # the check name-based so this works across jax versions without
        # importing private exception types.
        for klass in type(exc).__mro__:
            if klass.__name__ in ("JaxRuntimeError", "XlaRuntimeError"):
                msg = str(exc)
                return not any(code in msg for code in _NON_TRANSIENT_CODES)
        exc = exc.__cause__ or exc.__context__
    return False


def run_with_retry(
    fn: Callable[[], Any],
    *,
    retries: int = 0,
    context: Optional[dict] = None,
    log_path: Optional[str] = None,
    backoff_s: float = 0.5,
) -> Any:
    """Run `fn()`, retrying up to `retries` times on transient faults.

    Each detected fault emits a `level_retry` JSONL record (utils/logging)
    with the error type and attempt number.  Non-transient exceptions and
    faults beyond the retry budget propagate unchanged.
    """
    attempt = 0
    while True:
        try:
            if _INJECT["n"] > 0:
                _INJECT["n"] -= 1
                raise InjectedFailure("synthetic fault (inject_failures)")
            return fn()
        except BaseException as exc:  # noqa: BLE001 - filtered below
            if not _is_transient(exc) or attempt >= retries:
                raise
            attempt += 1
            obs_metrics.inc("level_retry")
            ialog.emit({
                "event": "level_retry",
                "attempt": attempt,
                "error": type(exc).__name__,
                "detail": str(exc)[:200],
                **(context or {}),
            }, log_path)
            try:
                import jax

                jax.clear_caches()  # drop live executables/buffers that may
                # reference poisoned device state before re-running
                from image_analogies_tpu.utils import devcache

                devcache.clear()  # cached input uploads may reference the
                # same poisoned device state; retries must re-upload
            except Exception:  # pragma: no cover - cache clear is best-effort
                pass
            time.sleep(backoff_s * attempt)
