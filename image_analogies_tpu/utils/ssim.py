"""SSIM — the parity metric (BASELINE.json:2 "SSIM parity vs CPU").

Standard Wang et al. 2004 SSIM with an 11-tap Gaussian window (sigma=1.5),
implemented in NumPy so the eval has no device dependency.
"""

from __future__ import annotations

import numpy as np


def _gauss_kernel(size: int = 11, sigma: float = 1.5) -> np.ndarray:
    x = np.arange(size, dtype=np.float64) - (size - 1) / 2.0
    k = np.exp(-(x**2) / (2 * sigma**2))
    return k / k.sum()


def _filter2(img: np.ndarray, k: np.ndarray) -> np.ndarray:
    pad = len(k) // 2
    x = np.pad(img, pad, mode="edge")
    x = np.apply_along_axis(lambda r: np.convolve(r, k, "valid"), 0, x)
    x = np.apply_along_axis(lambda r: np.convolve(r, k, "valid"), 1, x)
    return x


def ssim(a: np.ndarray, b: np.ndarray, data_range: float = 1.0) -> float:
    """Mean SSIM of two images in [0, data_range]; RGB averaged per channel."""
    a = np.asarray(a, np.float64)
    b = np.asarray(b, np.float64)
    if a.shape != b.shape:
        raise ValueError(f"shape mismatch {a.shape} vs {b.shape}")
    if a.ndim == 3:
        return float(np.mean([ssim(a[..., c], b[..., c], data_range)
                              for c in range(a.shape[-1])]))
    k = _gauss_kernel()
    c1 = (0.01 * data_range) ** 2
    c2 = (0.03 * data_range) ** 2
    mu_a, mu_b = _filter2(a, k), _filter2(b, k)
    va = _filter2(a * a, k) - mu_a**2
    vb = _filter2(b * b, k) - mu_b**2
    cab = _filter2(a * b, k) - mu_a * mu_b
    num = (2 * mu_a * mu_b + c1) * (2 * cab + c2)
    den = (mu_a**2 + mu_b**2 + c1) * (va + vb + c2)
    return float(np.mean(num / den))
