"""Host-side utilities: image I/O, checkpointing, logging, SSIM eval."""
