"""Per-level checkpoint / resume (SURVEY.md §5.4).

All cross-level state of the synthesis is exactly {B' level plane, source map
s} (Hertzmann §3), so checkpointing one level is one small ``.npz``.  The
driver saves after each level and, when ``resume_from_level`` is set, reloads
every already-finished (coarser) level instead of recomputing it.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np


def level_path(ckpt_dir: str, level: int) -> str:
    return os.path.join(ckpt_dir, f"level_{level:02d}.npz")


def save_level(ckpt_dir: str, level: int, bp: np.ndarray,
               s: np.ndarray) -> str:
    os.makedirs(ckpt_dir, exist_ok=True)
    path = level_path(ckpt_dir, level)
    tmp = path + ".tmp.npz"
    np.savez(tmp, level=level, bp=bp, s=s)
    os.replace(tmp, path)
    return path


def load_level(ckpt_dir: str, level: int
               ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    path = level_path(ckpt_dir, level)
    if not os.path.exists(path):
        return None
    with np.load(path) as z:
        return z["bp"].astype(np.float32), z["s"].astype(np.int32)
