"""Per-level checkpoint / resume (SURVEY.md §5.4).

All cross-level state of the synthesis is exactly {B' level plane, source map
s} (Hertzmann §3), so checkpointing one level is one small ``.npz``.  The
driver saves after each level and, when ``resume_from_level`` is set, reloads
every already-finished (coarser) level instead of recomputing it.
"""

from __future__ import annotations

import hashlib
import os
import zipfile
from typing import Optional, Tuple

import numpy as np

from image_analogies_tpu import chaos
from image_analogies_tpu.chaos import faults as chaos_faults
from image_analogies_tpu.obs import metrics as obs_metrics
from image_analogies_tpu.obs import trace as obs_trace


def level_path(ckpt_dir: str, level: int) -> str:
    return os.path.join(ckpt_dir, f"level_{level:02d}.npz")


def run_digest(params, a_shape, b_shape) -> str:
    """Fingerprint of (engine params, input shapes): a checkpoint written
    under a different run configuration must not be silently resumed — the
    bp/s planes would be wrong-shaped or semantically stale."""
    payload = repr((sorted(
        (k, v) for k, v in vars(params).items()
        # aux + performance-only knobs are excluded: enabling logging,
        # changing shard counts, or retry budgets produces the same bp/s
        # planes (sharded==serial is test-locked to 1e-5), so those
        # checkpoints stay resumable (round-2 ADVICE item 4).  match_mode
        # and strategy stay IN the digest: two_pass/batched outputs are
        # not parity-equivalent to exact_hi/wavefront.
        if k not in ("checkpoint_dir", "resume_from_level", "profile_dir",
                     "log_path", "db_shards", "data_shards", "level_retries",
                     "save_levels_dir", "level_sync", "metrics",
                     "dispatch_timeout_s",
                     # catalog tiering serves bit-identical features at
                     # every tier, so wiring it on/off never changes the
                     # bp/s planes — those checkpoints stay resumable
                     "catalog_dir", "catalog_host_bytes")),
        tuple(a_shape), tuple(b_shape)))
    return hashlib.sha256(payload.encode()).hexdigest()[:16]


def clip_digest(params, a_shape, b_shape, n_frames: int, phase: str) -> str:
    """Digest for the sharded VIDEO path's stacked per-level checkpoints:
    the single-image `run_digest` extended with the clip length and the
    two_phase phase tag (phase-1 and phase-2 planes are different state
    and must never resume into each other)."""
    base = run_digest(params, a_shape, b_shape)
    return hashlib.sha256(
        f"{base}:clip:{n_frames}:{phase}".encode()).hexdigest()[:16]


def _payload_checksum(bp: np.ndarray, s: np.ndarray,
                      digest: str = "") -> str:
    """sha256 over the two payload planes (shape + dtype + bytes) AND the
    stored run digest: the integrity seal stored INSIDE the npz, checked
    on load.  The run digest answers "is this the same run config?"; the
    checksum answers "did these exact bytes survive the round trip?" —
    partial writes and bit rot fail the second even when the first still
    matches.  The digest rides inside the seal so rot landing on the
    digest field itself reads as damage, not as a stale checkpoint."""
    h = hashlib.sha256()
    for arr in (np.ascontiguousarray(bp), np.ascontiguousarray(s)):
        h.update(repr((arr.shape, str(arr.dtype))).encode())
        h.update(arr.tobytes())
    h.update(digest.encode())
    return h.hexdigest()[:32]


def quarantine(path: str, *, counter: str = "ckpt.quarantined",
               event: str = "ckpt_quarantined") -> str:
    """Move a damaged file aside as ``<path>.corrupt`` (never deleted:
    the bytes are evidence) and record the event.  Returns the
    quarantine path.

    Defaults keep the original checkpoint contract; other planes reuse
    the pattern with their own telemetry names (serve/journal.py passes
    ``serve.journal.quarantined`` / ``journal_quarantined``)."""
    qpath = path + ".corrupt"
    os.replace(path, qpath)
    obs_metrics.inc(counter)
    obs_trace.emit_record({"event": event, "path": path})
    return qpath


def save_level(ckpt_dir: str, level: int, bp: np.ndarray,
               s: np.ndarray, digest: str = "") -> str:
    # raising kinds fire here (before any bytes move); the "corrupt"
    # directive is captured now but applied AFTER the atomic commit —
    # modeling a write that LOOKED successful yet left damaged bytes,
    # the failure mode the load-side checksum exists for.
    directive = chaos.site("ckpt.save", level=level)
    os.makedirs(ckpt_dir, exist_ok=True)
    path = level_path(ckpt_dir, level)
    tmp = path + ".tmp.npz"
    np.savez(tmp, level=level, bp=bp, s=s, digest=digest,
             checksum=_payload_checksum(bp, s, digest))
    os.replace(tmp, path)
    if directive == "corrupt":
        chaos_faults.corrupt_file(path, chaos.plan_seed() or 0)
    return path


def load_level(ckpt_dir: str, level: int, digest: str = ""
               ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Returns (bp, s) or None when missing, stale, or damaged.

    Stale (digest mismatch) is a clean skip: the file is intact, it just
    belongs to a different run config — it stays on disk.  Damaged
    (unreadable container, missing arrays, checksum mismatch) is
    quarantined: renamed to ``.corrupt`` so the next run doesn't trip on
    it again, counted in ``ckpt.quarantined``, and the level recomputes.
    """
    chaos.site("ckpt.load", level=level)
    path = level_path(ckpt_dir, level)
    if not os.path.exists(path):
        return None
    try:
        with np.load(path) as z:
            stored = str(z["digest"]) if "digest" in z.files else ""
            bp = z["bp"].astype(np.float32)
            s = z["s"].astype(np.int32)
            # integrity BEFORE staleness: a failed seal is damage no
            # matter which field the rot landed on (a genuinely stale
            # file still carries a self-consistent seal)
            if "checksum" in z.files:
                want = str(z["checksum"])
                got = _payload_checksum(z["bp"], z["s"], stored)
                if want != got:
                    raise ValueError(
                        f"checkpoint payload checksum mismatch at {path}")
            if digest and stored != digest:
                return None
    except (zipfile.BadZipFile, OSError, ValueError, KeyError, EOFError):
        quarantine(path)
        return None
    return bp, s
