"""Per-level checkpoint / resume (SURVEY.md §5.4).

All cross-level state of the synthesis is exactly {B' level plane, source map
s} (Hertzmann §3), so checkpointing one level is one small ``.npz``.  The
driver saves after each level and, when ``resume_from_level`` is set, reloads
every already-finished (coarser) level instead of recomputing it.
"""

from __future__ import annotations

import hashlib
import os
from typing import Optional, Tuple

import numpy as np


def level_path(ckpt_dir: str, level: int) -> str:
    return os.path.join(ckpt_dir, f"level_{level:02d}.npz")


def run_digest(params, a_shape, b_shape) -> str:
    """Fingerprint of (engine params, input shapes): a checkpoint written
    under a different run configuration must not be silently resumed — the
    bp/s planes would be wrong-shaped or semantically stale."""
    payload = repr((sorted(
        (k, v) for k, v in vars(params).items()
        # aux + performance-only knobs are excluded: enabling logging,
        # changing shard counts, or retry budgets produces the same bp/s
        # planes (sharded==serial is test-locked to 1e-5), so those
        # checkpoints stay resumable (round-2 ADVICE item 4).  match_mode
        # and strategy stay IN the digest: two_pass/batched outputs are
        # not parity-equivalent to exact_hi/wavefront.
        if k not in ("checkpoint_dir", "resume_from_level", "profile_dir",
                     "log_path", "db_shards", "data_shards", "level_retries",
                     "save_levels_dir", "level_sync", "metrics")),
        tuple(a_shape), tuple(b_shape)))
    return hashlib.sha256(payload.encode()).hexdigest()[:16]


def clip_digest(params, a_shape, b_shape, n_frames: int, phase: str) -> str:
    """Digest for the sharded VIDEO path's stacked per-level checkpoints:
    the single-image `run_digest` extended with the clip length and the
    two_phase phase tag (phase-1 and phase-2 planes are different state
    and must never resume into each other)."""
    base = run_digest(params, a_shape, b_shape)
    return hashlib.sha256(
        f"{base}:clip:{n_frames}:{phase}".encode()).hexdigest()[:16]


def save_level(ckpt_dir: str, level: int, bp: np.ndarray,
               s: np.ndarray, digest: str = "") -> str:
    os.makedirs(ckpt_dir, exist_ok=True)
    path = level_path(ckpt_dir, level)
    tmp = path + ".tmp.npz"
    np.savez(tmp, level=level, bp=bp, s=s, digest=digest)
    os.replace(tmp, path)
    return path


def load_level(ckpt_dir: str, level: int, digest: str = ""
               ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Returns (bp, s) or None when missing OR stale: a checkpoint whose
    recorded digest disagrees with the current run's is skipped (the level
    recomputes) instead of resuming with wrong planes."""
    path = level_path(ckpt_dir, level)
    if not os.path.exists(path):
        return None
    with np.load(path) as z:
        stored = str(z["digest"]) if "digest" in z.files else ""
        if digest and stored != digest:
            return None
        return z["bp"].astype(np.float32), z["s"].astype(np.int32)
