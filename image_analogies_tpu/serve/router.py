"""Consistent-hash request router over a fleet of Server workers.

The front half of ROADMAP direction 1: requests hash onto a ring of
virtual nodes keyed by the existing batch key (params digest x shape
bucket x exemplar hash, serve/batcher.py), so same-exemplar traffic
lands on the worker already holding the warm devcache/KD-tree/compiled
programs.  The router never computes — it forwards to
:meth:`serve.fleet.Fleet.forward` and chains the worker future onto its
own, tracking every in-flight request by idempotency key so a dead
worker's futures can be re-answered after the journal handoff
(``Fleet._replace`` -> :meth:`Router.on_worker_replaced`) without the
client ever seeing the death.

Spillover: a gated worker (open breaker / saturated queue, judged by
the fleet health loop) or a hop fault walks the key to its next ring
successor with capped jittered backoff
(:func:`utils.failure.backoff_delay`, jitter seeded from the idem key
so retry timing is deterministic per request).  ``Rejected("poison")``
and ``Rejected("bad_idempotency_key")`` never spill — they are verdicts
about the REQUEST, not the worker, and must stay identical on any
replica.

Ring determinism: positions come from sha256, never ``hash()`` —
``PYTHONHASHSEED`` would scatter affinity across processes (the same
reason chaos/faults.py seeds its streams from sha256).

Host-side only: no jax imports, no jit — the serve grep-lock scans this
file.
"""

from __future__ import annotations

import bisect
import hashlib
import threading
import time
from concurrent.futures import Future, InvalidStateError
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from image_analogies_tpu import chaos
from image_analogies_tpu.obs import ledger as obs_ledger
from image_analogies_tpu.obs import metrics as obs_metrics
from image_analogies_tpu.obs import trace as obs_trace
from image_analogies_tpu.serve import batcher
from image_analogies_tpu.serve import journal as serve_journal
from image_analogies_tpu.serve.types import Rejected, Response
from image_analogies_tpu.utils import failure


def _point(s: str) -> int:
    """Deterministic 64-bit ring position (sha256 prefix, never hash())."""
    return int.from_bytes(hashlib.sha256(s.encode()).digest()[:8], "big")


class Ring:
    """Consistent-hash ring with ``vnodes`` virtual nodes per worker.

    Adding or removing one worker only remaps the keys whose nearest
    vnode belonged to it — every other key keeps its home (the affinity
    property the rebalance test pins)."""

    def __init__(self, vnodes: int = 32):
        self.vnodes = int(vnodes)
        self._points: List[Tuple[int, str]] = []  # sorted (position, wid)
        self._lock = threading.Lock()

    def add(self, wid: str) -> None:
        with self._lock:
            for i in range(self.vnodes):
                bisect.insort(self._points,
                              (_point(f"{wid}#{i}"), wid))

    def remove(self, wid: str) -> None:
        with self._lock:
            self._points = [p for p in self._points if p[1] != wid]

    def members(self) -> List[str]:
        with self._lock:
            return sorted({wid for _, wid in self._points})

    def successors(self, key: str) -> List[str]:
        """Distinct workers in ring order starting at ``key``'s home."""
        with self._lock:
            pts = self._points
            if not pts:
                return []
            start = bisect.bisect_left(pts, (_point(key), ""))
            order: List[str] = []
            seen = set()
            for i in range(len(pts)):
                wid = pts[(start + i) % len(pts)][1]
                if wid not in seen:
                    seen.add(wid)
                    order.append(wid)
            return order


class _Pending:
    """One in-flight routed request: enough to re-submit by idem key."""

    __slots__ = ("idem", "wid", "future", "payload", "deadline_s",
                 "priority")

    def __init__(self, idem: str, wid: str, future: "Future[Response]",
                 payload: Tuple[Any, ...], deadline_s: Optional[float],
                 priority: int = 2):
        self.idem = idem
        self.wid = wid
        self.future = future
        self.payload = payload
        self.deadline_s = deadline_s
        self.priority = priority


def _resolve(fut: "Future[Response]", src: "Future[Response]") -> None:
    """Copy ``src``'s outcome onto ``fut``; first resolution wins.

    Racing resolutions (worker answer vs handoff re-submit) carry
    bit-identical bytes — the engine is deterministic and the journal
    dedupes — so dropping the loser is safe, not a coin flip."""
    if fut.done():
        return
    try:
        exc = src.exception()
        if exc is not None:
            fut.set_exception(exc)
        else:
            fut.set_result(src.result())
    except InvalidStateError:
        pass


class Router:
    """Hashes requests to workers, tracks in-flight futures by idem key,
    and re-answers stranded requests after a journal handoff."""

    def __init__(self, fleet: "Any", *, vnodes: int = 32,
                 spill_retries: int = 3, backoff_s: float = 0.05,
                 backoff_cap_s: float = 1.0, decision_log=None):
        self._fleet = fleet
        self.ring = Ring(vnodes)
        self._spill_retries = int(spill_retries)
        self._backoff_s = float(backoff_s)
        self._backoff_cap_s = float(backoff_cap_s)
        self._pending: Dict[str, _Pending] = {}
        self._lock = threading.Lock()
        # Router verdicts can't land in any worker journal (single-
        # writer, often another process) — they persist in the fleet's
        # DecisionLog (serve/journal.py) when one is configured, so
        # `ia why` can attribute spills and re-chains cross-process.
        self._dlog = decision_log

    def _decide(self, idem: Optional[str], verdict: str, cause: str,
                **extra) -> None:
        if self._dlog is not None:
            self._dlog.record(idem, "router", verdict, cause, **extra)
        else:
            obs_ledger.emit_decision("router", verdict, cause,
                                     idem=idem, **extra)

    # ------------------------------------------------------------------
    # submit path

    def submit(self, a: np.ndarray, ap: np.ndarray, b: np.ndarray,
               params=None, deadline_s: Optional[float] = None,
               idempotency_key: Optional[str] = None,
               priority: int = 2) -> "Future[Response]":
        """Route one request to its ring home (spilling as needed) and
        return a router-owned Future chained to the worker's."""
        if (idempotency_key is not None
                and not serve_journal.valid_idem(idempotency_key)):
            obs_metrics.inc("router.rejected")
            raise Rejected("bad_idempotency_key")
        p = params if params is not None else self._fleet.default_params()
        kstr = batcher.key_str(batcher.batch_key(a, ap, b, p))
        idem = idempotency_key or serve_journal.idem_key(
            kstr, np.asarray(b))
        obs_metrics.inc("router.requests")
        fut: "Future[Response]" = Future()
        payload = (a, ap, b, p)
        # Every routing record (router_route / router_spill) and the
        # downstream worker's spans share one trace id: adopt the
        # caller's (the HTTP hop set it from X-IA-Trace) or mint here.
        with obs_trace.ensure_trace("router_submit", origin_request=idem):
            wid, src = self._route(kstr, idem, payload, deadline_s,
                                   priority=priority)
        ent = _Pending(idem, wid, fut, payload, deadline_s,
                       priority=priority)
        with self._lock:
            self._pending[idem] = ent
        self._chain(src, ent)
        return fut

    def home_for_style(self, exemplar_hash: str) -> Optional[str]:
        """Home worker for a STYLE (exemplar sha1), without a batch key.

        Catalog prefetch and operators ask "which worker owns this
        style" before any traffic exists — there is no params digest or
        target shape yet, so this keys the ring on the exemplar hash
        alone (style-grain placement).  Request routing stays at
        batch-key grain (`submit`), but both walk the SAME ring, so
        membership changes move prefetch placement and traffic
        consistently.  Health gates are ignored on purpose: placement
        answers ownership, not this-instant dispatchability.  None when
        the ring is empty."""
        order = self.ring.successors(exemplar_hash)
        return order[0] if order else None

    def _route(self, kstr: str, idem: str, payload: Tuple[Any, ...],
               deadline_s: Optional[float], priority: int = 2
               ) -> Tuple[str, "Future[Response]"]:
        """Walk ring successors with capped jittered backoff until one
        worker accepts the forward."""
        a, ap, b, p = payload
        jseed = _point(idem) & 0x7FFFFFFF
        last: Optional[BaseException] = None
        for attempt in range(self._spill_retries + 1):
            if attempt:
                time.sleep(failure.backoff_delay(
                    attempt, backoff_s=self._backoff_s,
                    backoff_cap_s=self._backoff_cap_s, jitter_seed=jseed))
            order = self.ring.successors(kstr)
            if not order:
                obs_metrics.inc("router.rejected")
                raise Rejected("fleet_empty")
            ungated = [w for w in order if not self._fleet.gated(w)]
            if not ungated:
                # Everything gated this instant — back off and re-poll;
                # the health loop clears gates as breakers close.
                if last is None:
                    last = Rejected("fleet_saturated")
                continue
            wid = ungated[attempt % len(ungated)]
            if wid != order[0]:
                obs_metrics.inc("router.spills")
                obs_trace.emit_record({"event": "router_spill",
                                       "idem": idem, "home": order[0],
                                       "to": wid, "attempt": attempt})
                self._decide(idem, "spill",
                             "home_gated" if order[0] not in ungated
                             else "hop_fault",
                             home=order[0], to=wid)
            try:
                chaos.site("router.forward", worker=wid, key=kstr)
                src = self._fleet.forward(wid, a, ap, b, p,
                                          deadline_s, idem,
                                          priority=priority)
                obs_metrics.inc("router.routed.{}".format(wid))
                obs_trace.emit_record({"event": "router_route",
                                       "idem": idem, "worker": wid,
                                       "key": kstr, "attempt": attempt})
                return wid, src
            except chaos.ProcessDeath:
                raise  # the ROUTER process dying is never contained
            except Rejected as exc:
                if exc.reason in ("poison", "bad_idempotency_key",
                                  "quota"):
                    # Verdicts about the request, not the worker: every
                    # replica would answer the same — never spill.  A
                    # quota refusal especially: spilling the viral
                    # tenant to ring successors would hand it exactly
                    # the fleet-wide capacity the quota exists to cap.
                    obs_metrics.inc("router.rejected")
                    raise
                last = exc
            except Exception as exc:  # noqa: BLE001 - hop fault, retry
                last = exc
            obs_metrics.inc("router.hop_faults")
        obs_metrics.inc("router.rejected")
        if isinstance(last, Rejected):
            raise last
        raise Rejected("fleet_unavailable")

    def _chain(self, src: "Future[Response]", ent: _Pending) -> None:
        """Resolve the router future from the worker future; unregister
        the pending entry once the answer lands."""

        def _done(f: "Future[Response]") -> None:
            with self._lock:
                if self._pending.get(ent.idem) is ent:
                    del self._pending[ent.idem]
            _resolve(ent.future, f)

        src.add_done_callback(_done)

    # ------------------------------------------------------------------
    # handoff path

    def pending_for(self, wid: str) -> List[_Pending]:
        with self._lock:
            return [e for e in self._pending.values()
                    if e.wid == wid and not e.future.done()]

    def on_worker_replaced(self, wid: str, handle: "Any") -> None:
        """Re-answer requests stranded on a dead worker.

        Entries whose idem key the replacement's ``recover()`` replayed
        chain onto the recovery future directly; everything else is
        re-forwarded by idem key — the journal's done-dedupe makes the
        re-submit exactly-once even when the original answer raced the
        death."""
        for ent in self.pending_for(wid):
            rec = handle.recovery_future(ent.idem)
            if rec is not None:
                obs_metrics.inc("router.rechained")
                obs_trace.emit_record({"event": "router_rechain",
                                       "idem": ent.idem, "worker": wid})
                self._decide(ent.idem, "rechain", "handoff_recovery",
                             worker_id=wid)
                self._chain(rec, ent)
                continue
            obs_metrics.inc("router.resubmitted")
            obs_trace.emit_record({"event": "router_resubmit",
                                   "idem": ent.idem, "worker": wid})
            self._decide(ent.idem, "resubmit", "handoff_not_replayed",
                         worker_id=wid)
            a, ap, b, p = ent.payload
            try:
                src = self._fleet.forward(wid, a, ap, b, p,
                                          ent.deadline_s, ent.idem,
                                          priority=ent.priority)
            except BaseException as exc:  # noqa: BLE001 - surfaced
                if not ent.future.done():
                    try:
                        ent.future.set_exception(exc)
                    except InvalidStateError:
                        pass
                with self._lock:
                    if self._pending.get(ent.idem) is ent:
                        del self._pending[ent.idem]
                continue
            self._chain(src, ent)

    def fail_pending(self, wid: str, exc: BaseException) -> int:
        """Terminal verdict for every request stranded on ``wid`` when
        NO replacement is coming (crash-loop parked slot): hanging the
        futures would strand clients forever.  Returns how many were
        failed."""
        failed = 0
        for ent in self.pending_for(wid):
            try:
                ent.future.set_exception(exc)
                failed += 1
                self._decide(ent.idem, "fail_pending", "crash_loop_gate",
                             worker_id=wid)
            except InvalidStateError:
                pass
            with self._lock:
                if self._pending.get(ent.idem) is ent:
                    del self._pending[ent.idem]
        if failed:
            obs_metrics.inc("router.failed_pending", failed)
        return failed

    def pending_count(self) -> int:
        with self._lock:
            return len(self._pending)
