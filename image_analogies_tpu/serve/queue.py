"""Bounded admission queue + compatibility-keyed batch pop.

One lock + condition guards a deque.  ``submit`` never blocks: at depth
it raises :class:`Rejected` immediately (backpressure is the client's
problem, unbounded memory growth is ours).  ``pop_batch`` is the worker
side: block for a leader, then coalesce same-key followers for at most
the batch window.  Requests with different keys are left in place for
other workers — the scan preserves arrival order per key.

Leader selection is deadline-aware (EDF) when ``deadline_ordering`` is
on: the earliest-deadline waiter leads, so tight deadlines dispatch
ahead of slack FIFO traffic instead of timing out behind it.  Starvation
is bounded, not assumed away: once the OLDEST waiter has queued longer
than ``age_bound_s`` it leads regardless of deadlines, so undeadlined
traffic always makes progress.

With a :class:`~.policy.QosPolicy` that arms ``weighted_fair``, the
leader pick becomes stride-scheduled across TENANTS (tenant = style =
the batch key's exemplar sha1): each tenant holds a running "pass"
value, the waiting tenant with the smallest pass leads, and its pass
advances by ``1 / priority`` of the picked request — so an
``interactive`` request (weight 4) costs its tenant a quarter of a
``background`` step, and a viral style with a thousand waiters still
only gets its fair share of leaders.  The aging bound applies on top
(a waiter older than ``age_bound_s`` leads unconditionally), and
same-key coalescing after the leader is unchanged — followers share
the leader's key, hence its tenant.  Without a policy the pick is
byte-identical to the pre-QoS queue.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Dict, List, Optional

from image_analogies_tpu import chaos
from image_analogies_tpu.obs import metrics as obs_metrics
from image_analogies_tpu.serve.policy import QosPolicy
from image_analogies_tpu.serve.types import Rejected, Request


def _tenant(req: Request) -> str:
    """Tenant identity = the batch key's exemplar sha1 (the same
    derivation the cost ledger uses in serve/worker.py)."""
    return str(req.key[-1]) if req.key else ""


class AdmissionQueue:
    def __init__(self, depth: int, deadline_ordering: bool = False,
                 age_bound_s: float = 5.0,
                 qos: Optional[QosPolicy] = None):
        self._depth = depth
        self._deadline_ordering = deadline_ordering
        self._age_bound_s = age_bound_s
        self._weighted_fair = bool(qos and qos.weighted_fair)
        # Stride-scheduling pass values, kept only for tenants with
        # waiters (bounded by queue depth; pruned on every pick).
        self._passes: Dict[str, float] = {}
        self._items: collections.deque[Request] = collections.deque()
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._closed = False

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)

    def submit(self, req: Request) -> None:
        # admission-layer fault injection (drills): a raising kind here
        # surfaces synchronously to the submitting client, like any other
        # admission refusal — never a half-enqueued request.
        chaos.site("serve.admit", request=req.request_id)
        with self._lock:
            if self._closed:
                obs_metrics.inc("serve.rejected")
                raise Rejected("shutting_down")
            if len(self._items) >= self._depth:
                obs_metrics.inc("serve.rejected")
                raise Rejected("queue_full")
            self._items.append(req)
            obs_metrics.inc("serve.accepted")
            obs_metrics.max_gauge("serve.queue_depth_peak", len(self._items))
            obs_metrics.set_gauge("serve.queue_depth", len(self._items))
            # notify_all: a window-waiting worker may consume a single
            # notify meant for a leader-waiting one and drop the wakeup.
            self._cond.notify_all()

    def _take_leader(self) -> Request:
        """Remove and return the leader (lock held, deque non-empty).

        FIFO by default; with deadline ordering the earliest-deadline
        waiter leads (ties + undeadlined keep arrival order), UNLESS the
        oldest waiter has aged past the bound — then it leads no matter
        what, so EDF reordering can delay it by at most the bound.
        """
        if self._weighted_fair and len(self._items) > 1:
            return self._take_leader_wf()
        if not self._deadline_ordering or len(self._items) == 1:
            return self._items.popleft()
        now = time.monotonic()
        oldest = min(range(len(self._items)),
                     key=lambda i: self._items[i].t_submit)
        if now - self._items[oldest].t_submit > self._age_bound_s:
            obs_metrics.inc("serve.aging_promotions")
            idx = oldest
        else:
            idx = min(range(len(self._items)),
                      key=lambda i: (
                          self._items[i].deadline
                          if self._items[i].deadline is not None
                          else float("inf"),
                          self._items[i].t_submit))
        return self._pop_at(idx)

    def _pop_at(self, idx: int) -> Request:
        """Remove and return item ``idx`` (lock held) via the rotate
        trick — deque has no O(1) mid-removal, but leaders are near the
        front in practice."""
        self._items.rotate(-idx)
        leader = self._items.popleft()
        self._items.rotate(idx)
        return leader

    def _best_of(self, indices: List[int]) -> int:
        """EDF (when armed) else arrival order, within one tenant's
        waiting indices (lock held)."""
        if not self._deadline_ordering:
            return min(indices, key=lambda i: self._items[i].t_submit)
        return min(indices, key=lambda i: (
            self._items[i].deadline
            if self._items[i].deadline is not None else float("inf"),
            self._items[i].t_submit))

    def _take_leader_wf(self) -> Request:
        """Stride-scheduled leader pick across tenants (lock held).

        The aging bound still trumps fairness — a waiter older than
        ``age_bound_s`` leads no matter whose turn it is, so weighted
        fairness can reorder, never starve."""
        now = time.monotonic()
        oldest = min(range(len(self._items)),
                     key=lambda i: self._items[i].t_submit)
        if now - self._items[oldest].t_submit > self._age_bound_s:
            obs_metrics.inc("serve.aging_promotions")
            return self._pop_at(oldest)
        waiting: Dict[str, List[int]] = {}
        for i, req in enumerate(self._items):
            waiting.setdefault(_tenant(req), []).append(i)
        # New tenants join at the current floor: no credit for having
        # been absent, no penalty for being late to the party.
        floor = min((self._passes[t] for t in waiting
                     if t in self._passes), default=0.0)
        for t in waiting:
            self._passes.setdefault(t, floor)
        tenant = min(waiting, key=lambda t: (self._passes[t],
                                             min(waiting[t])))
        idx = self._best_of(waiting[tenant])
        leader = self._items[idx]
        self._passes[tenant] += 1.0 / max(1, int(leader.priority))
        # Prune pass state to tenants that still have waiters, so the
        # dict is bounded by queue depth, not tenant-lifetime history.
        self._passes = {t: v for t, v in self._passes.items()
                        if t in waiting}
        obs_metrics.inc("serve.wf_picks")
        return self._pop_at(idx)

    def pop_batch(self, max_batch: int, window_s: float) -> Optional[List[Request]]:
        """Return a batch of same-key requests, or None when closed+empty.

        The leader (see :meth:`_take_leader`) fixes the key; we then wait
        up to ``window_s`` for same-key followers, waking early whenever
        a new submit lands.  The leader is held outside the deque during the
        window, so a second worker calling pop_batch concurrently picks up
        the next *different*-key request rather than splitting the batch.
        """
        with self._lock:
            while not self._items:
                if self._closed:
                    return None
                self._cond.wait()
            leader = self._take_leader()
            batch = [leader]
            end = time.monotonic() + max(0.0, window_s)
            while len(batch) < max_batch:
                kept: collections.deque[Request] = collections.deque()
                for item in self._items:
                    if item.key == leader.key and len(batch) < max_batch:
                        batch.append(item)
                    else:
                        kept.append(item)
                self._items = kept
                if len(batch) >= max_batch or self._closed:
                    break
                remaining = end - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(remaining)
            now = time.monotonic()
            for req in batch:
                req.t_dequeue = now
                obs_metrics.observe("serve.queue_wait_ms",
                                    (now - req.t_submit) * 1e3)
            obs_metrics.set_gauge("serve.queue_depth", len(self._items))
            return batch

    def requeue(self, req: Request) -> None:
        """Put an already-admitted request back at the FRONT of the queue
        (crash containment).  Bypasses the depth bound on purpose — the
        request holds an admission slot it never released; rejecting it
        here would lose it.  Works even after close() so a crash during
        drain still resolves every future."""
        with self._lock:
            self._items.appendleft(req)
            obs_metrics.inc("serve.requeued")
            obs_metrics.set_gauge("serve.queue_depth", len(self._items))
            self._cond.notify_all()

    def restore(self, reqs: List[Request]) -> None:
        """Re-enqueue journal-replayed requests in their original admit
        order (recovery).  Like :meth:`requeue`, bypasses the depth bound
        and the chaos admission site: these requests were ALREADY
        admitted — by the previous incarnation of this process — and the
        journal is the witness; bouncing them here would lose accepted
        work, the exact failure the journal exists to prevent."""
        with self._lock:
            if not reqs:
                return
            self._items.extend(reqs)
            obs_metrics.max_gauge("serve.queue_depth_peak", len(self._items))
            obs_metrics.set_gauge("serve.queue_depth", len(self._items))
            self._cond.notify_all()

    def close(self) -> None:
        """Stop accepting; wake all workers so they can drain and exit."""
        with self._lock:
            self._closed = True
            self._cond.notify_all()

    def drain_rejected(self) -> List[Request]:
        """Dump any still-queued requests (non-draining shutdown)."""
        with self._lock:
            items = list(self._items)
            self._items.clear()
            self._cond.notify_all()
        return items
