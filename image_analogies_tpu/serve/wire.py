"""Length-prefixed raw-f32 wire format for the serve HTTP transport.

The JSON transport (serve/http.py) ships planes as nested lists of
floats — ~12 bytes of ASCII per f32 plus parse cost on both sides.
This module is the negotiated binary alternative: a fixed little-endian
framing around raw ``float32`` payloads, so a 1024^2 plane is 4 MiB on
the wire and decodes with two ``np.frombuffer`` views instead of a JSON
parse.

Frame layout (all integers little-endian uint32)::

    magic   b"IAF2"       (4 bytes — "Image Analogies F32", version 2
                            framing: v1 was the JSON list transport)
    count   u32           number of arrays
    per array:
      ndim  u32
      dims  u32 * ndim
      data  f32 * prod(dims)   (C-contiguous)

Strictness: decode validates the magic, every length, and that the
buffer is consumed EXACTLY — a truncated or padded body is a protocol
error, not a best-effort parse (the serve journal's spill-file hygiene
taught that lesson).  Caps mirror the JSON path's implicit limits:
``MAX_ARRAYS`` and ``MAX_ELEMS`` bound a hostile frame before any
allocation happens.
"""

from __future__ import annotations

import json
import struct
from typing import List, Sequence

import numpy as np

MAGIC = b"IAF2"
# Content type both sides negotiate on (request Content-Type, response
# Accept).  JSON stays the default; this is opt-in per request.
CONTENT_TYPE = "application/x-ia-f32"

# A frame carries at most this many arrays (requests ship 3 planes,
# responses 1) and this many f32 elements per array (a 16k^2 plane —
# far beyond anything the engine accepts, near enough to bound a
# hostile count before the multiply in the allocator).
MAX_ARRAYS = 16
MAX_ELEMS = 1 << 28

_U32 = struct.Struct("<I")


class WireError(ValueError):
    """Malformed binary frame (maps to HTTP 400 in serve/http.py)."""


def encode_planes(arrays: Sequence[np.ndarray]) -> bytes:
    """Serialize float32 arrays into one IAF2 frame."""
    if len(arrays) > MAX_ARRAYS:
        raise WireError(f"too many arrays ({len(arrays)} > {MAX_ARRAYS})")
    parts = [MAGIC, _U32.pack(len(arrays))]
    for arr in arrays:
        a = np.ascontiguousarray(arr, dtype=np.float32)
        parts.append(_U32.pack(a.ndim))
        for d in a.shape:
            parts.append(_U32.pack(d))
        parts.append(a.tobytes())
    return b"".join(parts)


def decode_planes(data: bytes) -> List[np.ndarray]:
    """Parse one IAF2 frame back into float32 arrays (exact-consume)."""
    buf = memoryview(data)
    if len(buf) < 8 or bytes(buf[:4]) != MAGIC:
        raise WireError("bad magic (not an IAF2 frame)")
    off = 4

    def u32() -> int:
        nonlocal off
        if off + 4 > len(buf):
            raise WireError("truncated frame (header)")
        (v,) = _U32.unpack_from(buf, off)
        off += 4
        return v

    count = u32()
    if count > MAX_ARRAYS:
        raise WireError(f"too many arrays ({count} > {MAX_ARRAYS})")
    out: List[np.ndarray] = []
    for _ in range(count):
        ndim = u32()
        if ndim > 8:
            raise WireError(f"ndim {ndim} exceeds 8")
        dims = [u32() for _ in range(ndim)]
        n = 1
        for d in dims:
            if d > MAX_ELEMS:
                raise WireError(f"dimension {d} exceeds {MAX_ELEMS}")
            n *= d
        if n > MAX_ELEMS:
            raise WireError(f"array of {n} elements exceeds {MAX_ELEMS}")
        nbytes = n * 4
        if off + nbytes > len(buf):
            raise WireError("truncated frame (payload)")
        arr = np.frombuffer(buf, dtype="<f4", count=n,
                            offset=off).reshape(dims)
        off += nbytes
        # np.array (not ascontiguousarray — that aliases the read-only
        # buffer view): handlers treat request planes as ordinary
        # writable host arrays
        out.append(np.array(arr, dtype=np.float32))
    if off != len(buf):
        raise WireError(f"{len(buf) - off} trailing bytes after frame")
    return out


# --- trace-context frame -----------------------------------------------------
#
# Negotiated alongside IAF2 on router->worker hops: a tiny side frame
# carrying the request's trace context (obs/trace.py TRACE_KEYS) so the
# hop that re-encodes planes also re-encodes the context — the codec
# roundtrip is the process-boundary rehearsal.  Same strictness rules
# as the plane frame: exact consume, validated lengths, string-only
# payload, hard cap before any allocation.

CONTEXT_MAGIC = b"IAT1"
MAX_CONTEXT = 4096


def encode_context(ctx: dict) -> bytes:
    """Serialize a str->str trace-context dict into one IAT1 frame."""
    for k, v in ctx.items():
        if not isinstance(k, str) or not isinstance(v, str):
            raise WireError("trace context must be str->str")
    blob = json.dumps(ctx, sort_keys=True).encode()
    if len(blob) > MAX_CONTEXT:
        raise WireError(f"trace context {len(blob)}B exceeds {MAX_CONTEXT}")
    return CONTEXT_MAGIC + _U32.pack(len(blob)) + blob


def decode_context(data: bytes) -> dict:
    """Parse one IAT1 frame back into a str->str dict (exact-consume)."""
    if len(data) < 8 or data[:4] != CONTEXT_MAGIC:
        raise WireError("bad magic (not an IAT1 context frame)")
    (n,) = _U32.unpack_from(data, 4)
    if n > MAX_CONTEXT:
        raise WireError(f"trace context {n}B exceeds {MAX_CONTEXT}")
    if len(data) != 8 + n:
        raise WireError("truncated/padded IAT1 frame")
    try:
        ctx = json.loads(data[8:8 + n].decode())
    except (UnicodeDecodeError, ValueError) as exc:
        raise WireError(f"undecodable trace context: {exc}")
    if not isinstance(ctx, dict) or not all(
            isinstance(k, str) and isinstance(v, str)
            for k, v in ctx.items()):
        raise WireError("trace context must be a str->str object")
    return ctx
