"""Elastic-fleet control plane: spawn / retire / gate verdicts.

Extracted from the fleet health daemon (serve/fleet.py) so POLICY lives
in one place and MECHANISM stays in the fleet: the health loop polls
worker health docs on its jittered cadence and hands them here; this
module renders the verdicts.

Two verdict families:

- :meth:`ControlPlane.gate_verdict` — the per-worker judgement the
  health daemon used to own inline (``Fleet._judge``): None = healthy,
  ``"dead"`` = missed liveness, else an advisory gate reason
  (``breaker_open`` / ``saturated``) that makes the router spill.
- :meth:`ControlPlane.reconcile` — the autoscaling loop (armed only
  when ``FleetConfig.policy`` is set).  It reads ONLY observed signals
  — per-worker queue depths and SLO burn rates from the health docs,
  windowed p95 from the timeline plane — compares them against the
  declarative :class:`~.policy.ControlPolicy`, and acts through the
  fleet's existing primitives: scale-up is ``Fleet._spawn`` + ring join
  + ``catalog warm`` pre-staging (cold builds never land in the request
  path); scale-down gates, ring-leaves, and retires the emptiest worker
  — never one holding inflight work, queued requests, or an unreplayed
  journal.

Every verdict that changes the fleet flows through the decision plane
(sealed DecisionLog line + ``serve.decision.*`` counter + trace
record) under a deterministic idem key ``ctl-<verdict>-<wid>``, so
``ia why ctl-scale_up-w2 --root <journal root>`` attributes each scale
event after the fact.

Host-side only: no jax imports, no jit (serve grep-lock scans this
file).
"""

from __future__ import annotations

import collections
import time
from typing import Any, Dict, List, Optional

from image_analogies_tpu.obs import ledger as obs_ledger
from image_analogies_tpu.obs import metrics as obs_metrics
from image_analogies_tpu.obs import timeline as obs_timeline
from image_analogies_tpu.obs import trace as obs_trace
from image_analogies_tpu.serve.policy import ControlPolicy


class ControlPlane:
    """Owns the fleet's spawn/retire/gate verdicts.

    Constructed by :class:`~.fleet.Fleet` for every fleet (the gate
    verdict is unconditional); the reconcile loop runs only when a
    :class:`ControlPolicy` is attached.  All methods are called from
    the fleet's health-daemon thread; cross-thread readers go through
    :meth:`status`.
    """

    def __init__(self, fleet, policy: Optional[ControlPolicy] = None,
                 clock=time.monotonic):
        self.fleet = fleet
        self.policy = policy
        self._clock = clock
        self._over = 0          # consecutive passes with scale-up pressure
        self._idle = 0          # consecutive passes idle enough to shrink
        self._last_up = -float("inf")
        self._last_down = -float("inf")
        self.events: "collections.deque[Dict[str, Any]]" = \
            collections.deque(maxlen=64)

    # ------------------------------------------------------------------
    # per-worker gate verdicts (extracted Fleet._judge)

    def gate_verdict(self, h: Optional[Dict[str, Any]]) -> Optional[str]:
        """None = healthy; "dead" = missed; else an advisory gate
        reason.  *h* is the worker's health doc, or None when the
        health call itself raised (unresponsive counts as dead)."""
        if h is None:
            return "dead"
        workers = h.get("workers") or {}
        if not h.get("accepting") or workers.get("alive", 0) == 0:
            return "dead"
        if h.get("recovering"):
            # Alive but not READY: journal replay in flight.  Liveness
            # gates the death verdict, and no advisory gate either —
            # spilling keys whose replay is about to answer them would
            # double-compute work the journal already holds.
            return None
        breakers = h.get("breakers") or {}
        if any(state == "open" for state in breakers.values()):
            return "breaker_open"
        cfg = self.fleet.cfg
        depth_gate = cfg.spill_queue_frac * cfg.serve.queue_depth
        if h.get("queue_depth", 0) >= depth_gate:
            return "saturated"
        return None

    # ------------------------------------------------------------------
    # observed signals

    @staticmethod
    def _timeline_p95() -> Optional[float]:
        """Worst per-worker windowed p95 from the timeline plane, or
        None when the plane is disarmed / has no latency points yet."""
        tl = obs_timeline.current()
        if tl is None:
            return None
        worst = None
        try:
            doc = tl.to_json()
        except Exception:  # noqa: BLE001 - timeline is advisory here
            return None
        for key, ent in (doc.get("series") or {}).items():
            if not key.endswith("serve.latency_ms"):
                continue
            points = ent.get("points") or []
            if not points:
                continue
            v = points[-1][1]
            if isinstance(v, dict) and v.get("p95") is not None:
                p95 = float(v["p95"])
                worst = p95 if worst is None else max(worst, p95)
        return worst

    def signals(self, healths: Dict[str, Optional[Dict[str, Any]]]
                ) -> Dict[str, Any]:
        """Digest one polling pass's health docs into the signal vector
        the policy is compared against."""
        ready = [h for h in healths.values()
                 if h and h.get("ok") and not h.get("recovering")]
        depths = [float(h.get("queue_depth") or 0) for h in ready]
        burns = [float((h.get("slo") or {}).get("burn_rate_fast") or 0.0)
                 for h in ready]
        open_breakers = sum(
            1 for h in ready
            if any(s == "open" for s in (h.get("breakers") or {}).values()))
        return {
            "size": len(healths),
            "ready": len(ready),
            "mean_depth": (sum(depths) / len(depths)) if depths else 0.0,
            "max_burn": max(burns) if burns else 0.0,
            "open_breakers": open_breakers,
            "p95_ms": self._timeline_p95(),
        }

    # ------------------------------------------------------------------
    # reconcile

    def _pressure(self, sig: Dict[str, Any]) -> Optional[str]:
        """Scale-up cause, or None when no signal is over target."""
        pol = self.policy
        if sig["mean_depth"] >= pol.queue_high:
            return "queue_pressure"
        if sig["max_burn"] >= pol.max_burn_rate:
            return "burn_rate"
        if pol.target_p95_ms and sig["p95_ms"] is not None \
                and sig["p95_ms"] >= pol.target_p95_ms:
            return "p95_target"
        return None

    def _calm(self, sig: Dict[str, Any]) -> bool:
        pol = self.policy
        return (sig["mean_depth"] <= pol.queue_low
                and sig["max_burn"] < pol.max_burn_rate
                and sig["open_breakers"] == 0)

    def reconcile(self, healths: Dict[str, Optional[Dict[str, Any]]]
                  ) -> Optional[Dict[str, Any]]:
        """One policy pass over one polling pass's health docs.  Returns
        the verdict record when the fleet changed, else None."""
        if self.policy is None:
            return None
        sig = self.signals(healths)
        now = self._clock()
        cause = self._pressure(sig)
        if cause is not None:
            self._over += 1
            self._idle = 0
        elif self._calm(sig):
            self._idle += 1
            self._over = 0
        else:
            self._over = 0
            self._idle = 0
        size = len(self.fleet.workers)
        if (cause is not None and self._over >= self.policy.scale_up_windows
                and size < self.policy.max_workers
                and now - self._last_up >= self.policy.scale_up_cooldown_s):
            self._over = 0
            self._last_up = now
            return self.scale_up(cause, signals=sig)
        if (self._idle >= self.policy.scale_down_windows
                and size > self.policy.min_workers
                and now - self._last_down
                >= self.policy.scale_down_cooldown_s):
            wid = self._pick_retire(healths)
            if wid is None:
                return None  # nobody is safely retireable; stay armed
            self._idle = 0
            self._last_down = now
            return self.scale_down(wid, "idle", signals=sig)
        return None

    # ------------------------------------------------------------------
    # actions (mechanism stays in the fleet; this orders it)

    def _next_wid(self) -> str:
        i = 0
        while "w{}".format(i) in self.fleet.workers:
            i += 1
        return "w{}".format(i)

    def scale_up(self, cause: str,
                 signals: Optional[Dict[str, Any]] = None
                 ) -> Dict[str, Any]:
        """Spawn one worker: ``Fleet._spawn`` + ring join + catalog
        pre-staging of the joining worker's home styles, so its first
        home-style request finds warm tiers instead of a cold build."""
        fleet = self.fleet
        wid = self._next_wid()
        t0 = time.monotonic()
        fleet._spawn(wid, generation=0)
        fleet.router.ring.add(wid)
        # Warm BEFORE recording the verdict: the verdict marks the
        # moment the worker is fully in service, not merely spawned.
        from image_analogies_tpu.catalog import tiers as catalog_tiers

        warmed = None
        if catalog_tiers.active():
            warmed = catalog_tiers.warm_for_fleet(
                fleet.router, only_worker=wid)
        return self._record("scale_up", cause, wid,
                            spawn_ms=round((time.monotonic() - t0) * 1e3, 3),
                            warmed_entries=(warmed or {}).get("entries"),
                            signals=signals)

    def _retireable(self, wid: str, h: Optional[Dict[str, Any]]) -> bool:
        """Safe-to-retire: alive, nothing queued, nothing inflight, no
        unreplayed journal entries, and the router holds no pending
        futures for it.  A worker failing ANY of these keeps its slot —
        retiring it would strand accepted work."""
        if h is None or not h.get("ok") or h.get("recovering"):
            return False
        if h.get("queue_depth", 0) or h.get("inflight", 0):
            return False
        journal = h.get("journal") or {}
        if isinstance(journal, dict) and journal.get("admitted") is not None:
            done = (int(journal.get("done") or 0)
                    + int(journal.get("deduped") or 0)
                    + int(journal.get("rejected") or 0)
                    + int(journal.get("poisoned") or 0))
            if int(journal.get("admitted") or 0) > done:
                return False
        if self.fleet.router.pending_for(wid):
            return False
        return True

    def _pick_retire(self, healths: Dict[str, Optional[Dict[str, Any]]]
                     ) -> Optional[str]:
        """The emptiest retireable worker (ties: highest index, so the
        fleet shrinks from the top and w0's journal root stays put)."""
        candidates = [wid for wid, h in healths.items()
                      if self._retireable(wid, h)]
        if not candidates:
            return None
        return sorted(
            candidates,
            key=lambda w: (
                (healths[w] or {}).get("queue_depth", 0),
                -int(w[1:]) if w[1:].isdigit() else 0))[0]

    def scale_down(self, wid: str, cause: str,
                   signals: Optional[Dict[str, Any]] = None
                   ) -> Optional[Dict[str, Any]]:
        """Drain and retire *wid*: gate (router spills away), ring-leave
        (successors inherit its keys), re-verify emptiness, then shut
        the handle down.  Aborts — fully restoring membership — if
        traffic raced in between the checks."""
        fleet = self.fleet
        handle = fleet.workers.get(wid)
        if handle is None:
            return None
        fleet.gate_worker(wid, "retiring")
        fleet.router.ring.remove(wid)
        try:
            h = handle.health()
        except Exception:  # noqa: BLE001 - dying during retire is fine
            h = None
        raced = (h is None or h.get("queue_depth", 0) or h.get("inflight", 0)
                 or fleet.router.pending_for(wid))
        if raced:
            fleet.router.ring.add(wid)
            fleet.ungate_worker(wid)
            self._record("scale_down_abort", "raced_traffic", wid,
                         signals=signals)
            return None
        # Membership is gone from the ring and the gate blocks spills,
        # so no new work can reach the handle: drop it from the worker
        # map FIRST (a racing forward now raises and the router spills
        # to a live successor), then drain-shutdown the empty server.
        with fleet._lock:
            fleet.workers.pop(wid, None)
            fleet._misses.pop(wid, None)
            fleet._scrapes.pop(wid, None)
            fleet._gates.pop(wid, None)
        handle.shutdown()
        return self._record("scale_down", cause, wid, signals=signals)

    # ------------------------------------------------------------------
    # decision plane

    def _record(self, verdict: str, cause: str, wid: str,
                **extra: Any) -> Dict[str, Any]:
        signals = extra.pop("signals", None) or {}
        size = len(self.fleet.workers)
        obs_metrics.inc("control.{}".format(verdict))
        obs_metrics.set_gauge("control.size", size)
        fields = {"worker_id": wid, "size": size,
                  "mean_depth": round(signals.get("mean_depth", 0.0), 3),
                  "max_burn": round(signals.get("max_burn", 0.0), 4)}
        fields.update({k: v for k, v in extra.items() if v is not None})
        # Deterministic idem key: `ia why ctl-scale_up-w2` reconstructs
        # the event from the sealed decision log after the fact.
        idem = "ctl-{}-{}".format(verdict, wid)
        if self.fleet.decisions is not None:
            self.fleet.decisions.record(idem, "control", verdict, cause,
                                        **fields)
        else:
            obs_ledger.emit_decision("control", verdict, cause, idem=idem,
                                     **fields)
        obs_trace.emit_record({"event": "control_verdict",
                               "verdict": verdict, "cause": cause,
                               "worker": wid, "size": size})
        rec = {"t": round(self._clock(), 3), "verdict": verdict,
               "cause": cause, "worker": wid, "size": size}
        self.events.append(rec)
        return rec

    # ------------------------------------------------------------------
    # status (for /healthz and `ia top`)

    def status(self) -> Dict[str, Any]:
        last: Optional[Dict[str, Any]] = None
        events: List[Dict[str, Any]] = list(self.events)
        if events:
            last = events[-1]
        doc: Dict[str, Any] = {
            "autoscale": self.policy is not None,
            "size": len(self.fleet.workers),
            "last_verdict": last,
            "events": len(events),
        }
        if self.policy is not None:
            doc["policy"] = self.policy.to_json()
        return doc
