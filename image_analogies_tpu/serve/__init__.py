"""Transport-agnostic serving subsystem (ROADMAP north star: amortize
compiles and exemplar work across concurrent callers instead of paying
one-shot CLI cold dispatch per request).

Layering (each module one concern):

- :mod:`serve.types`    — ServeConfig / Request / Response / Rejected.
- :mod:`serve.queue`    — thread-safe admission queue (bounded depth,
  explicit ``Rejected(reason="queue_full")`` backpressure).
- :mod:`serve.batcher`  — the compatibility key micro-batching groups by
  (AnalogyParams digest + tune shape buckets + exemplar content).
- :mod:`serve.degrade`  — deadline cost model: cancel-before-dispatch vs
  degrade (fewer pyramid levels / coarser patch) decisions.
- :mod:`serve.worker`   — worker pool owning device dispatch; wraps every
  engine call in ``utils.failure.run_with_retry``.
- :mod:`serve.server`   — lifecycle (warmup before traffic, drain on
  shutdown) + the in-process :class:`Client` API tests use.
- :mod:`serve.loadgen`  — ``ia serve --selftest N`` synthetic load.
- :mod:`serve.http`     — optional loopback stdlib ``http.server`` front
  end (``ia serve --http PORT``); never required by tests.
- :mod:`serve.router`   — consistent-hash ring (sha256 positions) +
  spillover routing by batch key; re-answers in-flight futures across a
  worker death by idempotency key.
- :mod:`serve.fleet`    — N stable-identity Server workers behind the
  router: health-gate loop, dead-worker detection, and journal-directory
  handoff to the replacement (``ia fleet``).

Everything here is host-side orchestration: no jax imports at module
scope, no direct jit/pjit anywhere (the grep-lock test enforces it) —
device work happens only inside the engine via the obs JitShim and
tune.resolve funnels.
"""

from image_analogies_tpu.serve.server import Client, Server
from image_analogies_tpu.serve.types import (
    DeadlineExceeded,
    FleetConfig,
    Rejected,
    Request,
    Response,
    ServeConfig,
)

__all__ = ["Client", "Server", "ServeConfig", "FleetConfig", "Request",
           "Response", "Rejected", "DeadlineExceeded"]
