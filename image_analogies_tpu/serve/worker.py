"""Worker pool: owns device dispatch for batches popped off the queue.

A compatible TPU batch of >= 2 members dispatches as ONE batched-engine
call (batch/engine.py, ``ServeConfig.batch_engine``): one compiled
program synthesizes every member's B' lane, with per-member fault
isolation and bit-identical outputs.  Everything else — and every
refused batch, reason on ``batch.fallback_sequential.<reason>`` — runs
the sequential per-member loop: one engine backend is constructed per
batch and shared by every member (the batch key guarantees identical
params + exemplar content, so the backend's per-level caches amortize
across the batch).  Degraded members run with their own substituted
params and therefore their own backend; correctness first, sharing
second.

Every engine call goes through ``utils.failure.run_with_retry`` so an
injected (or real) transient device failure retries inside the server
and the client never observes it.

Two containment layers sit around that:

- a shared :class:`serve.breaker.CircuitBreaker` — consecutive dispatch
  failures trip it and further requests fail fast with
  ``Rejected("circuit_open")`` instead of burning workers;
- crash containment in the worker loop — an escape below the
  per-request handler (a genuine worker crash) is caught, the batch's
  unresolved requests are requeued (bounded per request) or failed with
  ``Rejected("worker_crash")``, and the thread SURVIVES.  No request is
  ever lost to a crashed thread, and the pool never shrinks.
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional

from image_analogies_tpu import chaos
from image_analogies_tpu.obs import ledger as obs_ledger
from image_analogies_tpu.obs import metrics as obs_metrics
from image_analogies_tpu.obs import recorder as obs_recorder
from image_analogies_tpu.obs import trace as obs_trace
from image_analogies_tpu.obs.slo import SloTracker
from image_analogies_tpu.serve import batcher
from image_analogies_tpu.serve import degrade as serve_degrade
from image_analogies_tpu.serve.breaker import CircuitBreaker
from image_analogies_tpu.serve.queue import AdmissionQueue
from image_analogies_tpu.serve.types import (
    DeadlineExceeded,
    Rejected,
    Request,
    Response,
    ServeConfig,
)
from image_analogies_tpu.utils import failure


class WorkerPool:
    def __init__(self, cfg: ServeConfig, queue: AdmissionQueue,
                 cost_model: Optional[serve_degrade.CostModel] = None,
                 slo: Optional[SloTracker] = None, journal=None,
                 obs_scope=None):
        self._cfg = cfg
        self._queue = queue
        self._obs_scope = obs_scope  # fleet worker's scope (None standalone)
        self._journal = journal  # write-ahead journal (None = disabled)
        self._cost = cost_model or serve_degrade.CostModel()
        self.breaker = CircuitBreaker(cfg.breaker_threshold,
                                      cfg.breaker_cooldown_s,
                                      backend=cfg.params.backend)
        self.slo = slo
        self._threads: List[threading.Thread] = []
        self._inflight = 0
        self._inflight_lock = threading.Lock()

    def start(self) -> None:
        # Publish the breaker gauge inside the server's run scope (gauges
        # set before the scope opens are dropped with the old registry).
        self.breaker.export_state()
        for i in range(self._cfg.workers):
            t = threading.Thread(target=self._loop, name=f"ia-serve-{i}",
                                 daemon=True)
            t.start()
            self._threads.append(t)

    @property
    def inflight(self) -> int:
        with self._inflight_lock:
            return self._inflight

    def liveness(self) -> dict:
        """Per-thread liveness for /healthz: ``{thread_name: is_alive}``."""
        return {t.name: t.is_alive() for t in self._threads}

    def join(self, timeout: Optional[float] = None) -> None:
        end = None if timeout is None else time.monotonic() + timeout
        for t in self._threads:
            t.join(None if end is None else max(0.0, end - time.monotonic()))

    def _loop(self) -> None:
        # The whole loop runs under the pool's obs scope (no-op when
        # standalone): every dispatch counter, span, and record this
        # thread produces lands in the fleet worker's own registry and
        # flight-recorder ring, chained up to the run's registry.
        with obs_metrics.scope_active(self._obs_scope):
            self._loop_scoped()

    def _loop_scoped(self) -> None:
        while True:
            batch = self._queue.pop_batch(self._cfg.max_batch,
                                          self._cfg.batch_window_ms / 1e3)
            if batch is None:
                return
            try:
                self._run_batch(batch)
            except chaos.ProcessDeath:
                # The chaos plane's process-death fault: deliberately NOT
                # contained — a dead process cannot requeue anything.
                # The thread exits, futures stay unresolved, and the only
                # recovery path is the write-ahead journal on restart
                # (the kill-restart drill's whole premise).
                obs_metrics.inc("serve.process_deaths")
                obs_trace.emit_record({"event": "serve_process_death",
                                       "batch_size": len(batch)})
                # Black box out the door LAST, so the ring contains the
                # death record itself; the sealed dump in the journal
                # dir is what `ia blackbox` renders post-mortem.  The
                # per-request context already unwound with the raise, so
                # the dump's attribution (which requests, which trace)
                # comes from the batch itself.
                obs_recorder.dump_current("process_death", extra={
                    "batch_size": len(batch),
                    "requests": [r.request_id for r in batch],
                    "key": batcher.key_str(batch[0].key),
                    "trace": (batch[0].trace or {}).get("trace")})
                return
            except BaseException as exc:  # noqa: BLE001 - crash containment
                self._contain_crash(batch, exc)

    def _contain_crash(self, batch: List[Request], exc: BaseException) -> None:
        """An escape below the per-request handler killed this batch run.
        Resolve every unresolved member — requeue (bounded) or fail — and
        keep the thread alive."""
        obs_metrics.inc("serve.worker_crashes")
        obs_trace.emit_record({"event": "serve_worker_crash",
                               "error": type(exc).__name__,
                               "detail": str(exc)[:200],
                               "batch_size": len(batch)})
        for req in batch:
            if req.future.done():
                continue
            if req.requeues < self._cfg.crash_requeues:
                req.requeues += 1
                self._decide(req, "requeue", "worker_crash",
                             requeues=req.requeues)
                self._queue.requeue(req)
            else:
                # Requeue budget exhausted: this request deterministically
                # takes workers down.  Persist the poison verdict so any
                # RESUBMISSION of the same idempotency key sheds at
                # admission with Rejected("poison") instead of crashing
                # the fleet again.
                self._decide(req, "poison", "crash_requeues_exhausted")
                if self._journal is not None and req.idem:
                    self._journal.record_poisoned(req.idem)
                obs_metrics.inc("serve.rejected")
                req.future.set_exception(Rejected("worker_crash"))

    def _track_inflight(self, delta: int) -> None:
        with self._inflight_lock:
            self._inflight += delta
            obs_metrics.set_gauge("serve.inflight", self._inflight)

    def _run_batch(self, batch: List[Request]) -> None:
        # batch-level fault injection (drills): raising kinds here model a
        # worker dying below the per-request handler — they escape into
        # _loop's crash containment, which must resolve every member.
        chaos.site("serve.dispatch", batch=len(batch))
        self._track_inflight(len(batch))
        obs_metrics.observe("serve.batch_size", len(batch))
        try:
            with obs_trace.span("serve_batch", size=len(batch),
                                key=batcher.key_str(batch[0].key)):
                if (self._cfg.batch_engine and len(batch) >= 2
                        and batch[0].params.backend == "tpu"
                        and self._dispatch_batch(batch)):
                    return
                backend = None
                for req in batch:
                    backend = self._run_one(req, backend, len(batch))
        finally:
            self._track_inflight(-len(batch))

    def _dispatch_batch(self, batch: List[Request]) -> bool:
        """Dispatch a compatible batch as ONE batched-engine call
        (batch/engine.py): one compiled program synthesizes every
        member's B' lane.  Returns True when every member was resolved
        here; False means "not handled" — the caller runs the
        sequential per-member loop, whose ``set_running`` tolerance
        covers members this path already claimed."""
        from image_analogies_tpu.batch import engine as batch_engine

        # Serve-side preflight the engine can't see: the batch key
        # guarantees identical request params, but degrade plans depend
        # on per-request deadlines and may diverge — a shared launch
        # cannot run members at different fidelity.
        plans = [serve_degrade.plan(req, self._cost,
                                    allow_degrade=self._cfg.degrade)
                 for req in batch]
        if any(action != "run" or degraded is not None
               for action, _, degraded in plans):
            obs_metrics.inc("batch.fallback_sequential.degrade_divergence")
            return False
        if not self.breaker.allow():
            return False  # sequential path fails each member fast
        params = plans[0][1]

        # claim every member; a cancelled member would break lane
        # alignment, so hand the whole batch back to the sequential loop
        for req in batch:
            try:
                if not req.future.set_running_or_notify_cancel():
                    return False
            except RuntimeError:
                if req.future.done():
                    return False

        # WAL transition for every member BEFORE the engine call (same
        # contract as the sequential path; replay treats a repeated
        # `dispatched` append from a later fallback as the same state)
        if self._journal is not None:
            for req in batch:
                if req.idem:
                    self._journal.record_dispatched(req.idem)

        t0 = time.monotonic()
        try:
            results = batch_engine.create_image_analogy_batch(
                batch[0].a, batch[0].ap, [req.b for req in batch], params)
        except batch_engine.BatchIncompatible:
            # reason already counted by the engine's refusal path
            return False
        except Exception:  # noqa: BLE001 - whole-launch failure
            # below per-lane isolation: the sequential path gives each
            # member its own retry envelope and breaker accounting
            obs_metrics.inc("batch.fallback_sequential.launch_error")
            return False
        dispatch_s = time.monotonic() - t0

        # ONE cost observation per launch with the SUMMED work units:
        # the EWMA rate is seconds per unit, so this attributes the
        # marginal per-member cost at dispatch_s / k automatically.
        # Observing the full launch wall-clock once per member would
        # inflate the learned rate k-fold and over-fire the degrade
        # ladder on every deadlined request that follows.
        units = 0.0
        ok_lanes = 0
        for req, res in zip(batch, results):
            if isinstance(res, Exception):
                continue
            units += serve_degrade.work_units(
                int(req.b.shape[0]) * int(req.b.shape[1]),
                params.levels, params.patch_size)
            ok_lanes += 1
        if ok_lanes:
            self._cost.observe(units, dispatch_s)
            self.breaker.record_success()

        for lane, (req, res) in enumerate(zip(batch, results)):
            with obs_trace.request_context(request=req.request_id,
                                           key=batcher.key_str(req.key),
                                           **(req.trace or {})):
                if isinstance(res, Exception):
                    # per-lane fault isolation: only this member
                    # re-runs, sequentially, with its own retry budget
                    obs_trace.emit_record({"event": "serve_batch_lane",
                                           "lane": lane,
                                           "request": req.request_id,
                                           "status": "fault",
                                           "error": type(res).__name__})
                    self._dispatch_one(req, None, len(batch))
                    continue
                now = time.monotonic()
                resp = Response(
                    request_id=req.request_id,
                    bp=res.bp,
                    bp_y=res.bp_y,
                    stats=res.stats,
                    batch_size=len(batch),
                    queue_ms=((req.t_dequeue or t0) - req.t_submit) * 1e3,
                    dispatch_ms=dispatch_s * 1e3,
                    total_ms=(now - req.t_submit) * 1e3,
                    degraded=None,
                )
                obs_metrics.inc("serve.completed")
                self._record_slo(req,
                                 req.deadline is None or now <= req.deadline)
                obs_metrics.observe("serve.latency_ms", resp.total_ms)
                obs_metrics.observe("serve.queue_ms", resp.queue_ms)
                obs_trace.emit_record({"event": "serve_batch_lane",
                                       "lane": lane,
                                       "request": req.request_id,
                                       "status": "ok"})
                self._emit_request_record(req, resp.status,
                                          batch_size=len(batch),
                                          dispatch_ms=resp.dispatch_ms)
                self._emit_cost(req, resp, params)
                if self._journal is not None and req.idem:
                    self._journal.record_done(req.idem, resp)
                req.future.set_result(resp)
        return True

    def _decide(self, req: Request, verdict: str, cause: str,
                **extra) -> None:
        """One control-plane verdict on this request's fate: counter +
        trace record (obs/ledger funnel) and, when journaled, a sealed
        ``decision`` line `ia why` replays."""
        obs_ledger.emit_decision("worker", verdict, cause,
                                 idem=req.idem, request=req.request_id,
                                 **extra)
        if self._journal is not None and req.idem:
            self._journal.record_decision(req.idem, "worker", verdict,
                                          cause, **extra)

    def _emit_cost(self, req: Request, resp: Response, params, *,
                   retries: int = 0) -> None:
        """Assemble this request's cost vector at dispatch completion.
        Fast-exits before building anything when both sinks (ledger
        plane, journal) are off — the disarmed path allocates nothing."""
        if not obs_ledger.armed() and self._journal is None:
            return
        degraded = resp.degraded or {}
        vec = {
            "tenant": str(req.key[-1]) if req.key else None,
            "trace": (req.trace or {}).get("trace"),
            "rid": resp.request_id,
            "status": resp.status,
            "queue_ms": round(resp.queue_ms, 3),
            "dispatch_ms": round(resp.dispatch_ms, 3),
            "total_ms": round(resp.total_ms, 3),
            "lanes": resp.batch_size,
            "degrade_levels": degraded.get("levels"),
            "retries": retries,
            "requeues": req.requeues,
            "priority": req.priority,
            "ann": bool(getattr(params, "ann_prefilter", False)),
            "catalog": bool(getattr(params, "catalog_dir", None)),
            "wire_bytes": req.wire_bytes,
        }
        obs_ledger.record(vec)
        obs_trace.emit_record({"event": "serve_cost", **vec})
        if self._journal is not None and req.idem:
            self._journal.record_cost(req.idem, vec)

    def _emit_request_record(self, req: Request, status: str, *,
                             batch_size: int, dispatch_ms: float = 0.0,
                             degraded=None) -> None:
        now = time.monotonic()
        queue_ms = ((req.t_dequeue or now) - req.t_submit) * 1e3
        obs_trace.emit_record({
            "event": "serve_request",
            "request": req.request_id,
            "status": status,
            "batch_size": batch_size,
            "queue_ms": round(queue_ms, 3),
            "dispatch_ms": round(dispatch_ms, 3),
            "total_ms": round((now - req.t_submit) * 1e3, 3),
            "degraded": degraded,
        })

    def _record_slo(self, req: Request, met: bool) -> None:
        """Feed the SLO tracker: only *deadlined* requests count toward
        the deadline-attainment SLO (undeadlined traffic has no promise
        to break)."""
        if self.slo is not None and req.deadline is not None:
            self.slo.record(met)

    def _run_one(self, req: Request, backend, batch_size: int):
        # Ambient request id + inbound trace context for the whole
        # per-request path: every span and record below — including the
        # engine's own level/fetch spans inside create_image_analogy —
        # inherits them, so `ia trace` renders one connected request-id
        # chain from admit to dispatch, stitched to the submitting hop's
        # trace even though this thread is not the submit thread.
        with obs_trace.request_context(request=req.request_id,
                                       key=batcher.key_str(req.key),
                                       **(req.trace or {})):
            return self._dispatch_one(req, backend, batch_size)

    def _dispatch_one(self, req: Request, backend, batch_size: int):
        """Dispatch one request; returns the (possibly newly built) shared
        backend for subsequent same-batch members."""
        # Lazy import: keep serve/ importable without touching jax until
        # a request actually dispatches.
        from image_analogies_tpu.backends import get_backend
        from image_analogies_tpu.models.analogy import create_image_analogy

        try:
            if not req.future.set_running_or_notify_cancel():
                return backend  # client cancelled while queued
        except RuntimeError:
            # already RUNNING: this request was requeued by crash
            # containment after its first dispatch started — proceed.
            if req.future.done():
                return backend

        action, params, degraded = serve_degrade.plan(
            req, self._cost, allow_degrade=self._cfg.degrade)
        if action == "timeout":
            obs_metrics.inc("serve.timeouts")
            self._record_slo(req, False)
            self._emit_request_record(req, "timeout", batch_size=batch_size)
            self._decide(req, "timeout", "deadline_expired")
            self._journal_rejected(req, "deadline")
            req.future.set_exception(
                DeadlineExceeded(req.request_id, -(req.remaining() or 0.0)))
            return backend

        if degraded is not None:
            # Instant on the serve track: the degrade ladder substituted
            # params for this request — part of its critical path.
            obs_trace.emit_record({"event": "serve_degrade_decision",
                                   "request": req.request_id,
                                   "degraded": degraded})
            self._decide(req, "degrade",
                         "best_effort" if degraded.get("best_effort")
                         else "ewma_over_budget",
                         levels=degraded.get("levels"))

        if not self.breaker.allow():
            # circuit open: fail fast, no dispatch, no retry burn
            obs_metrics.inc("serve.rejected")
            self._record_slo(req, False)
            self._emit_request_record(req, "rejected", batch_size=batch_size)
            self._decide(req, "shed", "breaker_open")
            self._journal_rejected(req, "circuit_open")
            req.future.set_exception(Rejected("circuit_open"))
            return backend

        if degraded is not None:
            # Substituted params -> different compiled programs; do not
            # share the batch backend.
            dispatch_backend = get_backend(params)
        else:
            backend = backend or get_backend(params)
            dispatch_backend = backend

        # WAL transition: dispatched BEFORE the engine call.  If the
        # process dies anywhere past this line without a done append,
        # replay sees `dispatched` and re-enqueues (counting the attempt
        # against the cross-restart poison budget).
        if self._journal is not None and req.idem:
            self._journal.record_dispatched(req.idem)

        t0 = time.monotonic()
        # Per-request attempt count for the cost vector: run_with_retry
        # absorbs transient faults invisibly, so the closure is the only
        # honest witness of how many engine calls this request burned.
        attempts = {"n": 0}

        def _invoke():
            attempts["n"] += 1
            return create_image_analogy(req.a, req.ap, req.b, params,
                                        backend=dispatch_backend)

        try:
            with obs_trace.span("serve_dispatch", request=req.request_id,
                                batch_size=batch_size,
                                degraded=bool(degraded)):
                result = failure.run_with_retry(
                    _invoke,
                    retries=self._cfg.request_retries,
                    context={"scope": "serve", "request": req.request_id},
                    log_path=self._cfg.params.log_path,
                    backoff_s=0.0,
                )
        except Exception as exc:  # noqa: BLE001 - forwarded to the client
            self.breaker.record_failure()
            obs_metrics.inc("serve.errors")
            self._record_slo(req, False)
            self._emit_request_record(req, "error", batch_size=batch_size,
                                      dispatch_ms=(time.monotonic() - t0) * 1e3)
            self._journal_rejected(req, "error")
            req.future.set_exception(exc)
            return backend

        self.breaker.record_success()
        dispatch_s = time.monotonic() - t0
        pixels = int(req.b.shape[0]) * int(req.b.shape[1])
        self._cost.observe(
            serve_degrade.work_units(pixels, params.levels, params.patch_size),
            dispatch_s)

        now = time.monotonic()
        resp = Response(
            request_id=req.request_id,
            bp=result.bp,
            bp_y=result.bp_y,
            stats=result.stats,
            batch_size=batch_size,
            queue_ms=((req.t_dequeue or t0) - req.t_submit) * 1e3,
            dispatch_ms=dispatch_s * 1e3,
            total_ms=(now - req.t_submit) * 1e3,
            degraded=degraded,
        )
        obs_metrics.inc("serve.completed")
        self._record_slo(req, req.deadline is None or now <= req.deadline)
        if degraded is not None:
            obs_metrics.inc("serve.degraded")
        obs_metrics.observe("serve.latency_ms", resp.total_ms)
        obs_metrics.observe("serve.queue_ms", resp.queue_ms)
        self._emit_request_record(req, resp.status, batch_size=batch_size,
                                  dispatch_ms=resp.dispatch_ms,
                                  degraded=degraded)
        self._emit_cost(req, resp, params,
                        retries=max(attempts["n"] - 1, 0))
        # WAL transition: done is appended (response spilled + digest
        # sealed) BEFORE the future resolves.  If the process dies between
        # the two, the client never saw the answer and replay serves the
        # recorded one — the exactly-once edge, not a duplicate.
        if self._journal is not None and req.idem:
            self._journal.record_done(req.idem, resp)
        req.future.set_result(resp)
        return backend

    def _journal_rejected(self, req: Request, reason: str) -> None:
        """Terminal non-success transition: replay must not re-enqueue a
        request whose client already saw a definitive refusal."""
        if self._journal is not None and req.idem:
            self._journal.record_rejected(req.idem, reason)
