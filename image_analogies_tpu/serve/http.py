"""Optional loopback HTTP front end (stdlib ``http.server`` only).

Strictly a thin transport over :class:`serve.server.Server` — no logic
lives here, and nothing in the test suite requires it (the sandbox has
no DNS; binding is loopback-only by construction).

API:
  GET  /healthz      -> Server.health(): ok, accepting, uptime_s,
                        queue_depth, inflight, breakers{backend: state},
                        workers{total, alive, threads}, devcache_bytes,
                        hbm_peak_bytes, slo{target, burn rates, ...}
  GET  /metrics      -> Prometheus text exposition (obs/live.py) of the
                        server's live metrics registry
  POST /v1/analogy   -> body {"a": [[...]], "ap": [[...]], "b": [[...]],
                        "deadline_ms": optional float,
                        "idempotency_key": optional str (journal dedupe;
                        must match [A-Za-z0-9_-]{1,64} — keys name spill
                        files, so anything else answers 400)}
                        reply {"request", "status", "bp", "timings", ...}

Planes are nested JSON lists of floats — fine for a loopback demo
transport, not a production wire format (see ROADMAP follow-ups).
"""

from __future__ import annotations

import json
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict

import numpy as np

from image_analogies_tpu.obs import live as obs_live
from image_analogies_tpu.serve import journal as serve_journal
from image_analogies_tpu.serve.server import Server
from image_analogies_tpu.serve.types import DeadlineExceeded, Rejected


def _make_handler(server: Server):
    class Handler(BaseHTTPRequestHandler):
        # Silence per-request stderr chatter; obs records cover it.
        def log_message(self, fmt, *args):  # noqa: A003
            pass

        def _reply(self, code: int, payload: Dict[str, Any]) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _reply_text(self, code: int, text: str, ctype: str) -> None:
            body = text.encode()
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802 - stdlib API
            if self.path == "/healthz":
                self._reply(200, server.health())
            elif self.path == "/metrics":
                server.refresh_gauges()
                self._reply_text(
                    200,
                    obs_live.render_prometheus(obs_live.snapshot_or_none()),
                    obs_live.CONTENT_TYPE)
            else:
                self._reply(404, {"error": "not_found"})

        def do_POST(self):  # noqa: N802 - stdlib API
            if self.path != "/v1/analogy":
                self._reply(404, {"error": "not_found"})
                return
            try:
                length = int(self.headers.get("Content-Length", "0"))
                req = json.loads(self.rfile.read(length) or b"{}")
                a = np.asarray(req["a"], dtype=np.float32)
                ap = np.asarray(req["ap"], dtype=np.float32)
                b = np.asarray(req["b"], dtype=np.float32)
            except (KeyError, ValueError, json.JSONDecodeError) as exc:
                self._reply(400, {"error": "bad_request", "detail": str(exc)})
                return
            deadline_ms = req.get("deadline_ms")
            idem = req.get("idempotency_key")
            if idem is not None:
                idem = str(idem)
                if not serve_journal.valid_idem(idem):
                    self._reply(400, {
                        "error": "bad_request",
                        "detail": "idempotency_key must match "
                                  "[A-Za-z0-9_-]{1,64}"})
                    return
            try:
                resp = server.submit(
                    a, ap, b,
                    deadline_s=None if deadline_ms is None
                    else float(deadline_ms) / 1e3,
                    idempotency_key=idem).result()
            except Rejected as exc:
                self._reply(429, {"error": "rejected", "reason": exc.reason})
                return
            except DeadlineExceeded:
                self._reply(504, {"error": "deadline_exceeded"})
                return
            except Exception as exc:  # noqa: BLE001 - surfaced to caller
                self._reply(500, {"error": "dispatch_failed",
                                  "detail": str(exc)})
                return
            self._reply(200, {
                "request": resp.request_id,
                "status": resp.status,
                "degraded": resp.degraded,
                "batch_size": resp.batch_size,
                "timings": {"queue_ms": round(resp.queue_ms, 3),
                            "dispatch_ms": round(resp.dispatch_ms, 3),
                            "total_ms": round(resp.total_ms, 3)},
                "bp": resp.bp.tolist(),
            })

    return Handler


def serve_http(server: Server, port: int) -> ThreadingHTTPServer:
    """Bind a loopback-only HTTP server; caller runs serve_forever()."""
    return ThreadingHTTPServer(("127.0.0.1", port), _make_handler(server))
