"""Optional loopback HTTP front end (stdlib ``http.server`` only).

Strictly a thin transport over :class:`serve.server.Server` — no logic
lives here, and nothing in the test suite requires it (the sandbox has
no DNS; binding is loopback-only by construction).

API:
  GET  /healthz      -> Server.health(): ok, accepting, uptime_s,
                        queue_depth, inflight, breakers{backend: state},
                        workers{total, alive, threads}, devcache_bytes,
                        hbm_peak_bytes, slo{target, burn rates, ...}
  GET  /metrics      -> Prometheus text exposition (obs/live.py) of the
                        server's live metrics registry
  GET  /timeline     -> windowed time-series JSON (obs/timeline.py)
                        when the process timeline is armed
                        (?window=10 selects a downsampling tier);
                        both scrape endpoints self-report duration and
                        errors under obs.scrape.*
  GET  /tenants      -> per-tenant heavy-hitter document
                        (obs/ledger.py): top-K styles by request count
                        with cost share, p95, degrade/retry tallies;
                        {"armed": false, "tenants": []} when the
                        metering plane is off
  POST /v1/analogy   -> body {"a": [[...]], "ap": [[...]], "b": [[...]],
                        "deadline_ms": optional float,
                        "idempotency_key": optional str (journal dedupe;
                        must match [A-Za-z0-9_-]{1,64} — keys name spill
                        files, so anything else answers 400)}
                        reply {"request", "status", "bp", "timings", ...}

Content negotiation (serve/wire.py): JSON is the DEFAULT both ways.  A
request with ``Content-Type: application/x-ia-f32`` ships the three
planes as one length-prefixed raw-f32 frame (order a, a', b) with
``deadline_ms`` / ``idempotency_key`` moved to the ``X-IA-Deadline-Ms``
/ ``X-IA-Idempotency-Key`` headers; a request with that type in its
``Accept`` header gets B' back as a single-array frame, the JSON
metadata fields relocated to ``X-IA-Request``/``X-IA-Status``/
``X-IA-Degraded``/``X-IA-Batch-Size``/``X-IA-Timings`` response
headers.  The two directions negotiate independently (binary in / JSON
out and vice versa both work); errors are always JSON.

Trace propagation: every POST reads ``X-IA-Trace``
(``trace_id/parent_span/request_id``, ``-`` for absent fields) and
adopts the caller's trace context — or mints one — before submitting,
so client, router, worker, and engine spans share one trace id; the
header is echoed on every response (success and error alike).
"""

from __future__ import annotations

import json
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional

import numpy as np

from image_analogies_tpu.obs import live as obs_live
from image_analogies_tpu.obs import metrics as obs_metrics
from image_analogies_tpu.obs import timeline as obs_timeline
from image_analogies_tpu.obs import trace as obs_trace
from image_analogies_tpu.serve import journal as serve_journal
from image_analogies_tpu.serve import policy as serve_policy
from image_analogies_tpu.serve import wire
from image_analogies_tpu.serve.server import Server
from image_analogies_tpu.serve.types import DeadlineExceeded, Rejected


def _make_handler(server: Server):
    return _make_handler_from(server.health, server.submit,
                              server.refresh_gauges,
                              tenants_fn=server.tenants_doc)


def _make_handler_from(health_fn, submit_fn, refresh_fn, metrics_fn=None,
                       timeline_fn=None, snapshot_fn=None,
                       tenants_fn=None):
    # metrics_fn(worker: Optional[str]) -> Optional[str]: override for
    # the /metrics exposition (the fleet's federated view, with
    # ?worker=<wid> selecting one worker's isolated registry).  None
    # keeps the default ambient-scope exposition.
    # timeline_fn(window_s: Optional[float]) -> dict: override for the
    # /timeline document; None uses the armed process timeline.
    # snapshot_fn() -> dict: when set, GET /metrics.json answers the raw
    # registry snapshot (subprocess workers export it so the fleet can
    # federate their isolated registries without scope chaining).
    class Handler(BaseHTTPRequestHandler):
        # Silence per-request stderr chatter; obs records cover it.
        def log_message(self, fmt, *args):  # noqa: A003
            pass

        def _reply(self, code: int, payload: Dict[str, Any],
                   headers: Optional[Dict[str, str]] = None) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _reply_text(self, code: int, text: str, ctype: str) -> None:
            body = text.encode()
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802 - stdlib API
            parts = urllib.parse.urlsplit(self.path)
            if parts.path == "/healthz":
                self._reply(200, health_fn())
            elif parts.path == "/metrics":
                self._scrape("metrics", self._get_metrics, parts)
            elif parts.path == "/metrics.json":
                if snapshot_fn is None:
                    self._reply(404, {"error": "not_found"})
                else:
                    self._scrape("metrics", self._get_metrics_json, parts)
            elif parts.path == "/timeline":
                self._scrape("timeline", self._get_timeline, parts)
            elif parts.path == "/tenants":
                self._scrape("tenants", self._get_tenants, parts)
            elif parts.path == "/archive/stats":
                self._scrape("archive", self._get_archive_stats, parts)
            else:
                self._reply(404, {"error": "not_found"})

        def _scrape(self, endpoint: str, fn, parts) -> None:
            """Meta-observability wrapper: every scrape endpoint counts
            itself and times itself (obs.scrape.*), so a slow or failing
            collector is visible in the very plane it collects.  The
            total is bumped BEFORE rendering (this scrape sees itself);
            the duration lands after (the next scrape exports it)."""
            t0 = time.perf_counter()
            obs_metrics.inc(f"obs.scrape.{endpoint}.total")
            try:
                fn(parts)
            except Exception as exc:  # noqa: BLE001 - counted + surfaced
                obs_metrics.inc("obs.scrape.errors")
                obs_metrics.inc(f"obs.scrape.{endpoint}.errors")
                self._reply(500, {"error": "scrape_failed",
                                  "detail": str(exc)})
            finally:
                obs_metrics.observe(f"obs.scrape.{endpoint}.duration_ms",
                                    (time.perf_counter() - t0) * 1e3)

        def _get_metrics(self, parts) -> None:
            refresh_fn()
            if metrics_fn is not None:
                query = urllib.parse.parse_qs(parts.query)
                worker = (query.get("worker") or [None])[0]
                text = metrics_fn(worker)
                if text is None:
                    self._reply(404, {"error": "unknown_worker",
                                      "worker": worker})
                    return
                self._reply_text(200, text, obs_live.CONTENT_TYPE)
                return
            self._reply_text(
                200,
                obs_live.render_prometheus(obs_live.snapshot_or_none()),
                obs_live.CONTENT_TYPE)

        def _get_metrics_json(self, parts) -> None:
            refresh_fn()
            self._reply(200, snapshot_fn())

        def _get_tenants(self, parts) -> None:
            if tenants_fn is not None:
                self._reply(200, tenants_fn())
                return
            from image_analogies_tpu.obs import ledger as obs_ledger
            self._reply(200, obs_ledger.tenants_doc())

        def _get_archive_stats(self, parts) -> None:
            from image_analogies_tpu.obs import archive as obs_archive
            self._reply(200, obs_archive.stats_doc())

        def _get_timeline(self, parts) -> None:
            query = urllib.parse.parse_qs(parts.query)
            window = (query.get("window") or [None])[0]
            try:
                window_s = float(window) if window is not None else None
            except ValueError:
                self._reply(400, {"error": "bad_window", "window": window})
                return
            fn = timeline_fn or obs_timeline.snapshot_json
            try:
                doc = fn(window_s)
            except KeyError as exc:
                self._reply(404, {"error": "unknown_window",
                                  "detail": str(exc)})
                return
            self._reply(200, doc)

        def do_POST(self):  # noqa: N802 - stdlib API
            if self.path != "/v1/analogy":
                self._reply(404, {"error": "not_found"})
                return
            ctype = (self.headers.get("Content-Type") or "").split(";")[0]
            binary_in = ctype.strip().lower() == wire.CONTENT_TYPE
            # A router->worker hop (serve/transport.py SubprocessHandle)
            # flags itself so the reply carries the full Response —
            # both planes plus stats/degraded detail — instead of the
            # client-facing single-plane shape.
            worker_hop = self.headers.get("X-IA-Worker-Hop") == "1"
            try:
                length = int(self.headers.get("Content-Length", "0"))
                body = self.rfile.read(length)
                if binary_in:
                    planes = wire.decode_planes(body)
                    if len(planes) != 3:
                        raise wire.WireError(
                            f"expected 3 planes (a, a', b), got "
                            f"{len(planes)}")
                    a, ap, b = planes
                    deadline_ms = self.headers.get("X-IA-Deadline-Ms")
                    if deadline_ms is not None:
                        deadline_ms = float(deadline_ms)
                    idem = self.headers.get("X-IA-Idempotency-Key")
                    params_doc = self.headers.get("X-IA-Params")
                    params_doc = json.loads(params_doc) \
                        if params_doc else None
                    priority = self.headers.get("X-IA-Priority")
                else:
                    req = json.loads(body or b"{}")
                    a = np.asarray(req["a"], dtype=np.float32)
                    ap = np.asarray(req["ap"], dtype=np.float32)
                    b = np.asarray(req["b"], dtype=np.float32)
                    deadline_ms = req.get("deadline_ms")
                    idem = req.get("idempotency_key")
                    params_doc = req.get("params")
                    priority = req.get("priority")
                # Priority class: an int weight or a class name
                # ("interactive"); absent/garbage degrades to standard
                # rather than erroring — priority is advisory.
                if isinstance(priority, str) and \
                        priority in serve_policy.PRIORITY_CLASSES:
                    priority = serve_policy.PRIORITY_CLASSES[priority]
                try:
                    priority = max(1, int(priority)) \
                        if priority is not None \
                        else serve_policy.PRIORITY_STANDARD
                except (TypeError, ValueError):
                    priority = serve_policy.PRIORITY_STANDARD
                params = None
                if params_doc is not None:
                    from image_analogies_tpu.serve import transport \
                        as serve_transport
                    params = serve_transport.params_from_json(params_doc)
            except (KeyError, TypeError, ValueError,
                    json.JSONDecodeError) as exc:
                self._reply(400, {"error": "bad_request", "detail": str(exc)})
                return
            if idem is not None:
                idem = str(idem)
                if not serve_journal.valid_idem(idem):
                    self._reply(400, {
                        "error": "bad_request",
                        "detail": "idempotency_key must match "
                                  "[A-Za-z0-9_-]{1,64}"})
                    return
            # Cross-process trace adoption: an inbound X-IA-Trace header
            # (trace/parent_span/request; malformed degrades to None,
            # never an error) joins the caller's trace; without one this
            # hop mints the trace id.  Either way every downstream span
            # — router, worker, engine — stitches to it, and the id is
            # echoed back so the client can correlate.
            ctx = obs_trace.parse_trace_header(
                self.headers.get(obs_trace.TRACE_HEADER)) or {}
            if "trace" not in ctx:
                ctx["trace"] = obs_trace.mint_trace_id()
            ctx["parent_span"] = "http"
            trace_hdr = obs_trace.format_trace_header(ctx)
            trace_headers = {obs_trace.TRACE_HEADER: trace_hdr} \
                if trace_hdr else None
            try:
                with obs_trace.request_context(**ctx):
                    resp = submit_fn(
                        a, ap, b, params=params,
                        deadline_s=None if deadline_ms is None
                        else float(deadline_ms) / 1e3,
                        idempotency_key=idem,
                        wire_bytes=len(body),
                        priority=priority).result()
            except Rejected as exc:
                self._reply(429, {"error": "rejected", "reason": exc.reason},
                            headers=trace_headers)
                return
            except DeadlineExceeded:
                self._reply(504, {"error": "deadline_exceeded"},
                            headers=trace_headers)
                return
            except Exception as exc:  # noqa: BLE001 - surfaced to caller
                self._reply(500, {"error": "dispatch_failed",
                                  "detail": str(exc)},
                            headers=trace_headers)
                return
            timings = {"queue_ms": round(resp.queue_ms, 3),
                       "dispatch_ms": round(resp.dispatch_ms, 3),
                       "total_ms": round(resp.total_ms, 3)}
            accept = (self.headers.get("Accept") or "")
            if wire.CONTENT_TYPE in accept.lower():
                out_planes = [np.asarray(resp.bp, np.float32)]
                if worker_hop:
                    out_planes.append(np.asarray(resp.bp_y, np.float32))
                frame = wire.encode_planes(out_planes)
                self.send_response(200)
                self.send_header("Content-Type", wire.CONTENT_TYPE)
                self.send_header("Content-Length", str(len(frame)))
                self.send_header("X-IA-Request", resp.request_id)
                self.send_header("X-IA-Status", resp.status)
                self.send_header("X-IA-Degraded",
                                 "1" if resp.degraded else "0")
                self.send_header("X-IA-Batch-Size", str(resp.batch_size))
                self.send_header("X-IA-Timings", json.dumps(timings))
                if worker_hop:
                    self.send_header(
                        "X-IA-Stats", json.dumps(resp.stats, default=str))
                    self.send_header(
                        "X-IA-Degraded-Detail",
                        json.dumps(resp.degraded, default=str))
                if trace_hdr:
                    self.send_header(obs_trace.TRACE_HEADER, trace_hdr)
                self.end_headers()
                self.wfile.write(frame)
                return
            doc = {
                "request": resp.request_id,
                "status": resp.status,
                "degraded": resp.degraded,
                "batch_size": resp.batch_size,
                "timings": timings,
                "trace": ctx["trace"],
                "bp": resp.bp.tolist(),
            }
            if worker_hop:
                doc["bp_y"] = np.asarray(resp.bp_y,
                                         np.float32).tolist()
                doc["stats"] = json.loads(
                    json.dumps(resp.stats, default=str))
                doc["degraded"] = json.loads(
                    json.dumps(resp.degraded, default=str))
            self._reply(200, doc, headers=trace_headers)

    return Handler


def serve_http(server: Server, port: int) -> ThreadingHTTPServer:
    """Bind a loopback-only HTTP server; caller runs serve_forever()."""
    return ThreadingHTTPServer(("127.0.0.1", port), _make_handler(server))


def serve_fleet_http(fleet, port: int) -> ThreadingHTTPServer:
    """Fleet front end: same transport, but /healthz is the FLEET view
    (per-worker liveness, ring membership, gates, journal ownership,
    per-worker obs scope identity), POST /v1/analogy routes through the
    consistent-hash Router, and GET /metrics is the FEDERATED exposition
    (obs/fleet.py): merged samples plus ``worker="<wid>"`` labeled
    series, with ``?worker=<wid>`` selecting one worker's isolated
    registry (unknown wid -> 404)."""

    def _refresh():
        for handle in list(fleet.workers.values()):
            try:
                handle.refresh_gauges()
            except Exception:  # noqa: BLE001 - a dying worker is fine
                pass

    return ThreadingHTTPServer(
        ("127.0.0.1", port),
        _make_handler_from(fleet.health, fleet.submit, _refresh,
                           metrics_fn=fleet.metrics_text,
                           tenants_fn=fleet.tenants_doc))
