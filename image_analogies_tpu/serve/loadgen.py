"""Synthetic load generator — ``ia serve --selftest N``.

Replays N requests with mixed target shapes (a few exemplar classes, so
both coalescing and singleton fallback paths exercise), optionally with
deadlines, against (1) a sequential one-at-a-time baseline calling the
engine directly and (2) the serving scheduler.  Prints a latency /
throughput / degradation summary and verifies batched responses are
bit-identical to singleton dispatch for the same request — the serving
layer must never change pixels.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from image_analogies_tpu.serve.server import Server
from image_analogies_tpu.serve.types import Rejected, ServeConfig

DEFAULT_SHAPES: Tuple[Tuple[int, int], ...] = ((20, 20), (24, 24), (16, 16))


def percentile(values: Sequence[float], q: float) -> float:
    if not values:
        return 0.0
    xs = sorted(values)
    idx = min(len(xs) - 1, int(round(q / 100.0 * (len(xs) - 1))))
    return xs[idx]


def make_load(n: int, shapes: Sequence[Tuple[int, int]], seed: int, *,
              zipf: Optional[float] = None, styles: int = 0
              ) -> List[Dict[str, Any]]:
    """N requests cycling through shape classes.  Exemplars are shared
    per class (the realistic serving pattern: one style, many targets)
    so same-class requests are batch-compatible; targets differ per
    request.

    With ``zipf=S`` the load is drawn over ``styles`` synthetic styles
    (distinct exemplar pairs == distinct tenants) with Zipf-skewed
    frequency: style of rank r is picked with probability proportional
    to ``r**-S``.  S=0 is uniform; S~1 is the classic heavy-hitter
    shape where one viral style dominates — the load the tenant
    metering plane (obs/ledger.py) exists to attribute.  Deterministic
    for a given (n, shapes, seed, zipf, styles)."""
    rng = np.random.RandomState(seed)
    if zipf is not None:
        n_styles = max(1, int(styles) or 8)
        ranks = np.arange(1, n_styles + 1, dtype=np.float64)
        probs = ranks ** -float(zipf)
        probs /= probs.sum()
        style_shapes = [shapes[s % len(shapes)] for s in range(n_styles)]
        exemplars_z = [(rng.rand(h, w).astype(np.float32),
                        rng.rand(h, w).astype(np.float32))
                       for h, w in style_shapes]
        picks = rng.choice(n_styles, size=n, p=probs)
        load = []
        for i in range(n):
            s = int(picks[i])
            h, w = style_shapes[s]
            a, ap = exemplars_z[s]
            load.append({"index": i, "style": s, "a": a, "ap": ap,
                         "b": rng.rand(h, w).astype(np.float32)})
        return load
    exemplars = {}
    for h, w in shapes:
        exemplars[(h, w)] = (rng.rand(h, w).astype(np.float32),
                             rng.rand(h, w).astype(np.float32))
    load = []
    for i in range(n):
        h, w = shapes[i % len(shapes)]
        a, ap = exemplars[(h, w)]
        load.append({"index": i, "a": a, "ap": ap,
                     "b": rng.rand(h, w).astype(np.float32)})
    return load


def parse_flash_crowd(spec: str) -> Dict[str, float]:
    """Parse ``--flash-crowd T0,DURATION,MULT``: at T0 seconds into the
    run the arrival rate multiplies by MULT for DURATION seconds, then
    falls back to the base rate — the canonical flash-crowd shape the
    autoscaling drill and ``ia bench`` share."""
    parts = [p.strip() for p in str(spec).split(",")]
    if len(parts) != 3:
        raise ValueError("--flash-crowd expects T0,DURATION,MULT "
                         "(e.g. 0.5,2.0,8)")
    t0, duration, mult = (float(p) for p in parts)
    if t0 < 0:
        raise ValueError("flash-crowd T0 must be >= 0")
    if duration <= 0:
        raise ValueError("flash-crowd DURATION must be > 0")
    if mult < 1:
        raise ValueError("flash-crowd MULT must be >= 1")
    return {"t0": t0, "duration": duration, "mult": mult}


def arrival_schedule(n: int, *, t0: float, duration: float, mult: float,
                     base_rps: float = 50.0, seed: int = 0) -> List[float]:
    """Deterministic arrival offsets (seconds from run start) for a
    flash-crowd load: Poisson arrivals at ``base_rps``, multiplied by
    ``mult`` inside the ``[t0, t0+duration)`` surge window.  One seed
    fixes the whole schedule, so the chaos drill and ``ia bench``
    replay the exact same traffic.  Delegates to the soak TraceSpec —
    the single arrival model selftests, drills, and soaks share."""
    from image_analogies_tpu.soak.trace import TraceSpec

    return TraceSpec(seed=int(seed), requests=max(0, int(n)),
                     base_rps=base_rps,
                     flash_crowds=((t0, duration, mult),)).arrivals()


def _pace(sched: Optional[List[float]], idx: int, t_start: float) -> None:
    """Sleep until request ``idx``'s scheduled arrival (no-op without a
    schedule)."""
    if sched is None:
        return
    delay = sched[idx] - (time.perf_counter() - t_start)
    if delay > 0:
        time.sleep(delay)


def style_hist(load: List[Dict[str, Any]]) -> Optional[Dict[str, int]]:
    """Per-style request counts of a zipf load (None for classic loads)."""
    if not load or "style" not in load[0]:
        return None
    hist: Dict[str, int] = {}
    for item in load:
        k = f"s{item['style']}"
        hist[k] = hist.get(k, 0) + 1
    return dict(sorted(hist.items(), key=lambda kv: (-kv[1], kv[0])))


def selftest(cfg: ServeConfig, n: int, *, seed: int = 0,
             deadline_ms: Optional[Any] = None,
             shapes: Sequence[Tuple[int, int]] = DEFAULT_SHAPES,
             zipf: Optional[float] = None, styles: int = 0,
             flash_crowd: Optional[Dict[str, float]] = None
             ) -> Dict[str, Any]:
    """Run the synthetic load end-to-end; returns the summary dict.

    ``deadline_ms`` may be a scalar (every request gets it) or a sequence
    cycled per request — a MIXED-deadline load (e.g. ``(300, None)``)
    interleaves tight-deadline traffic with undeadlined bulk, which is
    what the queue's EDF ordering exists for: the summary's timeout count
    under such a load is the thing deadline ordering lowers."""
    from image_analogies_tpu.models.analogy import create_image_analogy
    from image_analogies_tpu.obs import metrics as obs_metrics
    from image_analogies_tpu.soak.trace import trace_plan

    load, sched, deadline_s = trace_plan(
        n, shapes, seed, zipf=zipf, styles=styles,
        flash_crowd=flash_crowd, deadline_ms=deadline_ms)

    # Sequential baseline: one-at-a-time engine calls, fresh backend each
    # (exactly what N independent `ia run` invocations would pay).
    seq_params = cfg.params.replace(metrics=False, log_path=None)
    baseline = {}
    t0 = time.perf_counter()
    for item in load:
        baseline[item["index"]] = create_image_analogy(
            item["a"], item["ap"], item["b"], seq_params).bp
    seq_s = time.perf_counter() - t0

    # Served run: burst-submit everything, then gather.
    responses: Dict[int, Any] = {}
    errors: Dict[int, BaseException] = {}
    rejected = 0
    journal_stats: Optional[Dict[str, int]] = None
    with Server(cfg) as srv:
        t0 = time.perf_counter()
        futures = {}
        for item in load:
            _pace(sched, item["index"], t0)
            try:
                futures[item["index"]] = srv.submit(
                    item["a"], item["ap"], item["b"],
                    deadline_s=deadline_s(item["index"]))
            except Rejected:
                rejected += 1
        for idx, fut in futures.items():
            try:
                responses[idx] = fut.result(timeout=600)
            except BaseException as exc:  # noqa: BLE001 - summarized
                errors[idx] = exc
        srv_s = time.perf_counter() - t0
        # Batched-engine ledger (read inside the server's run scope):
        # launches vs completions is the compression the lane axis buys —
        # with batching engaged, completed requests strictly exceed
        # engine launches; fallback reasons say why it didn't engage.
        snap = obs_metrics.snapshot() or {}
        counters = snap.get("counters", {})
        batch_ledger = {
            "launches": int(counters.get("batch.launches", 0)),
            "lanes": int(counters.get("batch.lanes", 0)),
            "lane_faults": int(counters.get("batch.lane_faults", 0)),
            "completed": int(counters.get("serve.completed", 0)),
            "fallbacks": {
                k.split("batch.fallback_sequential.", 1)[1]: int(v)
                for k, v in sorted(counters.items())
                if k.startswith("batch.fallback_sequential.")},
        }
        if cfg.journal_dir:
            # journaled smoke: every completed request resubmitted under
            # its derived content key must dedupe, not recompute
            deduped = 0
            for idx in sorted(responses):
                item = load[idx]
                try:
                    again = srv.submit(item["a"], item["ap"],
                                       item["b"]).result(timeout=600)
                    if (again.request_id == responses[idx].request_id
                            and np.array_equal(again.bp,
                                               responses[idx].bp)):
                        deduped += 1
                except BaseException:  # noqa: BLE001 - counted below
                    pass
            journal_stats = dict(srv.health()["journal"] or {})
            journal_stats["resubmit_deduped"] = deduped

    ok = [r for r in responses.values() if r.degraded is None]
    degraded = [r for r in responses.values() if r.degraded is not None]
    # Bit-identity: full-fidelity served outputs must equal the singleton
    # baseline exactly (degraded responses legitimately differ).
    identical = all(
        np.array_equal(responses[idx].bp, baseline[idx])
        for idx in responses if responses[idx].degraded is None)
    latencies = [r.total_ms for r in responses.values()]
    batch_hist: Dict[int, int] = {}
    for r in responses.values():
        batch_hist[r.batch_size] = batch_hist.get(r.batch_size, 0) + 1

    return {
        "n": n,
        "shapes": [list(s) for s in shapes],
        "sequential_s": round(seq_s, 3),
        "served_s": round(srv_s, 3),
        "sequential_rps": round(n / seq_s, 3) if seq_s else 0.0,
        "served_rps": round(len(responses) / srv_s, 3) if srv_s else 0.0,
        "speedup": round(seq_s / srv_s, 3) if srv_s else 0.0,
        "p50_ms": round(percentile(latencies, 50), 2),
        "p95_ms": round(percentile(latencies, 95), 2),
        "completed": len(ok),
        "degraded": len(degraded),
        "timeouts": sum(1 for e in errors.values()
                        if type(e).__name__ == "DeadlineExceeded"),
        "errors": sum(1 for e in errors.values()
                      if type(e).__name__ != "DeadlineExceeded"),
        "rejected": rejected,
        "batch_size_hist": {str(k): v for k, v in sorted(batch_hist.items())},
        "batch_engine": batch_ledger,
        "bit_identical": bool(identical),
        "journal": journal_stats,
        "zipf": zipf,
        "style_hist": style_hist(load),
        "flash_crowd": flash_crowd,
    }


def fleet_selftest(fcfg: "Any", n: int, *, seed: int = 0,
                   deadline_ms: Optional[Any] = None,
                   shapes: Sequence[Tuple[int, int]] = DEFAULT_SHAPES,
                   zipf: Optional[float] = None, styles: int = 0,
                   flash_crowd: Optional[Dict[str, float]] = None
                   ) -> Dict[str, Any]:
    """``ia fleet --selftest N``: the synthetic load routed through the
    consistent-hash Router over a worker fleet, against the same
    sequential baseline.  On top of the single-server gates it verifies
    ring affinity did something (per-worker routed counts), reports the
    negotiated wire codec (the ``--wire`` flag exercises IAF2 vs JSON),
    and counts spills/handoffs — all under the same bit-identity bar."""
    from image_analogies_tpu.models.analogy import create_image_analogy
    from image_analogies_tpu.obs import metrics as obs_metrics
    from image_analogies_tpu.serve.fleet import Fleet
    from image_analogies_tpu.soak.trace import trace_plan

    load, sched, deadline_s = trace_plan(
        n, shapes, seed, zipf=zipf, styles=styles,
        flash_crowd=flash_crowd, deadline_ms=deadline_ms)

    seq_params = fcfg.serve.params.replace(metrics=False, log_path=None)
    baseline = {}
    t0 = time.perf_counter()
    for item in load:
        baseline[item["index"]] = create_image_analogy(
            item["a"], item["ap"], item["b"], seq_params).bp
    seq_s = time.perf_counter() - t0

    responses: Dict[int, Any] = {}
    errors: Dict[int, BaseException] = {}
    rejected = 0
    with Fleet(fcfg) as fl:
        t0 = time.perf_counter()
        futures = {}
        for item in load:
            _pace(sched, item["index"], t0)
            try:
                futures[item["index"]] = fl.submit(
                    item["a"], item["ap"], item["b"],
                    deadline_s=deadline_s(item["index"]))
            except Rejected:
                rejected += 1
        for idx, fut in futures.items():
            try:
                responses[idx] = fut.result(timeout=600)
            except BaseException as exc:  # noqa: BLE001 - summarized
                errors[idx] = exc
        srv_s = time.perf_counter() - t0
        health = fl.health()
        snap = obs_metrics.snapshot() or {}
        counters = snap.get("counters", {})

    ok = [r for r in responses.values() if r.degraded is None]
    degraded = [r for r in responses.values() if r.degraded is not None]
    identical = all(
        np.array_equal(responses[idx].bp, baseline[idx])
        for idx in responses if responses[idx].degraded is None)
    latencies = [r.total_ms for r in responses.values()]
    routed = {k.split("router.routed.", 1)[1]: int(v)
              for k, v in counters.items()
              if k.startswith("router.routed.")}
    codecs = {k.split("router.wire.", 1)[1]: int(v)
              for k, v in counters.items()
              if k.startswith("router.wire.")}

    return {
        "n": n,
        "fleet_size": fcfg.size,
        "wire": fcfg.wire,
        "transport": getattr(fcfg, "transport", "inproc"),
        "shapes": [list(s) for s in shapes],
        "sequential_s": round(seq_s, 3),
        "served_s": round(srv_s, 3),
        "sequential_rps": round(n / seq_s, 3) if seq_s else 0.0,
        "served_rps": round(len(responses) / srv_s, 3) if srv_s else 0.0,
        "speedup": round(seq_s / srv_s, 3) if srv_s else 0.0,
        "p50_ms": round(percentile(latencies, 50), 2),
        "p95_ms": round(percentile(latencies, 95), 2),
        "completed": len(ok),
        "degraded": len(degraded),
        "timeouts": sum(1 for e in errors.values()
                        if type(e).__name__ == "DeadlineExceeded"),
        "errors": sum(1 for e in errors.values()
                      if type(e).__name__ != "DeadlineExceeded"),
        "rejected": rejected,
        "routed": routed,
        "codecs": codecs,
        "wire_bytes": int(counters.get("router.wire_bytes", 0)),
        "spills": int(counters.get("router.spills", 0)),
        "hop_faults": int(counters.get("router.hop_faults", 0)),
        "handoffs": health.get("handoffs", 0),
        "ring": health.get("ring", {}),
        "bit_identical": bool(identical),
        "zipf": zipf,
        "style_hist": style_hist(load),
        "flash_crowd": flash_crowd,
        "control": health.get("control"),
    }


def render_fleet(summary: Dict[str, Any]) -> str:
    lines = [
        f"fleet selftest: {summary['n']} requests over "
        f"{summary['fleet_size']} workers (wire={summary['wire']}, "
        f"transport={summary.get('transport', 'inproc')})",
        f"  sequential: {summary['sequential_s']}s "
        f"({summary['sequential_rps']} req/s)",
        f"  routed:     {summary['served_s']}s "
        f"({summary['served_rps']} req/s, speedup x{summary['speedup']})",
        f"  latency:    p50 {summary['p50_ms']}ms  p95 {summary['p95_ms']}ms",
        f"  outcomes:   {summary['completed']} ok, "
        f"{summary['degraded']} degraded, {summary['timeouts']} timeout, "
        f"{summary['rejected']} rejected, {summary['errors']} error",
        f"  affinity:   routed {summary['routed']} "
        f"(ring members {summary['ring'].get('members', [])})",
        f"  wire:       {summary['codecs']} "
        f"({summary['wire_bytes']} frame bytes)",
        f"  resilience: {summary['spills']} spills, "
        f"{summary['hop_faults']} hop faults, "
        f"{summary['handoffs']} handoffs",
        f"  bit-identical to singleton dispatch: "
        f"{summary['bit_identical']}",
    ]
    if summary.get("style_hist"):
        lines.insert(-1, f"  styles:     zipf S={summary['zipf']} -> "
                     f"{summary['style_hist']}")
    if summary.get("flash_crowd"):
        fc = summary["flash_crowd"]
        lines.insert(-1, f"  flash crowd: x{fc['mult']} surge at "
                     f"t0={fc['t0']}s for {fc['duration']}s")
    ctl = summary.get("control")
    if ctl and ctl.get("autoscale"):
        lines.insert(-1, f"  autoscale:  fleet size {ctl.get('size')}"
                     f" (last verdict: {ctl.get('last_verdict')})")
    return "\n".join(lines)


def render(summary: Dict[str, Any]) -> str:
    lines = [
        f"selftest: {summary['n']} requests over shapes "
        f"{summary['shapes']}",
        f"  sequential: {summary['sequential_s']}s "
        f"({summary['sequential_rps']} req/s)",
        f"  served:     {summary['served_s']}s "
        f"({summary['served_rps']} req/s, speedup x{summary['speedup']})",
        f"  latency:    p50 {summary['p50_ms']}ms  p95 {summary['p95_ms']}ms",
        f"  outcomes:   {summary['completed']} ok, "
        f"{summary['degraded']} degraded, {summary['timeouts']} timeout, "
        f"{summary['rejected']} rejected, {summary['errors']} error",
        f"  batches:    sizes {summary['batch_size_hist']}",
        f"  bit-identical to singleton dispatch: "
        f"{summary['bit_identical']}",
    ]
    be = summary.get("batch_engine")
    if be:
        lines.insert(-1,
                     f"  batch eng:  {be['launches']} launches / "
                     f"{be['lanes']} lanes for {be['completed']} "
                     f"completions, {be['lane_faults']} lane faults"
                     + (f", fallbacks {be['fallbacks']}"
                        if be["fallbacks"] else ""))
    if summary.get("style_hist"):
        lines.insert(-1, f"  styles:     zipf S={summary['zipf']} -> "
                     f"{summary['style_hist']}")
    jn = summary.get("journal")
    if jn:
        lines.append(
            f"  journal:    {jn.get('admitted', 0)} admitted, "
            f"{jn.get('done', 0)} done, "
            f"{jn.get('deduped', 0)} deduped "
            f"({jn.get('resubmit_deduped', 0)} resubmissions answered "
            "from the journal)")
    return "\n".join(lines)
