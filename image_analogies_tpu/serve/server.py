"""Server lifecycle + in-process Client API.

Lifecycle contract:

1. ``start()`` opens one obs run scope for the whole server lifetime
   (worker threads join it reentrantly — every request's spans, records,
   and counters land in one run log), runs ``tune.warmup`` AOT
   precompilation for the configured bucket set, and only then starts
   accepting traffic.
2. ``submit()`` is non-blocking: it returns a Future or raises
   :class:`Rejected` immediately.
3. ``shutdown()`` stops admission (new submits -> Rejected), drains
   in-flight and queued work (unless ``drain=False``, which fails queued
   requests with Rejected("shutting_down")), joins the workers, then
   closes the run scope so ``run_end`` carries the final counters.
"""

from __future__ import annotations

import contextlib
import threading
import time
from concurrent.futures import Future
from typing import Any, Dict, Optional

import numpy as np

from image_analogies_tpu.config import AnalogyParams
from image_analogies_tpu.obs import metrics as obs_metrics
from image_analogies_tpu.obs import trace as obs_trace
from image_analogies_tpu.obs.slo import SloTracker
from image_analogies_tpu.serve import batcher
from image_analogies_tpu.serve import degrade as serve_degrade
from image_analogies_tpu.serve.degrade import CostModel
from image_analogies_tpu.serve.queue import AdmissionQueue
from image_analogies_tpu.serve.types import (
    Rejected,
    Request,
    Response,
    ServeConfig,
)
from image_analogies_tpu.serve.worker import WorkerPool
from image_analogies_tpu.tune import warmup as tune_warmup


class Server:
    def __init__(self, cfg: ServeConfig):
        self.cfg = cfg
        self._queue = AdmissionQueue(
            cfg.queue_depth,
            deadline_ordering=cfg.deadline_ordering,
            age_bound_s=cfg.ordering_age_bound_s)
        # Seed the degrade cost EWMA: store (this device's persisted
        # rate) > packaged class table > optimistic default.
        rate, self.cost_prior_source = serve_degrade.load_prior(cfg.params)
        self.cost_model = CostModel(
            rate, seeded=self.cost_prior_source != "default")
        self.slo = SloTracker(cfg.slo_target,
                              fast_window_s=cfg.slo_fast_window_s,
                              slow_window_s=cfg.slo_slow_window_s)
        self._pool = WorkerPool(cfg, self._queue, self.cost_model,
                                slo=self.slo)
        self._exit = contextlib.ExitStack()
        self._accepting = False
        self._started = False
        self._next_id = 0
        self._id_lock = threading.Lock()
        self._t_start: Optional[float] = None
        self.warmup_report: list = []

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "Server":
        if self._started:
            return self
        self._started = True
        # One run scope for the server's lifetime; metrics forced on so
        # admission/latency counters exist even when params.metrics is
        # unset (log_path still controls whether records hit disk).
        scope_params = self.cfg.params.replace(metrics=True)
        self._exit.enter_context(obs_trace.run_scope(
            scope_params,
            manifest_extra={"serve": {
                "queue_depth": self.cfg.queue_depth,
                "batch_window_ms": self.cfg.batch_window_ms,
                "max_batch": self.cfg.max_batch,
                "workers": self.cfg.workers,
                "warmup_sizes": [list(s) for s in self.cfg.warmup_sizes],
                "deadline_ordering": self.cfg.deadline_ordering,
                "breaker_threshold": self.cfg.breaker_threshold,
                "cost_prior": self.cost_prior_source,
                "slo_target": self.cfg.slo_target,
            }}))
        obs_metrics.inc(f"serve.cost_prior.{self.cost_prior_source}")
        obs_metrics.set_gauge("serve.queue_depth", 0)
        if self.cfg.warmup_sizes:
            with obs_trace.span("serve_warmup",
                                sizes=len(self.cfg.warmup_sizes)):
                self.warmup_report = tune_warmup.warmup_buckets(
                    self.cfg.params, self.cfg.warmup_sizes)
        self._pool.start()
        self._t_start = time.monotonic()
        self._accepting = True
        return self

    def shutdown(self, drain: bool = True) -> None:
        if not self._started:
            return
        self._accepting = False
        if not drain:
            for req in self._queue.drain_rejected():
                req.future.set_exception(Rejected("shutting_down"))
        self._queue.close()
        self._pool.join(self.cfg.drain_timeout_s)
        if self.cfg.cost_persist:
            try:
                serve_degrade.persist_rate(self.cost_model, self.cfg.params)
            except Exception:  # pragma: no cover - persistence best-effort
                pass
        self._started = False
        self._exit.close()

    def __enter__(self) -> "Server":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.shutdown()

    # -- request path ------------------------------------------------------

    def submit(self, a: np.ndarray, ap: np.ndarray, b: np.ndarray,
               params: Optional[AnalogyParams] = None,
               deadline_s: Optional[float] = None) -> "Future[Response]":
        """Enqueue one request; returns a Future resolving to a Response
        (or raising DeadlineExceeded / the dispatch error).  Raises
        :class:`Rejected` when the server is full or shutting down."""
        if not self._accepting:
            raise Rejected("shutting_down")
        if self._pool.breaker.admission_open():
            # Breaker-aware admission: the dispatch breaker is open, so
            # an accepted request would only sit in the queue to be
            # fast-failed at dispatch.  Shed one hop earlier instead —
            # queue_depth stays honest during brownouts.  admission_open
            # is non-claiming, so the half-open probe still flows.
            obs_metrics.inc("serve.rejected")
            obs_metrics.inc("serve.rejected.breaker_open")
            raise Rejected("breaker_open")
        p = params or self.cfg.params
        if deadline_s is None:
            deadline_s = self.cfg.default_deadline_s
        with self._id_lock:
            self._next_id += 1
            rid = self._next_id
        fut: "Future[Response]" = Future()
        req = Request(
            request_id=rid,
            a=np.asarray(a), ap=np.asarray(ap), b=np.asarray(b),
            params=p,
            key=batcher.batch_key(a, ap, b, p),
            future=fut,
        )
        if deadline_s is not None:
            req.deadline = req.t_submit + deadline_s
        self._queue.submit(req)  # Rejected propagates to the caller
        # Admission instant: the first hop of the request's trace chain
        # (ia trace renders admit -> queue wait -> batch -> dispatch).
        obs_trace.emit_record({"event": "serve_admit",
                               "request": rid,
                               "key": batcher.key_str(req.key),
                               "deadline_s": deadline_s,
                               "queue_depth": len(self._queue)})
        return fut

    def request(self, a, ap, b, params=None, deadline_s=None,
                timeout: Optional[float] = None) -> Response:
        """Blocking convenience: submit + wait."""
        return self.submit(a, ap, b, params, deadline_s).result(timeout)

    @property
    def queue_depth(self) -> int:
        return len(self._queue)

    # -- live telemetry ------------------------------------------------------

    def refresh_gauges(self) -> None:
        """Bring point-in-time gauges current before a /metrics scrape
        (event-driven gauges update themselves; these are sampled)."""
        if self._t_start is not None:
            obs_metrics.set_gauge("serve.uptime_s",
                                  round(time.monotonic() - self._t_start, 3))
        obs_metrics.set_gauge("serve.queue_depth", len(self._queue))
        self._pool.breaker.export_state()

    def health(self) -> Dict[str, Any]:
        """JSON-ready /healthz payload: liveness + the state an operator
        (or the future multi-host router) needs to route around trouble."""
        live = self._pool.liveness()
        snap = obs_metrics.snapshot()
        gauges = snap.get("gauges", {})
        breaker = self._pool.breaker
        workers_ok = all(live.values()) if live else True
        return {
            "ok": bool(self._started and self._accepting and workers_ok),
            "accepting": self._accepting,
            "uptime_s": (round(time.monotonic() - self._t_start, 3)
                         if self._t_start is not None else 0.0),
            "queue_depth": len(self._queue),
            "inflight": self._pool.inflight,
            "breakers": {breaker.backend: breaker.state},
            "workers": {
                "total": len(live),
                "alive": sum(1 for ok in live.values() if ok),
                "threads": live,
            },
            "devcache_bytes": gauges.get("devcache.bytes", 0),
            # per-device hbm.peak_bytes.d<N> watermarks -> worst device
            "hbm_peak_bytes": max(
                (v for k, v in gauges.items()
                 if k.startswith("hbm.peak_bytes.")), default=0),
            "slo": self.slo.snapshot(),
        }


class Client:
    """In-process client facade — the API tests (and embedders) use.
    Exists so call sites depend on the request surface, not on server
    lifecycle internals; a future remote client keeps this interface."""

    def __init__(self, server: Server):
        self._server = server

    def submit(self, a, ap, b, params=None, deadline_s=None):
        return self._server.submit(a, ap, b, params, deadline_s)

    def request(self, a, ap, b, params=None, deadline_s=None, timeout=None):
        return self._server.request(a, ap, b, params, deadline_s, timeout)
