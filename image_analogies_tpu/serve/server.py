"""Server lifecycle + in-process Client API.

Lifecycle contract:

1. ``start()`` opens one obs run scope for the whole server lifetime
   (worker threads join it reentrantly — every request's spans, records,
   and counters land in one run log), runs ``tune.warmup`` AOT
   precompilation for the configured bucket set, and only then starts
   accepting traffic.
2. ``submit()`` is non-blocking: it returns a Future or raises
   :class:`Rejected` immediately.
3. ``shutdown()`` stops admission (new submits -> Rejected), drains
   in-flight and queued work (unless ``drain=False``, which fails queued
   requests with Rejected("shutting_down")), joins the workers, then
   closes the run scope so ``run_end`` carries the final counters.

Durability (``ServeConfig.journal_dir``): a write-ahead request journal
(serve/journal.py) records every admit before the queue sees it and
every transition after.  ``start()`` then runs :meth:`Server.recover`
BEFORE accepting traffic: finished entries arm done-dedupe (duplicate
submissions answer instantly with the recorded response — exactly-once
from the client's view), incomplete entries re-enqueue in original admit
order, and entries whose dispatch history already exhausted
``crash_requeues`` are marked poisoned and shed forever with
``Rejected("poison")``.  ``kill()`` is the non-graceful teardown drills
use to model process death.
"""

from __future__ import annotations

import contextlib
import functools
import threading
import time
from concurrent.futures import Future
from typing import Any, Dict, Optional

import numpy as np

from image_analogies_tpu.config import AnalogyParams
from image_analogies_tpu.obs import ceilings as obs_ceilings
from image_analogies_tpu.obs import ledger as obs_ledger
from image_analogies_tpu.obs import metrics as obs_metrics
from image_analogies_tpu.obs import trace as obs_trace
from image_analogies_tpu.obs.slo import SloTracker
from image_analogies_tpu.serve import batcher
from image_analogies_tpu.serve import degrade as serve_degrade
from image_analogies_tpu.serve import journal as serve_journal
from image_analogies_tpu.serve.degrade import CostModel
from image_analogies_tpu.serve.policy import TenantQuota
from image_analogies_tpu.serve.queue import AdmissionQueue
from image_analogies_tpu.serve.types import (
    Rejected,
    Request,
    Response,
    ServeConfig,
)
from image_analogies_tpu.serve.worker import WorkerPool
from image_analogies_tpu.tune import warmup as tune_warmup


def _scoped(fn):
    """Bracket a Server entry point in the server's obs scope, so a
    fleet worker's counters land in its own registry no matter which
    thread (router, HTTP handler, health loop) called in.  Transparent
    when ``obs_scope`` is None (standalone server)."""
    @functools.wraps(fn)
    def wrapper(self, *args, **kwargs):
        with obs_metrics.scope_active(self.obs_scope):
            return fn(self, *args, **kwargs)
    return wrapper


class Server:
    def __init__(self, cfg: ServeConfig,
                 obs_scope: Optional[obs_metrics.ObsScope] = None):
        self.cfg = cfg
        # Fleet workers get their OWN observability scope (isolated
        # registry + flight recorder, writes chained to the fleet's run
        # scope); a standalone server leaves this None and the module
        # helpers resolve to the run scope exactly as before.  Every
        # entry point below brackets itself in scope_active(), which is
        # a transparent no-op for None.
        self.obs_scope = obs_scope
        self._queue = AdmissionQueue(
            cfg.queue_depth,
            deadline_ordering=cfg.deadline_ordering,
            age_bound_s=cfg.ordering_age_bound_s,
            qos=cfg.qos)
        # Per-tenant admission quota: None unless the QoS policy arms a
        # positive rate — the disabled path must stay byte-identical to
        # the pre-QoS server.  Cost shares feed back from the tenant
        # ledger, so a tenant burning an outsized share of dispatch time
        # sees its refill rate squeezed (see policy.TenantQuota).
        self._quota = (TenantQuota(cfg.qos,
                                   shares_fn=obs_ledger.tenants_doc)
                       if cfg.qos is not None and cfg.qos.quota_rps > 0
                       else None)
        # Seed the degrade cost EWMA: store (this device's persisted
        # rate) > packaged class table > optimistic default.
        rate, self.cost_prior_source = serve_degrade.load_prior(cfg.params)
        self.cost_model = CostModel(
            rate, seeded=self.cost_prior_source != "default")
        self.slo = SloTracker(cfg.slo_target,
                              fast_window_s=cfg.slo_fast_window_s,
                              slow_window_s=cfg.slo_slow_window_s)
        if obs_scope is not None:
            obs_scope.slo = self.slo
            if cfg.journal_dir:
                # black-box dumps land next to the worker's journal —
                # the one directory that survives this worker's death
                obs_scope.dump_dir = cfg.journal_dir
        # Write-ahead journal: None unless configured — the disabled
        # request path must never touch the journal module (zero-cost
        # contract, locked by tests).
        self._journal = (serve_journal.RequestJournal(
            cfg.journal_dir, fsync=cfg.journal_fsync)
            if cfg.journal_dir else None)
        # idem -> Future for requests reconstructed by recover(); lets an
        # embedder (or drill) wait for replayed work to finish.
        self.recovery: Dict[str, "Future[Response]"] = {}
        self.recovery_stats: Optional[Dict[str, int]] = None
        self._pool = WorkerPool(cfg, self._queue, self.cost_model,
                                slo=self.slo, journal=self._journal,
                                obs_scope=obs_scope)
        self._exit = contextlib.ExitStack()
        self._accepting = False
        self._started = False
        self._ledger_armed = False
        self._next_id = 0
        self._id_lock = threading.Lock()
        self._t_start: Optional[float] = None
        self.warmup_report: list = []

    # -- lifecycle ---------------------------------------------------------

    @_scoped
    def start(self) -> "Server":
        if self._started:
            return self
        self._started = True
        # One run scope for the server's lifetime; metrics forced on so
        # admission/latency counters exist even when params.metrics is
        # unset (log_path still controls whether records hit disk).
        scope_params = self.cfg.params.replace(metrics=True)
        self._exit.enter_context(obs_trace.run_scope(
            scope_params,
            manifest_extra={"serve": {
                "queue_depth": self.cfg.queue_depth,
                "batch_window_ms": self.cfg.batch_window_ms,
                "max_batch": self.cfg.max_batch,
                "workers": self.cfg.workers,
                "warmup_sizes": [list(s) for s in self.cfg.warmup_sizes],
                "deadline_ordering": self.cfg.deadline_ordering,
                "breaker_threshold": self.cfg.breaker_threshold,
                "cost_prior": self.cost_prior_source,
                "slo_target": self.cfg.slo_target,
                "journal": self.cfg.journal_dir,
                "ledger": self.cfg.ledger,
            }}))
        if self.cfg.ledger:
            # Tenant metering plane: arm (or join) the process ledger
            # for the server's lifetime.  arm() nests, so a fleet of
            # in-process workers shares one plane and the last shutdown
            # disarms it.
            obs_ledger.arm(capacity=self.cfg.ledger_capacity,
                           tenant_k=self.cfg.tenant_k)
            self._ledger_armed = True
        if self.obs_scope is None and self.cfg.journal_dir:
            # standalone journaled server: the run scope's flight
            # recorder dumps into this journal dir on a death path
            scope = obs_metrics.current_scope()
            if scope is not None and scope.dump_dir is None:
                scope.dump_dir = self.cfg.journal_dir
        obs_metrics.inc(f"serve.cost_prior.{self.cost_prior_source}")
        obs_metrics.set_gauge("serve.queue_depth", 0)
        if self.cfg.warmup_sizes:
            with obs_trace.span("serve_warmup",
                                sizes=len(self.cfg.warmup_sizes)):
                self.warmup_report = tune_warmup.warmup_buckets(
                    self.cfg.params, self.cfg.warmup_sizes)
        if self._journal is not None:
            # Replay BEFORE traffic: recovered work re-enqueues first,
            # and done-dedupe / poison state is armed before the first
            # duplicate submission can arrive.
            self._journal.open()
            self.recover()
        self._pool.start()
        self._t_start = time.monotonic()
        self._accepting = True
        return self

    @_scoped
    def shutdown(self, drain: bool = True) -> None:
        if not self._started:
            return
        self._accepting = False
        if not drain:
            for req in self._queue.drain_rejected():
                req.future.set_exception(Rejected("shutting_down"))
        self._queue.close()
        self._pool.join(self.cfg.drain_timeout_s)
        if self.cfg.cost_persist:
            try:
                serve_degrade.persist_rate(self.cost_model, self.cfg.params)
            except Exception:  # pragma: no cover - persistence best-effort
                pass
        if self._journal is not None:
            self._journal.close()
        self._disarm_ledger()
        self._started = False
        self._exit.close()

    def _disarm_ledger(self) -> None:
        if self._ledger_armed:
            self._ledger_armed = False
            obs_ledger.disarm()

    @_scoped
    def kill(self) -> None:
        """Non-graceful teardown — the drill-facing stand-in for process
        death.  Nothing is drained and no future is resolved: queued and
        in-flight clients are left hanging, exactly as a real death
        leaves them.  The write-ahead journal on disk is the only thing
        that survives; a new Server on the same ``journal_dir`` picks the
        work back up via :meth:`recover`."""
        if not self._started:
            return
        self._accepting = False
        self._queue.close()
        self._queue.drain_rejected()  # dropped unresolved, like a death
        self._pool.join(2.0)
        if self._journal is not None:
            self._journal.close()
        self._disarm_ledger()
        self._started = False
        self._exit.close()

    # -- recovery ----------------------------------------------------------

    @_scoped
    def recover(self) -> Dict[str, int]:
        """Replay the journal: arm done-dedupe and the poison set, then
        re-enqueue every incomplete entry in original admit order.
        Replayed requests carry no deadline (the original client's
        absolute deadline died with the old process; the recovered
        response is what a duplicate submission dedupes against) and
        continue their pre-restart dispatch history: an entry whose
        ``dispatched`` count already exceeds ``crash_requeues`` is marked
        poisoned and shed instead of being given another chance to crash
        the fleet."""
        assert self._journal is not None
        rep = self._journal.replay()
        stats = {"entries": len(rep.entries), "replayed": 0, "poisoned": 0,
                 "done": 0, "unrecoverable": 0,
                 "quarantined": rep.quarantined}
        restored = []
        for ent in rep.incomplete:
            if ent.dispatched > self.cfg.crash_requeues:
                obs_ledger.emit_decision("server", "poison",
                                         "replay_dispatch_exhausted",
                                         idem=ent.idem)
                self._journal.record_decision(
                    ent.idem, "server", "poison",
                    "replay_dispatch_exhausted",
                    dispatched=ent.dispatched)
                self._journal.record_poisoned(ent.idem)
                stats["poisoned"] += 1
                obs_trace.emit_record({"event": "serve_replay",
                                       "idem": ent.idem,
                                       "action": "poisoned",
                                       "dispatched": ent.dispatched})
                continue
            payload = self._journal.load_payload(ent.idem)
            if payload is None:  # spill damaged: quarantined, not re-run
                obs_ledger.emit_decision("server", "reject",
                                         "payload_corrupt", idem=ent.idem)
                self._journal.record_rejected(ent.idem, "payload_corrupt")
                stats["unrecoverable"] += 1
                obs_trace.emit_record({"event": "serve_replay",
                                       "idem": ent.idem,
                                       "action": "unrecoverable"})
                continue
            a, ap, b, params = payload
            with self._id_lock:
                self._next_id += 1
                rid = self._next_id
            fut: "Future[Response]" = Future()
            req = Request(
                request_id=rid, a=a, ap=ap, b=b, params=params,
                key=batcher.batch_key(a, ap, b, params), future=fut,
                idem=ent.idem, replayed=True, requeues=ent.dispatched)
            restored.append(req)
            self.recovery[ent.idem] = fut
            stats["replayed"] += 1
            obs_ledger.emit_decision("server", "replay",
                                     "incomplete_after_restart",
                                     idem=ent.idem)
            self._journal.record_decision(ent.idem, "server", "replay",
                                          "incomplete_after_restart",
                                          dispatched=ent.dispatched)
            obs_metrics.inc("serve.journal.replayed")
            obs_trace.emit_record({"event": "serve_replay",
                                   "idem": ent.idem, "request": rid,
                                   "action": "requeued",
                                   "dispatched": ent.dispatched})
        stats["done"] = sum(1 for e in rep.entries.values()
                            if e.done is not None)
        self._queue.restore(restored)
        obs_trace.emit_record({"event": "serve_recovery", **stats})
        self.recovery_stats = stats
        return stats

    def wait_recovered(self, timeout: Optional[float] = None) -> Dict[str, str]:
        """Block until every journal-replayed request resolves; returns
        ``{idem: outcome}`` where outcome is the response status or the
        exception type name."""
        end = None if timeout is None else time.monotonic() + timeout
        out: Dict[str, str] = {}
        for idem, fut in self.recovery.items():
            left = None if end is None else max(0.0,
                                                end - time.monotonic())
            try:
                out[idem] = fut.result(left).status
            except Exception as exc:  # noqa: BLE001 - summarized
                out[idem] = type(exc).__name__
        return out

    def __enter__(self) -> "Server":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.shutdown()

    # -- request path ------------------------------------------------------

    @_scoped
    def submit(self, a: np.ndarray, ap: np.ndarray, b: np.ndarray,
               params: Optional[AnalogyParams] = None,
               deadline_s: Optional[float] = None,
               idempotency_key: Optional[str] = None,
               wire_bytes: int = 0,
               priority: int = 2) -> "Future[Response]":
        """Enqueue one request; returns a Future resolving to a Response
        (or raising DeadlineExceeded / the dispatch error).  Raises
        :class:`Rejected` when the server is full or shutting down.

        With the journal enabled, ``idempotency_key`` (or the derived
        content key) makes submission exactly-once across restarts: a
        key the journal already finished answers instantly with the
        recorded response, and a key marked poisoned sheds with
        ``Rejected("poison")`` before it can touch a worker — checked
        ahead of the breaker, so known-poison retries never trip it."""
        if not self._accepting:
            raise Rejected("shutting_down")
        p = params or self.cfg.params
        key = idem = None
        if self._journal is not None:
            if (idempotency_key is not None
                    and not serve_journal.valid_idem(idempotency_key)):
                # The key names files under the journal dir — anything
                # outside [A-Za-z0-9_-]{1,64} (path separators, dots)
                # is refused before it can touch a path or a journal
                # line.  HTTP pre-checks this and answers 400.
                obs_metrics.inc("serve.rejected")
                raise Rejected("bad_idempotency_key")
            key = batcher.batch_key(a, ap, b, p)
            idem = idempotency_key or serve_journal.idem_key(
                batcher.key_str(key), np.asarray(b))
            if self._journal.is_poisoned(idem):
                obs_metrics.inc("serve.rejected")
                obs_metrics.inc("serve.poisoned")
                obs_ledger.emit_decision("server", "shed", "poison",
                                         idem=idem)
                raise Rejected("poison")
            cached = self._journal.lookup_done(idem)
            if cached is not None:
                obs_metrics.inc("serve.journal.deduped")
                obs_trace.emit_record({"event": "serve_dedupe",
                                       "request": cached.request_id,
                                       "idem": idem})
                # The dedupe verdict is part of this key's causal chain
                # ("done, bit-exact dedupe on retry") — journal it so
                # `ia why` shows the retry was answered, not re-run.
                obs_ledger.emit_decision("server", "dedupe",
                                         "journal_done", idem=idem)
                self._journal.record_decision(idem, "server", "dedupe",
                                              "journal_done")
                fut: "Future[Response]" = Future()
                fut.set_result(cached)
                return fut
            rec = self.recovery.get(idem)
            if rec is not None and not rec.done():
                # Join-replay: this key is ALREADY being recomputed by
                # recover()'s replay — a duplicate submission (e.g. a
                # router re-forward after a cross-process handoff, where
                # no in-process future exists to re-chain) joins the
                # in-flight replayed request instead of re-admitting it,
                # keeping recovery exactly-once-compute across the
                # process boundary.
                obs_metrics.inc("serve.journal.join_replay")
                obs_trace.emit_record({"event": "serve_join_replay",
                                       "idem": idem})
                obs_ledger.emit_decision("server", "join_replay",
                                         "replay_in_flight", idem=idem)
                self._journal.record_decision(idem, "server",
                                              "join_replay",
                                              "replay_in_flight")
                joined: "Future[Response]" = Future()

                def _chain(f: "Future[Response]",
                           out: "Future[Response]" = joined) -> None:
                    if out.done():
                        return
                    exc = f.exception()
                    if exc is not None:
                        out.set_exception(exc)
                    else:
                        out.set_result(f.result())

                rec.add_done_callback(_chain)
                return joined
        if self._pool.breaker.admission_open():
            # Breaker-aware admission: the dispatch breaker is open, so
            # an accepted request would only sit in the queue to be
            # fast-failed at dispatch.  Shed one hop earlier instead —
            # queue_depth stays honest during brownouts.  admission_open
            # is non-claiming, so the half-open probe still flows.
            obs_metrics.inc("serve.rejected")
            obs_metrics.inc("serve.rejected.breaker_open")
            obs_ledger.emit_decision("server", "shed", "breaker_open",
                                     idem=idem)
            raise Rejected("breaker_open")
        if self._quota is not None:
            # Per-tenant admission quota (tenant = the batch key's
            # exemplar sha1): a tenant out of tokens is shed HERE, on
            # its own request, before it can hold a queue slot — the
            # viral style degrades itself, not the fleet.  "quota" is a
            # verdict about the request, so the router never spills it
            # to another worker (that would hand the throttled tenant
            # fleet-wide capacity).
            if key is None:
                key = batcher.batch_key(a, ap, b, p)
            tenant = str(key[-1])
            if not self._quota.try_admit(tenant):
                obs_metrics.inc("serve.rejected")
                obs_metrics.inc("serve.quota_throttled")
                obs_ledger.record_throttle(tenant)
                obs_ledger.emit_decision("server", "shed", "quota",
                                         idem=idem, tenant=tenant[:12])
                if self._journal is not None and idem is not None:
                    self._journal.record_decision(
                        idem, "server", "shed", "quota")
                raise Rejected("quota")
        if deadline_s is None:
            deadline_s = self.cfg.default_deadline_s
        with self._id_lock:
            self._next_id += 1
            rid = self._next_id
        fut = Future()
        req = Request(
            request_id=rid,
            a=np.asarray(a), ap=np.asarray(ap), b=np.asarray(b),
            params=p,
            key=key if key is not None else batcher.batch_key(a, ap, b, p),
            future=fut,
            idem=idem,
            wire_bytes=wire_bytes,
            priority=priority,
            # Submit runs on the caller's thread; the worker thread that
            # dispatches is a different one — the trace context crosses
            # via the request itself.
            trace=obs_trace.capture_trace(),
        )
        if deadline_s is not None:
            req.deadline = req.t_submit + deadline_s
        if self._journal is not None:
            # WAL ordering: the admit record (payload spill + sealed
            # line) lands BEFORE the queue sees the request, so an
            # accepted request with no journal trace cannot exist.
            self._journal.record_admit(
                idem, rid, req.a, req.ap, req.b, p, deadline_s,
                batcher.key_str(req.key))
            try:
                self._queue.submit(req)
            except Rejected as exc:
                self._journal.record_rejected(idem, exc.reason)
                raise
        else:
            self._queue.submit(req)  # Rejected propagates to the caller
        # Admission instant: the first hop of the request's trace chain
        # (ia trace renders admit -> queue wait -> batch -> dispatch).
        obs_trace.emit_record({"event": "serve_admit",
                               "request": rid,
                               "key": batcher.key_str(req.key),
                               "deadline_s": deadline_s,
                               "queue_depth": len(self._queue)})
        return fut

    def request(self, a, ap, b, params=None, deadline_s=None,
                timeout: Optional[float] = None) -> Response:
        """Blocking convenience: submit + wait."""
        return self.submit(a, ap, b, params, deadline_s).result(timeout)

    @property
    def queue_depth(self) -> int:
        return len(self._queue)

    # -- live telemetry ------------------------------------------------------

    @_scoped
    def refresh_gauges(self) -> None:
        """Bring point-in-time gauges current before a /metrics scrape
        (event-driven gauges update themselves; these are sampled)."""
        if self._t_start is not None:
            obs_metrics.set_gauge("serve.uptime_s",
                                  round(time.monotonic() - self._t_start, 3))
        obs_metrics.set_gauge("serve.queue_depth", len(self._queue))
        self._pool.breaker.export_state()

    @_scoped
    def tenants_doc(self) -> Dict[str, Any]:
        """JSON-ready /tenants payload: the metering plane's per-tenant
        heavy-hitter document (obs/ledger.py).  ``armed: false`` with an
        empty list when the ledger is off."""
        return obs_ledger.tenants_doc()

    @_scoped
    def health(self) -> Dict[str, Any]:
        """JSON-ready /healthz payload: liveness + the state an operator
        (or the future multi-host router) needs to route around trouble."""
        live = self._pool.liveness()
        snap = obs_metrics.snapshot()
        gauges = snap.get("gauges", {})
        breaker = self._pool.breaker
        workers_ok = all(live.values()) if live else True
        # Liveness vs readiness split: a worker still working through
        # its journal replay backlog is ALIVE (accepting, threads up)
        # but not READY — the fleet health daemon gates its death
        # verdict on liveness only, so a long recovery never triggers a
        # spurious handoff.
        recovering = any(not f.done() for f in self.recovery.values())
        return {
            "ok": bool(self._started and self._accepting and workers_ok),
            "accepting": self._accepting,
            "ready": bool(self._accepting and not recovering),
            "recovering": recovering,
            "recovery": self.recovery_stats,
            "uptime_s": (round(time.monotonic() - self._t_start, 3)
                         if self._t_start is not None else 0.0),
            "queue_depth": len(self._queue),
            "inflight": self._pool.inflight,
            "breakers": {breaker.backend: breaker.state},
            "workers": {
                "total": len(live),
                "alive": sum(1 for ok in live.values() if ok),
                "threads": live,
            },
            "devcache_bytes": gauges.get("devcache.bytes", 0),
            # per-device hbm.peak_bytes.d<N> watermarks -> worst device
            "hbm_peak_bytes": max(
                (v for k, v in gauges.items()
                 if k.startswith("hbm.peak_bytes.")), default=0),
            "slo": self.slo.snapshot(),
            # durability plane: live serve.journal.* counter tallies
            # plus lock-holder pid / active segment index, so a router
            # can tell which incarnation owns the journal before a
            # handoff (None when the journal is disabled)
            "journal": ({**self._journal.stats(), **self._journal.info()}
                        if self._journal is not None else None),
            # process vitals from /proc (graceful off-Linux): the
            # ceilings watchdog and `ia top` read the same source.
            "vitals": obs_ceilings.read_proc_vitals(),
            # per-tenant admission quota state (None when QoS is off)
            "quota": (self._quota.snapshot()
                      if self._quota is not None else None),
        }


class Client:
    """In-process client facade — the API tests (and embedders) use.
    Exists so call sites depend on the request surface, not on server
    lifecycle internals; a future remote client keeps this interface."""

    def __init__(self, server: Server):
        self._server = server

    def submit(self, a, ap, b, params=None, deadline_s=None,
               idempotency_key=None):
        return self._server.submit(a, ap, b, params, deadline_s,
                                   idempotency_key=idempotency_key)

    def request(self, a, ap, b, params=None, deadline_s=None, timeout=None):
        return self._server.request(a, ap, b, params, deadline_s, timeout)
