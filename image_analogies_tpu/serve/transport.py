"""Worker transport seam: how the fleet reaches a worker.

ROADMAP item 1's last gap.  The fleet/router layer (serve/fleet.py,
serve/router.py) never talks to a :class:`serve.server.Server` directly
any more — it talks to a *handle* obtained from a :class:`Transport`:

- :class:`InProcessTransport` — today's default, bit-for-bit: each
  worker is an in-process Server with its own chained obs scope; the
  router->worker hop round-trips planes and trace context through the
  negotiated codec exactly as before.
- :class:`SubprocessTransport` — each worker is a real child process
  (``python -m image_analogies_tpu.serve.worker_main``) on its own
  loopback HTTP port, speaking the SAME wire: IAF2 plane frames,
  ``X-IA-Trace`` context, ``X-IA-*`` metadata headers.  kill() is a
  real SIGKILL, so the per-worker journal lock holds a real foreign
  pid and the replacement's stale-lock sweep / recovery replay is
  proven against an actual process corpse.

The spawn handshake: config travels as one JSON document on the child's
stdin; the child reports ``{"pid", "port"}`` on a dedicated ready pipe
(``--ready-fd``) only AFTER ``Server.start()`` finished journal
recovery and the HTTP socket is bound — so "spawn returned" means
"worker is answering", with :attr:`FleetConfig.spawn_timeout_s`
bounding the wait (jax import + warmup happen before ready).

:class:`CrashLoopSupervisor` is the respawn governor the health daemon
consults on every death: deaths within ``crash_loop_window_s`` of their
own spawn are RAPID, rapid streaks back off (capped jittered,
:func:`utils.failure.backoff_delay`, jitter seeded from the wid so the
schedule is deterministic per slot), and ``crash_loop_threshold``
consecutive rapid deaths gate the slot instead of respawning forever.

Host-side only: no jax imports, no jit (the serve grep-lock scans this
file).  The ENGINE runs inside each worker, wherever that is.
"""

from __future__ import annotations

import contextlib
import dataclasses
import hashlib
import json as _json
import os
import select
import signal
import subprocess
import sys
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Dict, List, Optional

import numpy as np

from image_analogies_tpu.config import AnalogyParams
from image_analogies_tpu.obs import metrics as obs_metrics
from image_analogies_tpu.obs import trace as obs_trace
from image_analogies_tpu.serve import wire
from image_analogies_tpu.serve.server import Server
from image_analogies_tpu.serve.policy import QosPolicy
from image_analogies_tpu.serve.types import (DeadlineExceeded, Rejected,
                                             Response, ServeConfig)
from image_analogies_tpu.utils import failure


# ---------------------------------------------------------------------------
# wire codec helpers (shared by both transports)


def _roundtrip_iaf2(arrays: List[np.ndarray]) -> List[np.ndarray]:
    return wire.decode_planes(wire.encode_planes(arrays))


def _roundtrip_json(arrays: List[np.ndarray]) -> List[np.ndarray]:
    # Exact for f32: tolist() yields doubles holding each f32 exactly;
    # JSON repr round-trips doubles; nearest-f32 of that double is the
    # original value.  The bit-identity gates re-verify, not assume.
    return [np.asarray(_json.loads(_json.dumps(
        np.asarray(a, np.float32).tolist())), dtype=np.float32)
        for a in arrays]


def _wrap_response(src: "Future[Response]", codec: str
                   ) -> "Future[Response]":
    """Chain a worker future through the response-side wire codec."""
    out: "Future[Response]" = Future()

    def _done(f: "Future[Response]") -> None:
        if out.done():
            return
        exc = f.exception()
        if exc is not None:
            out.set_exception(exc)
            return
        resp = f.result()
        try:
            if codec == "iaf2":
                frame = wire.encode_planes(
                    [np.asarray(resp.bp, np.float32),
                     np.asarray(resp.bp_y, np.float32)])
                obs_metrics.inc("router.wire_bytes", len(frame))
                bp, bp_y = wire.decode_planes(frame)
            else:
                bp, bp_y = _roundtrip_json([resp.bp, resp.bp_y])
            out.set_result(dataclasses.replace(resp, bp=bp, bp_y=bp_y))
        except Exception as wexc:  # noqa: BLE001 - protocol error
            out.set_exception(wexc)

    src.add_done_callback(_done)
    return out


# ---------------------------------------------------------------------------
# ServeConfig / AnalogyParams JSON codec (the spawn-protocol payload —
# same asdict/ctor roundtrip the journal already proves exact)


def params_to_json(params: AnalogyParams) -> Dict[str, Any]:
    return dataclasses.asdict(params)


def params_from_json(doc: Dict[str, Any]) -> AnalogyParams:
    return AnalogyParams(**doc)


def config_to_json(cfg: ServeConfig) -> Dict[str, Any]:
    return dataclasses.asdict(cfg)


def config_from_json(doc: Dict[str, Any]) -> ServeConfig:
    doc = dict(doc)
    params = params_from_json(doc.pop("params"))
    doc["warmup_sizes"] = tuple(
        tuple(int(d) for d in s) for s in doc.get("warmup_sizes") or ())
    if doc.get("qos") is not None:
        doc["qos"] = QosPolicy.from_json(doc["qos"])
    return ServeConfig(params=params, **doc)


# ---------------------------------------------------------------------------
# crash-loop supervision (pure bookkeeping — the health daemon acts)


class CrashLoopSupervisor:
    """Respawn governor: classifies each worker death by uptime and
    answers (rapid streak, respawn delay, gate verdict).

    A death with ``uptime_s < window_s`` extends the slot's RAPID
    streak; a death after a healthy run resets it.  Rapid respawns back
    off with the fleet's capped jittered schedule; ``threshold``
    consecutive rapid deaths (0 disables) return ``gate=True`` — the
    slot is parked instead of burning spawns forever."""

    def __init__(self, window_s: float, threshold: int,
                 backoff_s: float, backoff_cap_s: float):
        self.window_s = float(window_s)
        self.threshold = int(threshold)
        self.backoff_s = float(backoff_s)
        self.backoff_cap_s = float(backoff_cap_s)
        self._rapid: Dict[str, int] = {}
        self._lock = threading.Lock()

    @staticmethod
    def _seed(wid: str) -> int:
        # sha256, never hash(): the jitter schedule must be the same
        # schedule in every process (the Ring makes the same argument).
        return int.from_bytes(
            hashlib.sha256(wid.encode()).digest()[:4], "big") & 0x7FFFFFFF

    def on_death(self, wid: str, uptime_s: float) -> Dict[str, Any]:
        with self._lock:
            rapid = self._rapid.get(wid, 0) + 1 \
                if uptime_s < self.window_s else 0
            self._rapid[wid] = rapid
        gate = bool(self.threshold and rapid >= self.threshold)
        delay = 0.0
        if rapid and not gate:
            delay = failure.backoff_delay(
                rapid, backoff_s=self.backoff_s,
                backoff_cap_s=self.backoff_cap_s,
                jitter_seed=self._seed(wid))
        return {"rapid": rapid, "delay_s": delay, "gate": gate}

    def reset(self, wid: str) -> None:
        with self._lock:
            self._rapid.pop(wid, None)


# ---------------------------------------------------------------------------
# in-process transport (today's behavior, moved — not changed)


class WorkerHandle:
    """One fleet slot: stable wid + the current in-process Server
    incarnation (the InProcessTransport handle)."""

    # What a worker advertises to codec negotiation.  In-process
    # workers always speak both; a remote worker would advertise its
    # own set here.
    wire_formats = ("iaf2", "json")

    def __init__(self, wid: str, server: Server, generation: int,
                 codec: str,
                 scope: Optional[obs_metrics.ObsScope] = None):
        self.wid = wid
        self.server = server
        self.generation = generation
        self.codec = codec
        self.scope = scope
        self.pid = os.getpid()
        self.spawned_at = time.monotonic()

    @property
    def scope_id(self) -> Optional[str]:
        return self.scope.scope_id if self.scope is not None else None

    # -- control plane -----------------------------------------------

    def health(self) -> Dict[str, Any]:
        return self.server.health()

    def snapshot(self) -> Optional[Dict[str, dict]]:
        """The worker's ISOLATED registry snapshot (None when the
        worker has no scope of its own)."""
        if self.scope is None:
            return None
        return self.scope.registry.snapshot()

    def refresh_gauges(self) -> None:
        self.server.refresh_gauges()

    def tenants(self) -> None:
        # In-process workers share the module ledger plane — the fleet
        # reads it once locally; per-handle reads would K-count it.
        return None

    def recovery_stats(self) -> Dict[str, Any]:
        return self.server.recovery_stats or {}

    def recovery_future(self, idem: str) -> Optional["Future[Response]"]:
        """The replay future recover() registered for ``idem`` (already
        codec-wrapped), or None if the journal had no incomplete entry."""
        src = self.server.recovery.get(idem)
        if src is None:
            return None
        return _wrap_response(src, self.codec)

    def kill(self) -> None:
        self.server.kill()

    def shutdown(self) -> None:
        self.server.shutdown()

    # -- data plane ----------------------------------------------------

    def forward(self, a, ap, b, params, deadline_s: Optional[float],
                idem: Optional[str], priority: int = 2
                ) -> "Future[Response]":
        """One router->worker hop: request planes AND the trace context
        through the negotiated codec, submit, response planes back
        through the codec."""
        ctx = obs_trace.capture_trace()
        hop_bytes = 0
        if self.codec == "iaf2":
            planes = [np.asarray(x, np.float32) for x in (a, ap, b)]
            frame = wire.encode_planes(planes)
            obs_metrics.inc("router.wire_bytes", len(frame))
            hop_bytes = len(frame)
            a, ap, b = wire.decode_planes(frame)
            if ctx:
                # The IAT1 side frame rides next to the plane frame; the
                # roundtrip is the same process-boundary rehearsal the
                # planes get.
                cframe = wire.encode_context(ctx)
                obs_metrics.inc("router.wire_bytes", len(cframe))
                hop_bytes += len(cframe)
                ctx = wire.decode_context(cframe)
        else:
            a, ap, b = _roundtrip_json([a, ap, b])
            if ctx:
                ctx = _json.loads(_json.dumps(ctx))
        obs_metrics.inc("router.wire.{}".format(self.codec))
        # Submit under the DECODED context: the worker-side Request
        # carries exactly what survived the wire, so the stitched trace
        # proves cross-codec propagation, not thread-local leakage.
        with obs_trace.request_context(**ctx) if ctx \
                else contextlib.nullcontext():
            src = self.server.submit(a, ap, b, params=params,
                                     deadline_s=deadline_s,
                                     idempotency_key=idem,
                                     wire_bytes=hop_bytes,
                                     priority=priority)
        return _wrap_response(src, self.codec)


class Transport:
    """Factory seam: how the fleet spawns and reaches workers."""

    name = "?"
    handle_cls: Any = WorkerHandle

    def spawn(self, wid: str, generation: int, cfg: ServeConfig,
              codec: str, *,
              scope_parent: Optional[obs_metrics.ObsScope] = None,
              spawn_timeout_s: float = 120.0):
        raise NotImplementedError


class InProcessTransport(Transport):
    """Today's default: workers are in-process Servers with chained
    per-worker obs scopes.  Behaviorally identical to the pre-seam
    fleet — the existing fleet/journal/chaos suites run unmodified."""

    name = "inproc"
    handle_cls = WorkerHandle

    def spawn(self, wid: str, generation: int, cfg: ServeConfig,
              codec: str, *,
              scope_parent: Optional[obs_metrics.ObsScope] = None,
              spawn_timeout_s: float = 120.0) -> WorkerHandle:
        # Per-worker obs scope: the worker's counters/spans land in its
        # OWN registry (isolated view for /metrics?worker=) and chain to
        # the fleet scope, so fleet-wide snapshots keep summing.
        scope = obs_metrics.ObsScope(
            scope_id="{}.g{}".format(wid, generation), parent=scope_parent)
        server = Server(cfg, obs_scope=scope).start()
        return WorkerHandle(wid, server, generation, codec, scope=scope)


# ---------------------------------------------------------------------------
# subprocess transport


# Live worker_main children spawned by THIS process — the orphan-reaping
# fixture (tests/conftest.py) sweeps it after every test so a failed
# subprocess test never leaks a jax-loaded child.
_LIVE: "set[subprocess.Popen]" = set()


def live_workers() -> List[subprocess.Popen]:
    return [p for p in _LIVE if p.poll() is None]


def reap_orphans() -> int:
    """SIGKILL every still-live child this process ever spawned.
    Returns how many needed killing (0 on a clean run)."""
    reaped = 0
    for proc in list(_LIVE):
        if proc.poll() is None:
            try:
                proc.kill()
                proc.wait(timeout=5.0)
                reaped += 1
            except Exception:  # noqa: BLE001 - best-effort sweep
                pass
        _LIVE.discard(proc)
    return reaped


def _read_ready(rfd: int, proc: subprocess.Popen,
                timeout_s: float) -> Dict[str, Any]:
    """Block until the child writes its ready line (newline-terminated
    JSON) on the startup pipe, the child exits, or the deadline passes."""
    deadline = time.monotonic() + timeout_s
    buf = b""
    while b"\n" not in buf:
        left = deadline - time.monotonic()
        if left <= 0:
            raise TimeoutError(
                "worker_main not ready within {:.1f}s".format(timeout_s))
        if proc.poll() is not None:
            raise RuntimeError(
                "worker_main exited rc={} before ready".format(
                    proc.returncode))
        readable, _, _ = select.select([rfd], [], [], min(left, 0.25))
        if not readable:
            continue
        chunk = os.read(rfd, 4096)
        if not chunk:
            # write end closed without a full line: the child is dying;
            # the poll() check above reports it next pass.
            time.sleep(0.02)
            continue
        buf += chunk
    return _json.loads(buf.split(b"\n", 1)[0].decode())


class SubprocessHandle:
    """One fleet slot backed by a real child process reached over
    loopback HTTP.  Same negotiated wire the in-process hop rehearses —
    IAF2 plane frames, X-IA-Trace context — but now it actually crosses
    a process boundary."""

    wire_formats = ("iaf2", "json")
    server = None  # no in-process Server: the child owns it
    scope = None   # no in-process scope: the child's registry is remote

    def __init__(self, wid: str, generation: int, codec: str,
                 proc: subprocess.Popen, port: int):
        self.wid = wid
        self.generation = generation
        self.codec = codec
        self.proc = proc
        self.pid = proc.pid
        self.port = int(port)
        self.base_url = "http://127.0.0.1:{}".format(self.port)
        self.spawned_at = time.monotonic()
        # Hop pool: blocking HTTP POSTs run here so forward() keeps the
        # in-process contract (returns a Future immediately).  Pool
        # threads have no TLS obs scope, so their counters resolve to
        # the process-default run scope — the fleet registry.
        self._pool = ThreadPoolExecutor(
            max_workers=8, thread_name_prefix="hop-{}".format(wid))

    @property
    def scope_id(self) -> str:
        # The child's registry is identified by slot, generation AND
        # real pid — /healthz shows at a glance which process answers.
        return "{}.g{}.pid{}".format(self.wid, self.generation, self.pid)

    # -- control plane -----------------------------------------------

    def _get_json(self, path: str, timeout: float = 5.0) -> Dict[str, Any]:
        import urllib.request

        with urllib.request.urlopen(self.base_url + path,
                                    timeout=timeout) as resp:
            return _json.loads(resp.read().decode())

    def health(self) -> Dict[str, Any]:
        return self._get_json("/healthz")

    def snapshot(self) -> Optional[Dict[str, dict]]:
        """The child's isolated registry via GET /metrics.json (the
        JSON twin of its Prometheus exposition).  None when the child
        is unreachable — a corpse has no fresh snapshot."""
        try:
            return self._get_json("/metrics.json")
        except Exception:  # noqa: BLE001 - dead/dying child
            return None

    def refresh_gauges(self) -> None:
        # The child refreshes its own gauges on every /metrics scrape;
        # nothing to do parent-side.
        pass

    def tenants(self) -> Optional[Dict[str, Any]]:
        """The child's /tenants document (its own armed ledger plane);
        None when the child is unreachable."""
        try:
            return self._get_json("/tenants")
        except Exception:  # noqa: BLE001 - dead/dying child
            return None

    def recovery_stats(self) -> Dict[str, Any]:
        try:
            return self.health().get("recovery") or {}
        except Exception:  # noqa: BLE001 - report empty, not raise
            return {}

    def recovery_future(self, idem: str) -> None:
        # Cross-process recovery has no in-process future to re-chain.
        # The router re-forwards stranded keys instead; the child's
        # join-replay/done-dedupe (server.submit) answers exactly-once.
        return None

    def alive(self) -> bool:
        return self.proc.poll() is None

    def kill(self) -> None:
        """Real SIGKILL.  The corpse leaves its journal lock on disk
        holding a real foreign pid — the replacement's open() sweeps it
        (journal.active_pid) exactly like any crashed operator process."""
        try:
            os.kill(self.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
        try:
            self.proc.wait(timeout=10.0)
        except Exception:  # noqa: BLE001 - reaped later by the fixture
            pass
        _LIVE.discard(self.proc)
        self._pool.shutdown(wait=False)

    def shutdown(self) -> None:
        """Graceful SIGTERM (the child drains + closes its journal),
        escalating to SIGKILL if it does not exit."""
        try:
            self.proc.terminate()
        except (ProcessLookupError, PermissionError):
            pass
        try:
            self.proc.wait(timeout=15.0)
        except Exception:  # noqa: BLE001 - escalate
            try:
                self.proc.kill()
                self.proc.wait(timeout=5.0)
            except Exception:  # noqa: BLE001 - reaped by the fixture
                pass
        _LIVE.discard(self.proc)
        self._pool.shutdown(wait=False)

    # -- data plane ----------------------------------------------------

    def forward(self, a, ap, b, params, deadline_s: Optional[float],
                idem: Optional[str], priority: int = 2
                ) -> "Future[Response]":
        """One router->worker hop over real HTTP.  Encoding and wire
        accounting happen on the CALLER thread (deterministic counters);
        the blocking POST + decode run on the hop pool.

        A transport-level disconnect (child SIGKILLed mid-request)
        leaves the future UNRESOLVED on purpose: the router's pending
        entry survives, and the handoff path re-answers it by idem key
        — the same hang-until-handoff contract the in-process transport
        has when a worker dies holding a request."""
        ctx = obs_trace.capture_trace()
        if self.codec == "iaf2":
            planes = [np.asarray(x, np.float32) for x in (a, ap, b)]
            body = wire.encode_planes(planes)
            obs_metrics.inc("router.wire_bytes", len(body))
            headers = {"Content-Type": wire.CONTENT_TYPE,
                       "Accept": wire.CONTENT_TYPE}
            if deadline_s is not None:
                headers["X-IA-Deadline-Ms"] = repr(float(deadline_s) * 1e3)
            if idem:
                headers["X-IA-Idempotency-Key"] = idem
            if params is not None:
                headers["X-IA-Params"] = _json.dumps(params_to_json(params))
        else:
            doc: Dict[str, Any] = {
                "a": np.asarray(a, np.float32).tolist(),
                "ap": np.asarray(ap, np.float32).tolist(),
                "b": np.asarray(b, np.float32).tolist(),
            }
            if deadline_s is not None:
                doc["deadline_ms"] = float(deadline_s) * 1e3
            if idem:
                doc["idempotency_key"] = idem
            if params is not None:
                doc["params"] = params_to_json(params)
            body = _json.dumps(doc).encode()
            obs_metrics.inc("router.wire_bytes", len(body))
            headers = {"Content-Type": "application/json"}
        headers["X-IA-Worker-Hop"] = "1"
        if priority != 2:
            headers["X-IA-Priority"] = str(int(priority))
        if ctx:
            hdr = obs_trace.format_trace_header(ctx)
            if hdr:
                headers[obs_trace.TRACE_HEADER] = hdr
        obs_metrics.inc("router.wire.{}".format(self.codec))
        fut: "Future[Response]" = Future()
        self._pool.submit(self._post, fut, body, headers)
        return fut

    def _post(self, fut: "Future[Response]", body: bytes,
              headers: Dict[str, str]) -> None:
        import urllib.error
        import urllib.request

        try:
            req = urllib.request.Request(
                self.base_url + "/v1/analogy", data=body,
                headers=headers, method="POST")
            with urllib.request.urlopen(req, timeout=600.0) as resp:
                data = resp.read()
                hdrs = resp.headers
        except urllib.error.HTTPError as exc:
            data = exc.read()
            try:
                doc = _json.loads(data.decode() or "{}")
            except Exception:  # noqa: BLE001 - non-JSON error body
                doc = {}
            if exc.code == 429:
                fut.set_exception(Rejected(doc.get("reason", "rejected")))
            elif exc.code == 504:
                fut.set_exception(DeadlineExceeded(-1, 0.0))
            else:
                fut.set_exception(RuntimeError(
                    "worker {} answered {}: {}".format(
                        self.wid, exc.code,
                        doc.get("detail") or doc.get("error") or "?")))
            return
        except Exception:  # noqa: BLE001 - transport-level disconnect
            # Child died (or socket reset) mid-request: leave the future
            # unresolved so the router's pending entry survives for the
            # handoff to re-answer.  Counted, never silent.
            obs_metrics.inc("router.hop_disconnects")
            obs_trace.emit_record({"event": "router_hop_disconnect",
                                   "worker": self.wid})
            return
        try:
            fut.set_result(self._decode(data, hdrs))
        except Exception as exc:  # noqa: BLE001 - protocol error
            fut.set_exception(exc)

    def _decode(self, data: bytes, hdrs) -> Response:
        ctype = (hdrs.get("Content-Type") or "").split(";")[0].strip()
        obs_metrics.inc("router.wire_bytes", len(data))
        if ctype.lower() == wire.CONTENT_TYPE:
            planes = wire.decode_planes(data)
            if len(planes) != 2:
                raise wire.WireError(
                    "hop reply expected 2 planes (bp, bp_y), got {}".format(
                        len(planes)))
            bp, bp_y = planes
            timings = _json.loads(hdrs.get("X-IA-Timings") or "{}")
            stats = _json.loads(hdrs.get("X-IA-Stats") or "{}")
            degraded = _json.loads(hdrs.get("X-IA-Degraded-Detail") or "null")
            return Response(
                request_id=int(hdrs.get("X-IA-Request") or 0),
                bp=bp, bp_y=bp_y, stats=stats,
                batch_size=int(hdrs.get("X-IA-Batch-Size") or 1),
                queue_ms=float(timings.get("queue_ms", 0.0)),
                dispatch_ms=float(timings.get("dispatch_ms", 0.0)),
                total_ms=float(timings.get("total_ms", 0.0)),
                degraded=degraded)
        doc = _json.loads(data.decode())
        timings = doc.get("timings") or {}
        return Response(
            request_id=int(doc.get("request", 0)),
            bp=np.asarray(doc["bp"], dtype=np.float32),
            bp_y=np.asarray(doc["bp_y"], dtype=np.float32),
            stats=doc.get("stats") or {},
            batch_size=int(doc.get("batch_size", 1)),
            queue_ms=float(timings.get("queue_ms", 0.0)),
            dispatch_ms=float(timings.get("dispatch_ms", 0.0)),
            total_ms=float(timings.get("total_ms", 0.0)),
            degraded=doc.get("degraded"))


class SubprocessTransport(Transport):
    """Spawn each worker as a worker_main child on its own loopback
    port.  spawn() returns only after the readiness handshake — the
    child has opened its journal (REAL pid in the lock), finished
    recovery replay, and bound its HTTP socket."""

    name = "subprocess"
    handle_cls = SubprocessHandle

    def spawn(self, wid: str, generation: int, cfg: ServeConfig,
              codec: str, *,
              scope_parent: Optional[obs_metrics.ObsScope] = None,
              spawn_timeout_s: float = 120.0) -> SubprocessHandle:
        doc = {"serve": config_to_json(cfg), "wid": wid,
               "generation": generation, "port": 0}
        rfd, wfd = os.pipe()
        os.set_inheritable(wfd, True)
        # Child stdout/stderr land in the worker's journal dir (the one
        # per-slot directory that survives the process) or /dev/null.
        if cfg.journal_dir:
            os.makedirs(cfg.journal_dir, exist_ok=True)
            log_fh = open(os.path.join(cfg.journal_dir, "worker.log"), "ab")
        else:
            log_fh = open(os.devnull, "wb")
        env = dict(os.environ)
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
        try:
            proc = subprocess.Popen(
                [sys.executable, "-m",
                 "image_analogies_tpu.serve.worker_main",
                 "--ready-fd", str(wfd)],
                stdin=subprocess.PIPE, stdout=log_fh,
                stderr=subprocess.STDOUT, pass_fds=(wfd,), env=env)
        finally:
            log_fh.close()
            os.close(wfd)
        _LIVE.add(proc)
        try:
            proc.stdin.write(_json.dumps(doc).encode())
            proc.stdin.close()
            ready = _read_ready(rfd, proc, spawn_timeout_s)
        except BaseException:
            try:
                proc.kill()
                proc.wait(timeout=5.0)
            except Exception:  # noqa: BLE001 - reaped by the fixture
                pass
            _LIVE.discard(proc)
            raise
        finally:
            os.close(rfd)
        return SubprocessHandle(wid, generation, codec, proc,
                                int(ready["port"]))


def make_transport(name: str) -> Transport:
    if name == "inproc":
        return InProcessTransport()
    if name == "subprocess":
        return SubprocessTransport()
    raise ValueError("unknown transport: {!r}".format(name))
