"""Deadline policy: run full, degrade, or cancel-before-dispatch.

Cost model: synthesis work scales ~ target pixels x pyramid levels x
patch area (the per-pixel candidate scan dominates both backends), so we
keep one EWMA rate in seconds per (pixel*level*patch^2) unit, updated
from every completed dispatch.  The prior is deliberately optimistic —
until we have measurements we'd rather attempt full fidelity and learn
from the overrun than degrade requests a fresh server could have served
whole.

The degradation ladder only ever *reduces* fidelity knobs the paper's
pyramid makes safe to reduce (fewer levels, then the minimum 3x3 patch);
a degraded response is a valid synthesis, just flagged.

The EWMA's STARTING rate is no longer hardwired: :func:`load_prior`
seeds it from the tune store (this device's last serve run persisted its
learned rate there), falling back to the packaged per-device-class rate
(tune/tables.py) and only then to the optimistic default — so a restarted
server makes informed degrade decisions from its first request instead
of re-learning the device from scratch.  Provenance is counted as
``serve.cost_prior.{store,packaged,default}``.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional, Tuple

from image_analogies_tpu.config import AnalogyParams
from image_analogies_tpu.serve.types import Request
from image_analogies_tpu.tune import store as tune_store
from image_analogies_tpu.tune import tables as tune_tables

# Optimistic prior (s per pixel*level*patch^2); EWMA weight of new samples.
_PRIOR_RATE = 1e-7
_ALPHA = 0.4


def work_units(pixels: int, levels: int, patch_size: int) -> float:
    return float(pixels) * max(1, levels) * patch_size * patch_size


class CostModel:
    """Thread-safe EWMA of observed dispatch cost.

    A ``seeded`` prior (loaded from the store/packaged tables) is treated
    as a real past measurement: the first observed sample BLENDS into it
    instead of replacing it — only the hardwired optimistic default is
    discarded wholesale on first contact with reality.
    """

    def __init__(self, prior_rate: float = _PRIOR_RATE,
                 seeded: bool = False):
        self._rate = prior_rate
        self._seeded = seeded
        self._samples = 1 if seeded else 0
        self._lock = threading.Lock()

    def observe(self, units: float, seconds: float) -> None:
        if units <= 0 or seconds <= 0:
            return
        sample = seconds / units
        with self._lock:
            if self._samples == 0:
                self._rate = sample
            else:
                self._rate = _ALPHA * sample + (1 - _ALPHA) * self._rate
            self._samples += 1

    def estimate(self, units: float) -> float:
        with self._lock:
            return self._rate * units

    @property
    def rate(self) -> float:
        with self._lock:
            return self._rate

    @property
    def samples(self) -> int:
        with self._lock:
            return self._samples

    @property
    def real_samples(self) -> int:
        """Observed (non-seed) samples — what persistence gates on."""
        with self._lock:
            return self._samples - (1 if self._seeded else 0)


def cost_key(params: AnalogyParams) -> str:
    """Tune-store key for this (backend, device class) pair's serve cost
    rate.  Device kind is read from jax lazily and best-effort — serve/
    stays importable (and this resolvable) without a working backend."""
    cls = "any"
    if params.backend == "tpu":
        try:
            import jax

            cls = tune_tables.device_class(
                jax.devices()[0].device_kind) or "any"
        except Exception:  # pragma: no cover - no backend available
            cls = "any"
    return f"serve_cost|{params.backend}|{cls}"


def load_prior(params: AnalogyParams) -> Tuple[float, str]:
    """Resolve the EWMA's starting rate: ``(rate, provenance)`` with
    provenance one of ``store`` (a previous serve run on this device
    persisted its learned rate), ``packaged`` (per-device-class rate
    shipped with the package), ``default`` (the optimistic hardwired
    prior)."""
    key = cost_key(params)
    entry = tune_store.load_entries().get(key)
    if entry is not None:
        rate = entry.get("cost_rate")
        if isinstance(rate, (int, float)) and rate > 0:
            return float(rate), "store"
    cls = key.rsplit("|", 1)[1]
    packaged = tune_tables.COST_RATES.get(f"{params.backend}|{cls}")
    if packaged:
        return packaged, "packaged"
    return _PRIOR_RATE, "default"


def persist_rate(model: CostModel, params: AnalogyParams) -> Optional[str]:
    """Write the model's learned rate into the tune store (the next
    server's ``store`` prior).  No-op without real observations — a prior
    that never met traffic must not launder itself into a measurement."""
    if model.real_samples < 1:
        return None
    key = cost_key(params)
    tune_store.merge_entries({key: {
        "cost_rate": model.rate,
        "source": "serve",
        "samples": model.samples,
    }})
    return key


def _ladder(params: AnalogyParams):
    """Fidelity configs from full to minimum, each a valid AnalogyParams
    substitution.  Patch sizes stay odd (engine invariant)."""
    patches = [params.patch_size]
    if params.patch_size > 3:
        patches.append(3)
    for levels in range(params.levels, 0, -1):
        for patch in patches:
            yield levels, patch


def plan(req: Request, model: CostModel, *, allow_degrade: bool
         ) -> Tuple[str, AnalogyParams, Optional[Dict[str, Any]]]:
    """Decide what to dispatch for ``req`` right now.

    Returns ``(action, params, degraded)`` with action one of:
    - ``"run"``      — full fidelity fits (or no deadline).
    - ``"degrade"``  — ``params`` substituted per ``degraded`` dict.
    - ``"timeout"``  — deadline already expired; cancel before dispatch.
    """
    remaining = req.remaining()
    if remaining is None:
        return "run", req.params, None
    if remaining <= 0:
        return "timeout", req.params, None
    pixels = int(req.b.shape[0]) * int(req.b.shape[1])
    full = model.estimate(
        work_units(pixels, req.params.levels, req.params.patch_size))
    if full <= remaining or not allow_degrade:
        return "run", req.params, None
    for levels, patch in _ladder(req.params):
        if levels == req.params.levels and patch == req.params.patch_size:
            continue
        est = model.estimate(work_units(pixels, levels, patch))
        if est <= remaining:
            return ("degrade",
                    req.params.replace(levels=levels, patch_size=patch),
                    {"levels": levels, "patch_size": patch,
                     "estimate_s": round(est, 4),
                     "full_estimate_s": round(full, 4)})
    # Nothing fits the deadline; dispatch the cheapest valid config rather
    # than guaranteeing failure — the response stays flagged as degraded.
    levels, patch = 1, min(3, req.params.patch_size)
    return ("degrade", req.params.replace(levels=levels, patch_size=patch),
            {"levels": levels, "patch_size": patch, "best_effort": True,
             "full_estimate_s": round(full, 4)})
