"""Batch-compatibility key.

Two requests may share one batched invocation iff a single engine
backend can serve both with identical compiled programs and identical
exemplar-side work:

- same ``AnalogyParams`` digest (``obs.trace.config_digest`` — the same
  digest the run manifest records, so batches are auditable from logs);
- same tune shape-bucket for the exemplar row count (``bucket_rows`` —
  the granularity at which PR 3's program reuse already keys compiled
  levels) and for the target;
- same exemplar *content* (sha1 of the A/A' planes).  This is stricter
  than the ISSUE's shape-bucket minimum on purpose: sharing a backend
  across identical exemplars lets the CPU matcher reuse its KD-tree and
  the TPU devcache its uploads, which is where the batched-throughput
  win over sequential dispatch comes from.  Requests with equal shapes
  but different exemplars still run — just as singleton batches.

Odd shapes need no special casing: a key nobody else shares simply
coalesces with nobody, and the window expires into singleton dispatch.
"""

from __future__ import annotations

import hashlib
from typing import Any, Tuple

import numpy as np

from image_analogies_tpu.config import AnalogyParams
from image_analogies_tpu.obs import trace as obs_trace
from image_analogies_tpu.tune import buckets as tune_buckets


def exemplar_digest(a: np.ndarray, ap: np.ndarray) -> str:
    h = hashlib.sha1()
    for arr in (a, ap):
        arr = np.ascontiguousarray(arr)
        h.update(str(arr.shape).encode())
        h.update(str(arr.dtype).encode())
        h.update(arr.tobytes())
    return h.hexdigest()[:12]


def key_str(key: Tuple[Any, ...]) -> str:
    """Canonical display form of a batch key (span attrs, trace labels):
    ``digest/a_bucket/b_bucket/exemplar``."""
    return "/".join(str(k) for k in key)


def batch_key(a: np.ndarray, ap: np.ndarray, b: np.ndarray,
              params: AnalogyParams) -> Tuple[Any, ...]:
    a_rows = int(a.shape[0]) * int(a.shape[1])
    b_rows = int(b.shape[0]) * int(b.shape[1])
    return (
        obs_trace.config_digest(params),
        tune_buckets.bucket_rows(a_rows),
        tune_buckets.bucket_rows(b_rows),
        exemplar_digest(a, ap),
    )
