"""Declarative control-plane policy: autoscaling targets + per-tenant
QoS, as plain JSON.

Two documents live here:

- :class:`ControlPolicy` — what the elastic fleet should look like
  (min/max workers, queue-depth / p95 / SLO-burn targets, hysteresis
  windows, cooldowns).  serve/control.py's reconcile loop reads ONLY
  this policy plus observed signals; it never invents thresholds.
- :class:`QosPolicy` — how one tenant's traffic may degrade ITSELF
  rather than the fleet: per-style token-bucket admission quotas (fed
  by the tenants sketch's observed cost shares), weighted-fair queue
  pop across tenants, and priority-class weights.

Both round-trip to plain JSON (``to_json`` / ``from_json`` /
``load``), so a policy is an artifact an operator checks in, not code.
:class:`TenantQuota` is the runtime half of the quota story: a bounded
dict of token buckets with a deterministic injectable clock, throttled
by observed cost share — a tenant consuming more than ``share_cap`` of
the fleet's dispatch cost has its refill scaled down proportionally.

Host-side only: no jax imports, no jit (serve grep-lock scans this
file).
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
from typing import Any, Dict, Optional

# Priority classes: Request.priority holds one of these weights.  The
# weight is the tenant's stride-scheduling share in the weighted-fair
# queue — interactive traffic advances 4x for every background step.
PRIORITY_BACKGROUND = 1
PRIORITY_STANDARD = 2
PRIORITY_INTERACTIVE = 4

PRIORITY_CLASSES: Dict[str, int] = {
    "background": PRIORITY_BACKGROUND,
    "standard": PRIORITY_STANDARD,
    "interactive": PRIORITY_INTERACTIVE,
}


@dataclasses.dataclass(frozen=True)
class QosPolicy:
    """Per-tenant QoS knobs for one worker's admission path.

    ``quota_rps``       per-tenant token refill rate (tokens/sec); 0
                        disables admission quotas entirely.
    ``quota_burst``     bucket capacity (burst allowance).
    ``share_cap``       observed-cost-share ceiling: a tenant whose
                        ledger ``cost_share`` exceeds this fraction has
                        its refill scaled by ``share_cap / share`` — the
                        viral style throttles harder as it gets hotter.
    ``share_refresh_s`` how often the bucket re-reads the tenants
                        sketch.
    ``weighted_fair``   stride-scheduled leader pick across tenants in
                        ``pop_batch`` (anti-starvation aging still
                        applies on top).
    ``max_tenants``     bound on tracked buckets (oldest evicted).
    """

    quota_rps: float = 0.0
    quota_burst: float = 8.0
    share_cap: float = 0.5
    share_refresh_s: float = 0.5
    weighted_fair: bool = True
    max_tenants: int = 64

    def __post_init__(self):
        if self.quota_rps < 0:
            raise ValueError("quota_rps must be >= 0")
        if self.quota_burst < 1:
            raise ValueError("quota_burst must be >= 1")
        if not 0.0 < self.share_cap <= 1.0:
            raise ValueError("share_cap must be in (0, 1]")
        if self.share_refresh_s <= 0:
            raise ValueError("share_refresh_s must be > 0")
        if self.max_tenants < 1:
            raise ValueError("max_tenants must be >= 1")

    def to_json(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @staticmethod
    def from_json(doc: Dict[str, Any]) -> "QosPolicy":
        if not isinstance(doc, dict):
            raise ValueError("qos policy must be a JSON object")
        known = {f.name for f in dataclasses.fields(QosPolicy)}
        extra = set(doc) - known
        if extra:
            raise ValueError(f"unknown qos policy fields: {sorted(extra)}")
        return QosPolicy(**doc)


@dataclasses.dataclass(frozen=True)
class ControlPolicy:
    """Declarative autoscaling targets for one fleet.

    Scale-up arms when ANY pressure signal holds for
    ``scale_up_windows`` consecutive reconcile passes: mean ready-worker
    queue depth >= ``queue_high``, fast SLO burn rate >=
    ``max_burn_rate``, or windowed p95 >= ``target_p95_ms`` (when set).
    Scale-down arms when mean depth <= ``queue_low`` AND burn is below
    target for ``scale_down_windows`` passes.  Each direction has its
    own cooldown so the fleet breathes instead of oscillating.
    """

    min_workers: int = 1
    max_workers: int = 4
    queue_high: float = 4.0
    queue_low: float = 0.5
    max_burn_rate: float = 2.0
    target_p95_ms: float = 0.0          # 0 = p95 signal disabled
    scale_up_windows: int = 2
    scale_down_windows: int = 4
    scale_up_cooldown_s: float = 1.0
    scale_down_cooldown_s: float = 2.0

    def __post_init__(self):
        if self.min_workers < 1:
            raise ValueError("min_workers must be >= 1")
        if self.max_workers < self.min_workers:
            raise ValueError("max_workers must be >= min_workers")
        if self.queue_high <= 0 or self.queue_low < 0:
            raise ValueError("queue_high must be > 0, queue_low >= 0")
        if self.queue_low >= self.queue_high:
            raise ValueError("queue_low must be < queue_high")
        if self.max_burn_rate <= 0:
            raise ValueError("max_burn_rate must be > 0")
        if self.target_p95_ms < 0:
            raise ValueError("target_p95_ms must be >= 0")
        if self.scale_up_windows < 1 or self.scale_down_windows < 1:
            raise ValueError("hysteresis windows must be >= 1")
        if self.scale_up_cooldown_s < 0 or self.scale_down_cooldown_s < 0:
            raise ValueError("cooldowns must be >= 0")

    def to_json(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @staticmethod
    def from_json(doc: Dict[str, Any]) -> "ControlPolicy":
        if not isinstance(doc, dict):
            raise ValueError("control policy must be a JSON object")
        known = {f.name for f in dataclasses.fields(ControlPolicy)}
        extra = set(doc) - known
        if extra:
            raise ValueError(
                f"unknown control policy fields: {sorted(extra)}")
        return ControlPolicy(**doc)

    @staticmethod
    def load(path: str) -> "ControlPolicy":
        with open(path) as f:
            return ControlPolicy.from_json(json.load(f))


class TenantQuota:
    """Per-tenant token buckets fed by observed cost shares.

    ``try_admit(tenant)`` spends one token from the tenant's bucket and
    reports whether the request may enter the queue.  Refill is
    ``quota_rps`` scaled DOWN when the tenants sketch says the tenant
    already consumes more than ``share_cap`` of observed dispatch cost:
    effective_rps = quota_rps * min(1, share_cap / cost_share).  The
    share map refreshes at most every ``share_refresh_s`` through the
    injected ``shares_fn`` (a callable returning the ledger's
    ``/tenants`` document), so the hot path stays a dict probe plus a
    couple of float ops.

    The clock is injectable for deterministic tests; buckets are
    bounded by ``max_tenants`` (least-recently-admitted evicted).
    """

    def __init__(self, policy: QosPolicy, shares_fn=None,
                 clock=time.monotonic):
        self.policy = policy
        self._shares_fn = shares_fn
        self._clock = clock
        self._lock = threading.Lock()
        self._buckets: Dict[str, Dict[str, float]] = {}
        self._shares: Dict[str, float] = {}
        self._shares_t = -float("inf")
        self.throttled = 0

    def _refresh_shares_locked(self, now: float) -> None:
        if self._shares_fn is None:
            return
        if now - self._shares_t < self.policy.share_refresh_s:
            return
        self._shares_t = now
        try:
            doc = self._shares_fn() or {}
        except Exception:  # noqa: BLE001 - shares are advisory
            return
        self._shares = {
            str(row.get("tenant")): float(row.get("cost_share") or 0.0)
            for row in doc.get("tenants", [])}

    def effective_rps(self, tenant: str) -> float:
        """Refill rate after the cost-share penalty (0 disables)."""
        share = self._shares.get(tenant, 0.0)
        rps = self.policy.quota_rps
        if share > self.policy.share_cap:
            rps *= self.policy.share_cap / share
        return rps

    def try_admit(self, tenant: str) -> bool:
        if self.policy.quota_rps <= 0:
            return True
        now = self._clock()
        with self._lock:
            self._refresh_shares_locked(now)
            bucket = self._buckets.get(tenant)
            if bucket is None:
                if len(self._buckets) >= self.policy.max_tenants:
                    oldest = min(self._buckets,
                                 key=lambda t: self._buckets[t]["t"])
                    self._buckets.pop(oldest)
                bucket = self._buckets[tenant] = {
                    "tokens": float(self.policy.quota_burst), "t": now}
            else:
                elapsed = max(0.0, now - bucket["t"])
                bucket["tokens"] = min(
                    float(self.policy.quota_burst),
                    bucket["tokens"] + elapsed * self.effective_rps(tenant))
                bucket["t"] = now
            if bucket["tokens"] >= 1.0:
                bucket["tokens"] -= 1.0
                return True
            self.throttled += 1
            return False

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "throttled": self.throttled,
                "tenants": {
                    t: {"tokens": round(b["tokens"], 3),
                        "effective_rps": round(self.effective_rps(t), 4)}
                    for t, b in self._buckets.items()},
            }
