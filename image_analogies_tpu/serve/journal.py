"""Write-ahead request journal — the serving plane's durability log.

Every admitted request is recorded BEFORE it enters the queue, then each
state transition is appended as it happens:

    admitted -> dispatched -> done(response digest)
                           -> rejected(reason)
                           -> poisoned

so a process death at any instant leaves a journal from which
:meth:`Server.recover` can reconstruct exactly what was owed to whom:

- ``done`` entries short-circuit duplicate submissions with the recorded
  response (exactly-once from the client's view — the response planes
  are spilled alongside the log);
- incomplete entries are re-enqueued in original admit order;
- entries whose ``dispatched`` count exhausted ``crash_requeues`` are
  marked ``poisoned`` and permanently shed with ``Rejected("poison")``
  so a poison request cannot crash the fleet twice.

Format: JSONL *segments* (``segment-%06d.jsonl``) where every line
carries a ``seal`` — sha256 over the canonical JSON of the rest of the
record — reusing ``utils/checkpoint.py``'s seal/quarantine pattern: a
torn tail or flipped bit fails the seal, the valid prefix is kept, and
the damaged segment is quarantined as ``.corrupt`` (evidence, never
deleted) instead of poisoning replay.  Appends are fsync'd by default
(``journal_fsync=False`` trades the sync for speed in tests).

Payload planes are spilled next to the log as checksummed ``.npz``
(``payloads/<idem>.npz`` inputs, ``payloads/<idem>.resp.npz`` the
recorded response), so the journal lines stay small and replay can both
re-run an incomplete request and answer a duplicate of a finished one.

Idempotency key: client-supplied, or ``sha1(batch key x payload
digest)`` — deterministic across processes, so a client retry after a
restart dedupes with no client-side cooperation.  Keys name files under
the journal directory, so client-supplied keys are confined to
``[A-Za-z0-9_-]{1,64}`` (:func:`valid_idem`), enforced at the HTTP and
``Server.submit`` boundaries and again by every path builder here —
a traversal-shaped key can never become a filesystem path.

Zero-cost when disabled: the server holds ``journal=None`` unless
``ServeConfig.journal_dir`` is set; no call site touches this module on
the disabled path (locked by tests/test_journal.py's poisoned-import
test).  Like the rest of serve/, this module is jax-free (grep-locked).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import re
import threading
import zipfile
from typing import Any, Dict, List, Optional

import numpy as np

from image_analogies_tpu import chaos
from image_analogies_tpu.config import AnalogyParams
from image_analogies_tpu.obs import metrics as obs_metrics
from image_analogies_tpu.obs import trace as obs_trace
from image_analogies_tpu.utils import checkpoint as ckpt

_SEGMENT_FMT = "segment-%06d.jsonl"
_LOCK_NAME = "journal.lock"
_OPS = ("admitted", "dispatched", "done", "rejected", "poisoned")
_IDEM_RE = re.compile(r"[A-Za-z0-9_-]{1,64}\Z")


class JournalLocked(RuntimeError):
    """The journal directory is owned by a LIVE foreign process.  Raised
    by :meth:`RequestJournal.open` so two live workers can never append
    to one journal — the single-writer invariant every replay guarantee
    rests on.  A dead owner's lock is swept, never raises."""

    def __init__(self, path: str, pid: int):
        super().__init__(
            f"journal at {path} is owned by live pid {pid}")
        self.path = path
        self.pid = pid


def valid_idem(idem: str) -> bool:
    """True when *idem* is safe to embed in journal lines and spill
    filenames.  Keys name files under the journal directory, so
    anything outside ``[A-Za-z0-9_-]{1,64}`` (path separators, dots,
    NULs, over-long strings) is refused at the submit/HTTP boundary —
    derived keys (sha1 hex) match by construction."""
    return isinstance(idem, str) and bool(_IDEM_RE.fullmatch(idem))


def idem_key(key_str: str, b: np.ndarray) -> str:
    """Idempotency key for a request: sha1 over the batch key (params
    digest x shape buckets x exemplar content) and the target plane's
    content.  Deterministic across processes — the property that makes a
    client retry after a server restart dedupe by construction."""
    b = np.ascontiguousarray(b)
    h = hashlib.sha1()
    h.update(key_str.encode())
    h.update(repr((b.shape, str(b.dtype))).encode())
    h.update(b.tobytes())
    return h.hexdigest()[:16]


def _seal(record: Dict[str, Any]) -> str:
    canonical = json.dumps(record, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(canonical.encode()).hexdigest()[:32]


def _plane_checksum(*arrays: np.ndarray) -> str:
    """Same recipe as checkpoint._payload_checksum: shape + dtype + bytes
    under one sha256, stored inside the npz, checked on load."""
    h = hashlib.sha256()
    for arr in arrays:
        arr = np.ascontiguousarray(arr)
        h.update(repr((arr.shape, str(arr.dtype))).encode())
        h.update(arr.tobytes())
    return h.hexdigest()[:32]


def response_digest(bp: np.ndarray, bp_y: np.ndarray) -> str:
    """Content digest of a response's output planes — what the ``done``
    journal line records, so an operator can audit that a replayed run
    reproduced the same bytes."""
    return _plane_checksum(bp, bp_y)


@dataclasses.dataclass
class JournalEntry:
    """Replay-time view of one idempotency key's transition history."""

    idem: str
    admit: Dict[str, Any]
    dispatched: int = 0
    done: Optional[Dict[str, Any]] = None
    rejected: Optional[str] = None
    poisoned: bool = False

    @property
    def complete(self) -> bool:
        return self.done is not None or self.rejected is not None \
            or self.poisoned


@dataclasses.dataclass
class Replay:
    """Result of :meth:`RequestJournal.replay`."""

    entries: Dict[str, JournalEntry]      # idem -> history
    order: List[str]                      # idems in original admit order
    quarantined: int = 0                  # segments moved to .corrupt
    lines: int = 0                        # valid sealed lines read

    @property
    def incomplete(self) -> List[JournalEntry]:
        return [self.entries[i] for i in self.order
                if not self.entries[i].complete]


class RequestJournal:
    """One directory of sealed JSONL segments + spilled payloads.

    Thread-safe: appends from the admission thread and every worker
    serialize on one lock (a request journal is an ordering witness —
    interleaved partial lines would defeat it)."""

    def __init__(self, path: str, fsync: bool = True):
        self.path = path
        self.fsync = fsync
        self._lock = threading.Lock()
        self._fh = None
        self._segment = 0
        # In-memory dedupe state, rebuilt by replay() and kept current by
        # record_done/record_poisoned during the process lifetime.
        self._done: Dict[str, Any] = {}       # idem -> Response | None(lazy)
        self._poisoned: set = set()
        os.makedirs(self._payload_dir, exist_ok=True)

    # -- paths -------------------------------------------------------------

    @property
    def _payload_dir(self) -> str:
        return os.path.join(self.path, "payloads")

    def _segment_path(self, index: int) -> str:
        return os.path.join(self.path, _SEGMENT_FMT % index)

    def _segments(self) -> List[str]:
        try:
            names = sorted(n for n in os.listdir(self.path)
                           if n.startswith("segment-")
                           and n.endswith(".jsonl"))
        except OSError:
            return []
        return [os.path.join(self.path, n) for n in names]

    @property
    def _lock_path(self) -> str:
        return os.path.join(self.path, _LOCK_NAME)

    def payload_path(self, idem: str) -> str:
        # Backstop behind the boundary validation in Server.submit /
        # http.py: an unvalidated key must fail loudly here, never
        # become a path outside the payload dir.
        if not valid_idem(idem):
            raise ValueError(f"unsafe idempotency key: {idem!r}")
        return os.path.join(self._payload_dir, f"{idem}.npz")

    def response_path(self, idem: str) -> str:
        if not valid_idem(idem):
            raise ValueError(f"unsafe idempotency key: {idem!r}")
        return os.path.join(self._payload_dir, f"{idem}.resp.npz")

    @staticmethod
    def _spill_tmp(final_path: str) -> str:
        """Per-writer temp name for a spill headed to *final_path* (the
        .npz suffix keeps np.savez from appending its own)."""
        return (f"{final_path}.{os.getpid()}"
                f".{threading.get_ident()}.tmp.npz")

    # -- append side -------------------------------------------------------

    def open(self) -> "RequestJournal":
        """Open a fresh segment for appends (one per server incarnation —
        a restart never appends into a segment a dead process may have
        torn)."""
        with self._lock:
            if self._fh is not None:
                return self
            # Single-writer gate: a lock held by a LIVE foreign process
            # refuses this opener (two appenders would tear the replay
            # history); a dead owner's lock is stale and active_pid()
            # sweeps it — the real-SIGKILL handoff path, where the
            # replacement inherits the corpse's directory.
            owner = self.active_pid()
            if owner is not None and owner != os.getpid():
                raise JournalLocked(self.path, owner)
            segs = self._segments()
            last = int(os.path.basename(segs[-1])[8:-6]) if segs else 0
            self._segment = last + 1
            self._fh = open(self._segment_path(self._segment), "a")
            # Advisory single-writer lock: marks the journal active so
            # compact() refuses to delete segments out from under a
            # live appender.  Released by close(); a crash leaves it
            # behind, so readers liveness-check the recorded pid.
            with open(self._lock_path, "w") as lf:
                lf.write(str(os.getpid()))
            # Sweep spill temp files orphaned by a crashed incarnation
            # (each writer uses a unique temp name, so these can only
            # be dead — the atomic os.replace either happened or not).
            try:
                for name in os.listdir(self._payload_dir):
                    if name.endswith(".tmp.npz"):
                        try:
                            os.remove(os.path.join(self._payload_dir,
                                                   name))
                        except OSError:
                            pass
            except OSError:
                pass
        return self

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                finally:
                    self._fh = None
                try:
                    os.remove(self._lock_path)
                except OSError:
                    pass

    def active_pid(self) -> Optional[int]:
        """PID of a process currently appending to this journal, or
        None.  A lock file whose owner is dead is stale — removed here
        so a crashed incarnation doesn't block compaction forever."""
        if self._fh is not None:
            return os.getpid()
        try:
            with open(self._lock_path) as f:
                pid = int(f.read().strip() or "0")
        except (OSError, ValueError):
            return None
        if pid <= 0:
            return None
        try:
            os.kill(pid, 0)
        except ProcessLookupError:
            # Stale: the recorded owner is a corpse.  Sweep the lock
            # (counted — the subprocess handoff drill reconciles this
            # against the real SIGKILL it delivered).
            try:
                os.remove(self._lock_path)
                obs_metrics.inc("serve.journal.stale_lock_swept")
            except OSError:
                pass
            return None
        except PermissionError:
            pass  # exists, owned by another user: still alive
        return pid

    def _append(self, record: Dict[str, Any]) -> None:
        # The chaos plane's process-death site: a ProcessDeath raised
        # here models the process dying with this transition unrecorded —
        # exactly the torn-history case replay must absorb.
        chaos.site("serve.journal", op=record.get("op", "?"))
        line = json.dumps({"seal": _seal(record), **record},
                          sort_keys=True, separators=(",", ":"))
        with self._lock:
            if self._fh is None:  # journal closed (shutdown race): drop
                return
            self._fh.write(line + "\n")
            self._fh.flush()
            if self.fsync:
                os.fsync(self._fh.fileno())
        obs_metrics.inc(f"serve.journal.{record['op']}")

    def record_admit(self, idem: str, request_id: int, a: np.ndarray,
                     ap: np.ndarray, b: np.ndarray, params: AnalogyParams,
                     deadline_s: Optional[float], key: str) -> None:
        """WAL step: spill the payload, then the admit line.  Runs BEFORE
        the queue sees the request — an admitted request with no journal
        line cannot exist, only the harmless converse."""
        ppath = self.payload_path(idem)
        if not os.path.exists(ppath):  # client retries reuse the spill
            # Unique temp per writer: a retry racing the original (both
            # past the exists check) must not interleave np.savez into
            # one file — each writes its own, os.replace is atomic,
            # last-one-wins lands a self-consistent spill either way.
            tmp = self._spill_tmp(ppath)
            np.savez(tmp, a=a, ap=ap, b=b,
                     params=json.dumps(dataclasses.asdict(params),
                                       sort_keys=True),
                     checksum=_plane_checksum(a, ap, b))
            os.replace(tmp, ppath)
        self._append({"op": "admitted", "idem": idem, "rid": request_id,
                      "key": key, "deadline_s": deadline_s})

    def record_dispatched(self, idem: str) -> None:
        self._append({"op": "dispatched", "idem": idem})

    def record_done(self, idem: str, resp: Any) -> None:
        """Spill the response, then the done line, then remember it for
        in-process dedupe.  Callers sequence this BEFORE resolving the
        client future: once a client can observe an answer, the journal
        already guarantees every future duplicate gets the same one."""
        rpath = self.response_path(idem)
        if not os.path.exists(rpath):
            tmp = self._spill_tmp(rpath)
            np.savez(tmp, bp=resp.bp, bp_y=resp.bp_y,
                     stats=json.dumps(resp.stats, default=str),
                     degraded=json.dumps(resp.degraded),
                     request_id=resp.request_id,
                     checksum=_plane_checksum(resp.bp, resp.bp_y))
            os.replace(tmp, rpath)
        self._append({"op": "done", "idem": idem,
                      "rid": resp.request_id,
                      "response_digest": response_digest(resp.bp,
                                                         resp.bp_y)})
        with self._lock:
            self._done[idem] = resp

    def record_rejected(self, idem: str, reason: str) -> None:
        self._append({"op": "rejected", "idem": idem, "reason": reason})

    def record_poisoned(self, idem: str) -> None:
        self._append({"op": "poisoned", "idem": idem})
        with self._lock:
            self._poisoned.add(idem)

    # -- dedupe / poison lookups (request path) ----------------------------

    def is_poisoned(self, idem: str) -> bool:
        with self._lock:
            return idem in self._poisoned

    def lookup_done(self, idem: str) -> Optional[Any]:
        """Recorded Response for a finished key, or None.  A replayed
        ``done`` is loaded lazily from its spill on first hit; a spill
        that fails its checksum is quarantined and the key degrades to
        not-done (the engine is deterministic, so a re-run still answers
        with the same bytes — exactly-once is preserved)."""
        with self._lock:
            if idem not in self._done:
                return None
            resp = self._done[idem]
        if resp is not None:
            return resp
        resp = self._load_response(idem)
        with self._lock:
            if resp is None:
                self._done.pop(idem, None)
            else:
                self._done[idem] = resp
        return resp

    def _load_response(self, idem: str) -> Optional[Any]:
        from image_analogies_tpu.serve.types import Response

        rpath = self.response_path(idem)
        if not os.path.exists(rpath):
            return None
        try:
            with np.load(rpath) as z:
                bp = z["bp"].astype(np.float32)
                bp_y = z["bp_y"].astype(np.float32)
                want = str(z["checksum"])
                if want != _plane_checksum(z["bp"], z["bp_y"]):
                    raise ValueError(
                        f"response payload checksum mismatch at {rpath}")
                stats = json.loads(str(z["stats"]))
                degraded = json.loads(str(z["degraded"]))
                rid = int(z["request_id"])
        except (zipfile.BadZipFile, OSError, ValueError, KeyError,
                EOFError):
            ckpt.quarantine(rpath, counter="serve.journal.quarantined",
                            event="journal_quarantined")
            return None
        return Response(request_id=rid, bp=bp, bp_y=bp_y, stats=stats,
                        batch_size=1, queue_ms=0.0, dispatch_ms=0.0,
                        total_ms=0.0, degraded=degraded)

    def load_payload(self, idem: str):
        """(a, ap, b, params, deadline_s-less admit payload) for replay,
        or None when the spill is missing/damaged (quarantined — the
        request cannot be re-run, only reported)."""
        ppath = self.payload_path(idem)
        if not os.path.exists(ppath):
            return None
        try:
            with np.load(ppath) as z:
                a = z["a"].astype(np.float32)
                ap = z["ap"].astype(np.float32)
                b = z["b"].astype(np.float32)
                want = str(z["checksum"])
                if want != _plane_checksum(z["a"], z["ap"], z["b"]):
                    raise ValueError(
                        f"journal payload checksum mismatch at {ppath}")
                params = AnalogyParams(**json.loads(str(z["params"])))
        except (zipfile.BadZipFile, OSError, ValueError, KeyError,
                EOFError, TypeError):
            ckpt.quarantine(ppath, counter="serve.journal.quarantined",
                            event="journal_quarantined")
            return None
        return a, ap, b, params

    # -- replay side -------------------------------------------------------

    def _read_segment(self, path: str) -> List[Dict[str, Any]]:
        """Sealed lines of one segment.  On the first unparseable or
        seal-failing line the valid prefix is kept, the damaged file is
        quarantined as ``.corrupt``, and the prefix is rewritten in its
        place so the next restart replays cleanly (the quarantined bytes
        stay as evidence, same contract as checkpoint quarantine)."""
        records: List[Dict[str, Any]] = []
        good_lines: List[str] = []
        damaged = False
        with open(path) as f:
            for line in f:
                stripped = line.strip()
                if not stripped:
                    continue
                try:
                    rec = json.loads(stripped)
                    seal = rec.pop("seal")
                    if seal != _seal(rec) or rec.get("op") not in _OPS:
                        raise ValueError("bad seal")
                except (json.JSONDecodeError, KeyError, ValueError,
                        AttributeError, TypeError):
                    damaged = True
                    break
                records.append(rec)
                good_lines.append(stripped)
        if damaged:
            ckpt.quarantine(path, counter="serve.journal.quarantined",
                            event="journal_quarantined")
            with open(path + ".tmp", "w") as f:
                for rec_line in good_lines:
                    f.write(rec_line + "\n")
                f.flush()
                os.fsync(f.fileno())
            os.replace(path + ".tmp", path)
        return records

    def replay(self) -> Replay:
        """Fold every segment's transitions into per-key histories.

        Duplicate transitions are idempotent folds (two ``done`` lines
        for one key — e.g. a retry that raced a death — count once); the
        admit ORDER is the original EDF submission order and is what
        recovery re-enqueues by."""
        entries: Dict[str, JournalEntry] = {}
        order: List[str] = []
        quarantined_before = _corrupt_count(self.path)
        lines = 0
        for seg in self._segments():
            for rec in self._read_segment(seg):
                lines += 1
                idem = str(rec.get("idem"))
                if not valid_idem(idem):
                    # Journal lines only ever carry boundary-validated
                    # keys; an unsafe idem means a handcrafted file —
                    # skip it so replay never turns it into a path.
                    continue
                op = rec["op"]
                if op == "admitted":
                    if idem not in entries:
                        entries[idem] = JournalEntry(idem=idem, admit=rec)
                        order.append(idem)
                    continue
                ent = entries.get(idem)
                if ent is None:
                    # transition without an admit (its admit line was in
                    # a torn prefix): synthesize so done/poisoned dedupe
                    # still works; it can never be re-enqueued (no
                    # payload reference is trusted without an admit).
                    ent = JournalEntry(idem=idem, admit={},
                                       rejected="orphaned")
                    entries[idem] = ent
                if op == "dispatched":
                    ent.dispatched += 1
                elif op == "done":
                    ent.done = rec
                elif op == "rejected":
                    ent.rejected = str(rec.get("reason", "rejected"))
                elif op == "poisoned":
                    ent.poisoned = True
        with self._lock:
            for ent in entries.values():
                if ent.done is not None:
                    self._done.setdefault(ent.idem, None)  # lazy load
                if ent.poisoned:
                    self._poisoned.add(ent.idem)
        return Replay(entries=entries, order=order,
                      quarantined=_corrupt_count(self.path)
                      - quarantined_before,
                      lines=lines)

    # -- tooling (`ia journal`) --------------------------------------------

    def inspect(self) -> Dict[str, Any]:
        """Read-only summary for ``ia journal inspect``."""
        rep = self.replay()
        states: Dict[str, int] = {}
        for ent in rep.entries.values():
            if ent.poisoned:
                st = "poisoned"
            elif ent.done is not None:
                st = "done"
            elif ent.rejected is not None:
                st = "rejected"
            elif ent.dispatched:
                st = "dispatched"
            else:
                st = "admitted"
            states[st] = states.get(st, 0) + 1
        return {
            "path": self.path,
            "segments": len(self._segments()),
            "corrupt_segments": _corrupt_count(self.path),
            "lines": rep.lines,
            "requests": len(rep.entries),
            "states": states,
            "incomplete": [e.idem for e in rep.incomplete],
            "poisoned": sorted(e.idem for e in rep.entries.values()
                               if e.poisoned),
        }

    def compact(self) -> Dict[str, Any]:
        """Rewrite the journal to its minimal equivalent: one fresh
        segment holding each key's FINAL state (admit lines only for
        still-incomplete work), dropping intermediate transitions and the
        input spills of finished requests.  Response spills are kept —
        they are what dedupe answers with.  ``.corrupt`` files are never
        touched.

        Refuses while the journal is active (``journal.lock`` held by a
        live pid): a live appender holds the newest segment open, so
        deleting it would send its fsync'd appends to an unlinked file
        and silently lose every transition after the compaction."""
        owner = self.active_pid()
        if owner is not None:
            raise RuntimeError(
                f"journal at {self.path} is active (pid {owner}); "
                "stop the server before compacting")
        rep = self.replay()
        before = {"segments": len(self._segments()), "lines": rep.lines}
        tmp = os.path.join(self.path, "compact.tmp")
        kept = 0
        with open(tmp, "w") as f:
            def put(rec: Dict[str, Any]) -> None:
                nonlocal kept
                f.write(json.dumps({"seal": _seal(rec), **rec},
                                   sort_keys=True,
                                   separators=(",", ":")) + "\n")
                kept += 1

            for idem in rep.order:
                ent = rep.entries[idem]
                if not ent.complete:
                    put(ent.admit)
                    for _ in range(ent.dispatched):
                        put({"op": "dispatched", "idem": idem})
            for idem, ent in sorted(rep.entries.items()):
                if ent.poisoned:
                    put({"op": "poisoned", "idem": idem})
                elif ent.done is not None:
                    put(ent.done)
            f.flush()
            os.fsync(f.fileno())
        segs = self._segments()
        last = int(os.path.basename(segs[-1])[8:-6]) if segs else 0
        os.replace(tmp, self._segment_path(last + 1))
        for seg in segs:
            os.remove(seg)
        for ent in rep.entries.values():
            if ent.complete:
                try:
                    os.remove(self.payload_path(ent.idem))
                except OSError:
                    pass
        return {**before, "after": {"segments": 1, "lines": kept},
                "dropped_lines": rep.lines - kept}

    def stats(self) -> Dict[str, int]:
        """Live journal counters (from the active obs registry) — what
        /healthz and the selftest summary surface."""
        snap = obs_metrics.snapshot() or {}
        counters = snap.get("counters", {})
        return {k.split("serve.journal.", 1)[1]: int(v)
                for k, v in counters.items()
                if k.startswith("serve.journal.")}

    def info(self) -> Dict[str, Any]:
        """Ownership facts for /healthz: which pid holds the advisory
        lock and which segment this incarnation appends to — what a
        router (or operator) checks before handing the directory to a
        replacement worker."""
        return {"lock_pid": self.active_pid(), "segment": self._segment}


def _corrupt_count(path: str) -> int:
    try:
        names = os.listdir(path) + os.listdir(os.path.join(path,
                                                           "payloads"))
    except OSError:
        return 0
    return sum(1 for n in names if n.endswith(".corrupt"))


def emit_replay_record(event: str, **fields: Any) -> None:
    """Recovery instants for the serve trace track (`ia trace`)."""
    obs_trace.emit_record({"event": event, **fields})
