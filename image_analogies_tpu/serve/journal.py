"""Write-ahead request journal — the serving plane's durability log.

Every admitted request is recorded BEFORE it enters the queue, then each
state transition is appended as it happens:

    admitted -> dispatched -> done(response digest)
                           -> rejected(reason)
                           -> poisoned

so a process death at any instant leaves a journal from which
:meth:`Server.recover` can reconstruct exactly what was owed to whom:

- ``done`` entries short-circuit duplicate submissions with the recorded
  response (exactly-once from the client's view — the response planes
  are spilled alongside the log);
- incomplete entries are re-enqueued in original admit order;
- entries whose ``dispatched`` count exhausted ``crash_requeues`` are
  marked ``poisoned`` and permanently shed with ``Rejected("poison")``
  so a poison request cannot crash the fleet twice.

Format: JSONL *segments* (``segment-%06d.jsonl``) where every line
carries a ``seal`` — sha256 over the canonical JSON of the rest of the
record — reusing ``utils/checkpoint.py``'s seal/quarantine pattern: a
torn tail or flipped bit fails the seal, the valid prefix is kept, and
the damaged segment is quarantined as ``.corrupt`` (evidence, never
deleted) instead of poisoning replay.  Appends are fsync'd by default
(``journal_fsync=False`` trades the sync for speed in tests).

Payload planes are spilled next to the log as checksummed ``.npz``
(``payloads/<idem>.npz`` inputs, ``payloads/<idem>.resp.npz`` the
recorded response), so the journal lines stay small and replay can both
re-run an incomplete request and answer a duplicate of a finished one.

Idempotency key: client-supplied, or ``sha1(batch key x payload
digest)`` — deterministic across processes, so a client retry after a
restart dedupes with no client-side cooperation.  Keys name files under
the journal directory, so client-supplied keys are confined to
``[A-Za-z0-9_-]{1,64}`` (:func:`valid_idem`), enforced at the HTTP and
``Server.submit`` boundaries and again by every path builder here —
a traversal-shaped key can never become a filesystem path.

Zero-cost when disabled: the server holds ``journal=None`` unless
``ServeConfig.journal_dir`` is set; no call site touches this module on
the disabled path (locked by tests/test_journal.py's poisoned-import
test).  Like the rest of serve/, this module is jax-free (grep-locked).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import re
import threading
import time
import zipfile
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from image_analogies_tpu import chaos
from image_analogies_tpu.config import AnalogyParams
from image_analogies_tpu.obs import metrics as obs_metrics
from image_analogies_tpu.obs import trace as obs_trace
from image_analogies_tpu.utils import checkpoint as ckpt

_SEGMENT_FMT = "segment-%06d.jsonl"
_LOCK_NAME = "journal.lock"
# State transitions (folded by replay) plus two attribution ops that
# ride alongside without shaping replay: ``cost`` (the per-request cost
# vector from obs/ledger.py) and ``decision`` (a control-plane verdict —
# degrade, shed, spill, poison, dedupe...).  `ia why` merges all of them
# into one causal chain.
_OPS = ("admitted", "dispatched", "done", "rejected", "poisoned",
        "cost", "decision")
_IDEM_RE = re.compile(r"[A-Za-z0-9_-]{1,64}\Z")


class JournalLocked(RuntimeError):
    """The journal directory is owned by a LIVE foreign process.  Raised
    by :meth:`RequestJournal.open` so two live workers can never append
    to one journal — the single-writer invariant every replay guarantee
    rests on.  A dead owner's lock is swept, never raises."""

    def __init__(self, path: str, pid: int):
        super().__init__(
            f"journal at {path} is owned by live pid {pid}")
        self.path = path
        self.pid = pid


def valid_idem(idem: str) -> bool:
    """True when *idem* is safe to embed in journal lines and spill
    filenames.  Keys name files under the journal directory, so
    anything outside ``[A-Za-z0-9_-]{1,64}`` (path separators, dots,
    NULs, over-long strings) is refused at the submit/HTTP boundary —
    derived keys (sha1 hex) match by construction."""
    return isinstance(idem, str) and bool(_IDEM_RE.fullmatch(idem))


def idem_key(key_str: str, b: np.ndarray) -> str:
    """Idempotency key for a request: sha1 over the batch key (params
    digest x shape buckets x exemplar content) and the target plane's
    content.  Deterministic across processes — the property that makes a
    client retry after a server restart dedupe by construction."""
    b = np.ascontiguousarray(b)
    h = hashlib.sha1()
    h.update(key_str.encode())
    h.update(repr((b.shape, str(b.dtype))).encode())
    h.update(b.tobytes())
    return h.hexdigest()[:16]


def _seal(record: Dict[str, Any]) -> str:
    canonical = json.dumps(record, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(canonical.encode()).hexdigest()[:32]


def _plane_checksum(*arrays: np.ndarray) -> str:
    """Same recipe as checkpoint._payload_checksum: shape + dtype + bytes
    under one sha256, stored inside the npz, checked on load."""
    h = hashlib.sha256()
    for arr in arrays:
        arr = np.ascontiguousarray(arr)
        h.update(repr((arr.shape, str(arr.dtype))).encode())
        h.update(arr.tobytes())
    return h.hexdigest()[:32]


def response_digest(bp: np.ndarray, bp_y: np.ndarray) -> str:
    """Content digest of a response's output planes — what the ``done``
    journal line records, so an operator can audit that a replayed run
    reproduced the same bytes."""
    return _plane_checksum(bp, bp_y)


@dataclasses.dataclass
class JournalEntry:
    """Replay-time view of one idempotency key's transition history."""

    idem: str
    admit: Dict[str, Any]
    dispatched: int = 0
    done: Optional[Dict[str, Any]] = None
    rejected: Optional[str] = None
    poisoned: bool = False

    @property
    def complete(self) -> bool:
        return self.done is not None or self.rejected is not None \
            or self.poisoned


@dataclasses.dataclass
class Replay:
    """Result of :meth:`RequestJournal.replay`."""

    entries: Dict[str, JournalEntry]      # idem -> history
    order: List[str]                      # idems in original admit order
    quarantined: int = 0                  # segments moved to .corrupt
    lines: int = 0                        # valid sealed lines read
    # cost/decision attribution lines per idem — not state, but compact
    # preserves them for still-incomplete work so `ia why` survives it.
    aux: Dict[str, List[Dict[str, Any]]] = dataclasses.field(
        default_factory=dict)

    @property
    def incomplete(self) -> List[JournalEntry]:
        return [self.entries[i] for i in self.order
                if not self.entries[i].complete]


class RequestJournal:
    """One directory of sealed JSONL segments + spilled payloads.

    Thread-safe: appends from the admission thread and every worker
    serialize on one lock (a request journal is an ordering witness —
    interleaved partial lines would defeat it)."""

    def __init__(self, path: str, fsync: bool = True):
        self.path = path
        self.fsync = fsync
        self._lock = threading.Lock()
        self._fh = None
        self._segment = 0
        # In-memory dedupe state, rebuilt by replay() and kept current by
        # record_done/record_poisoned during the process lifetime.
        self._done: Dict[str, Any] = {}       # idem -> Response | None(lazy)
        self._poisoned: set = set()
        os.makedirs(self._payload_dir, exist_ok=True)

    # -- paths -------------------------------------------------------------

    @property
    def _payload_dir(self) -> str:
        return os.path.join(self.path, "payloads")

    def _segment_path(self, index: int) -> str:
        return os.path.join(self.path, _SEGMENT_FMT % index)

    def _segments(self) -> List[str]:
        try:
            names = sorted(n for n in os.listdir(self.path)
                           if n.startswith("segment-")
                           and n.endswith(".jsonl"))
        except OSError:
            return []
        return [os.path.join(self.path, n) for n in names]

    @property
    def _lock_path(self) -> str:
        return os.path.join(self.path, _LOCK_NAME)

    def payload_path(self, idem: str) -> str:
        # Backstop behind the boundary validation in Server.submit /
        # http.py: an unvalidated key must fail loudly here, never
        # become a path outside the payload dir.
        if not valid_idem(idem):
            raise ValueError(f"unsafe idempotency key: {idem!r}")
        return os.path.join(self._payload_dir, f"{idem}.npz")

    def response_path(self, idem: str) -> str:
        if not valid_idem(idem):
            raise ValueError(f"unsafe idempotency key: {idem!r}")
        return os.path.join(self._payload_dir, f"{idem}.resp.npz")

    @staticmethod
    def _spill_tmp(final_path: str) -> str:
        """Per-writer temp name for a spill headed to *final_path* (the
        .npz suffix keeps np.savez from appending its own)."""
        return (f"{final_path}.{os.getpid()}"
                f".{threading.get_ident()}.tmp.npz")

    # -- append side -------------------------------------------------------

    def open(self) -> "RequestJournal":
        """Open a fresh segment for appends (one per server incarnation —
        a restart never appends into a segment a dead process may have
        torn)."""
        with self._lock:
            if self._fh is not None:
                return self
            # Single-writer gate: a lock held by a LIVE foreign process
            # refuses this opener (two appenders would tear the replay
            # history); a dead owner's lock is stale and active_pid()
            # sweeps it — the real-SIGKILL handoff path, where the
            # replacement inherits the corpse's directory.
            owner = self.active_pid()
            if owner is not None and owner != os.getpid():
                raise JournalLocked(self.path, owner)
            segs = self._segments()
            last = int(os.path.basename(segs[-1])[8:-6]) if segs else 0
            self._segment = last + 1
            self._fh = open(self._segment_path(self._segment), "a")
            # Advisory single-writer lock: marks the journal active so
            # compact() refuses to delete segments out from under a
            # live appender.  Released by close(); a crash leaves it
            # behind, so readers liveness-check the recorded pid.
            with open(self._lock_path, "w") as lf:
                lf.write(str(os.getpid()))
            # Sweep spill temp files orphaned by a crashed incarnation
            # (each writer uses a unique temp name, so these can only
            # be dead — the atomic os.replace either happened or not).
            try:
                for name in os.listdir(self._payload_dir):
                    if name.endswith(".tmp.npz"):
                        try:
                            os.remove(os.path.join(self._payload_dir,
                                                   name))
                        except OSError:
                            pass
            except OSError:
                pass
        return self

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                finally:
                    self._fh = None
                try:
                    os.remove(self._lock_path)
                except OSError:
                    pass

    def active_pid(self) -> Optional[int]:
        """PID of a process currently appending to this journal, or
        None.  A lock file whose owner is dead is stale — removed here
        so a crashed incarnation doesn't block compaction forever."""
        if self._fh is not None:
            return os.getpid()
        try:
            with open(self._lock_path) as f:
                pid = int(f.read().strip() or "0")
        except (OSError, ValueError):
            return None
        if pid <= 0:
            return None
        try:
            os.kill(pid, 0)
        except ProcessLookupError:
            # Stale: the recorded owner is a corpse.  Sweep the lock
            # (counted — the subprocess handoff drill reconciles this
            # against the real SIGKILL it delivered).
            try:
                os.remove(self._lock_path)
                obs_metrics.inc("serve.journal.stale_lock_swept")
            except OSError:
                pass
            return None
        except PermissionError:
            pass  # exists, owned by another user: still alive
        return pid

    def _append(self, record: Dict[str, Any]) -> None:
        # The chaos plane's process-death site: a ProcessDeath raised
        # here models the process dying with this transition unrecorded —
        # exactly the torn-history case replay must absorb.
        chaos.site("serve.journal", op=record.get("op", "?"))
        # Wall-clock stamp on every line so `ia why` can merge-order
        # events across worker journals and the router's DecisionLog
        # (pre-stamp journals sort by file order, which is still causal
        # within one journal).
        record.setdefault("ts", round(time.time(), 6))
        line = json.dumps({"seal": _seal(record), **record},
                          sort_keys=True, separators=(",", ":"))
        with self._lock:
            if self._fh is None:  # journal closed (shutdown race): drop
                return
            self._fh.write(line + "\n")
            self._fh.flush()
            if self.fsync:
                os.fsync(self._fh.fileno())
        obs_metrics.inc(f"serve.journal.{record['op']}")

    def record_admit(self, idem: str, request_id: int, a: np.ndarray,
                     ap: np.ndarray, b: np.ndarray, params: AnalogyParams,
                     deadline_s: Optional[float], key: str) -> None:
        """WAL step: spill the payload, then the admit line.  Runs BEFORE
        the queue sees the request — an admitted request with no journal
        line cannot exist, only the harmless converse."""
        ppath = self.payload_path(idem)
        if not os.path.exists(ppath):  # client retries reuse the spill
            # Unique temp per writer: a retry racing the original (both
            # past the exists check) must not interleave np.savez into
            # one file — each writes its own, os.replace is atomic,
            # last-one-wins lands a self-consistent spill either way.
            tmp = self._spill_tmp(ppath)
            np.savez(tmp, a=a, ap=ap, b=b,
                     params=json.dumps(dataclasses.asdict(params),
                                       sort_keys=True),
                     checksum=_plane_checksum(a, ap, b))
            os.replace(tmp, ppath)
        self._append({"op": "admitted", "idem": idem, "rid": request_id,
                      "key": key, "deadline_s": deadline_s})

    def record_dispatched(self, idem: str) -> None:
        self._append({"op": "dispatched", "idem": idem})

    def record_done(self, idem: str, resp: Any) -> None:
        """Spill the response, then the done line, then remember it for
        in-process dedupe.  Callers sequence this BEFORE resolving the
        client future: once a client can observe an answer, the journal
        already guarantees every future duplicate gets the same one."""
        rpath = self.response_path(idem)
        if not os.path.exists(rpath):
            tmp = self._spill_tmp(rpath)
            np.savez(tmp, bp=resp.bp, bp_y=resp.bp_y,
                     stats=json.dumps(resp.stats, default=str),
                     degraded=json.dumps(resp.degraded),
                     request_id=resp.request_id,
                     checksum=_plane_checksum(resp.bp, resp.bp_y))
            os.replace(tmp, rpath)
        self._append({"op": "done", "idem": idem,
                      "rid": resp.request_id,
                      "response_digest": response_digest(resp.bp,
                                                         resp.bp_y)})
        with self._lock:
            self._done[idem] = resp

    def record_rejected(self, idem: str, reason: str) -> None:
        self._append({"op": "rejected", "idem": idem, "reason": reason})

    def record_poisoned(self, idem: str) -> None:
        self._append({"op": "poisoned", "idem": idem})
        with self._lock:
            self._poisoned.add(idem)

    def record_cost(self, idem: str, vec: Dict[str, Any]) -> None:
        """Persist the per-request cost vector (obs/ledger.py) beside
        the request's own transitions — `ia why`'s timing evidence."""
        self._append({"op": "cost", "idem": idem, "vec": vec})

    def record_decision(self, idem: str, site: str, verdict: str,
                        cause: Optional[str] = None,
                        **extra: Any) -> None:
        """Persist one control-plane verdict for this key.  Callers
        pair this with obs/ledger.emit_decision (counters + trace);
        this line is the durable half `ia why` replays."""
        rec = {"op": "decision", "idem": idem, "site": site,
               "verdict": verdict}
        if cause is not None:
            rec["cause"] = cause
        if extra:
            rec.update(extra)
        self._append(rec)

    # -- dedupe / poison lookups (request path) ----------------------------

    def is_poisoned(self, idem: str) -> bool:
        with self._lock:
            return idem in self._poisoned

    def lookup_done(self, idem: str) -> Optional[Any]:
        """Recorded Response for a finished key, or None.  A replayed
        ``done`` is loaded lazily from its spill on first hit; a spill
        that fails its checksum is quarantined and the key degrades to
        not-done (the engine is deterministic, so a re-run still answers
        with the same bytes — exactly-once is preserved)."""
        with self._lock:
            if idem not in self._done:
                return None
            resp = self._done[idem]
        if resp is not None:
            return resp
        resp = self._load_response(idem)
        with self._lock:
            if resp is None:
                self._done.pop(idem, None)
            else:
                self._done[idem] = resp
        return resp

    def _load_response(self, idem: str) -> Optional[Any]:
        from image_analogies_tpu.serve.types import Response

        rpath = self.response_path(idem)
        if not os.path.exists(rpath):
            return None
        try:
            with np.load(rpath) as z:
                bp = z["bp"].astype(np.float32)
                bp_y = z["bp_y"].astype(np.float32)
                want = str(z["checksum"])
                if want != _plane_checksum(z["bp"], z["bp_y"]):
                    raise ValueError(
                        f"response payload checksum mismatch at {rpath}")
                stats = json.loads(str(z["stats"]))
                degraded = json.loads(str(z["degraded"]))
                rid = int(z["request_id"])
        except (zipfile.BadZipFile, OSError, ValueError, KeyError,
                EOFError):
            ckpt.quarantine(rpath, counter="serve.journal.quarantined",
                            event="journal_quarantined")
            return None
        return Response(request_id=rid, bp=bp, bp_y=bp_y, stats=stats,
                        batch_size=1, queue_ms=0.0, dispatch_ms=0.0,
                        total_ms=0.0, degraded=degraded)

    def load_payload(self, idem: str):
        """(a, ap, b, params, deadline_s-less admit payload) for replay,
        or None when the spill is missing/damaged (quarantined — the
        request cannot be re-run, only reported)."""
        ppath = self.payload_path(idem)
        if not os.path.exists(ppath):
            return None
        try:
            with np.load(ppath) as z:
                a = z["a"].astype(np.float32)
                ap = z["ap"].astype(np.float32)
                b = z["b"].astype(np.float32)
                want = str(z["checksum"])
                if want != _plane_checksum(z["a"], z["ap"], z["b"]):
                    raise ValueError(
                        f"journal payload checksum mismatch at {ppath}")
                params = AnalogyParams(**json.loads(str(z["params"])))
        except (zipfile.BadZipFile, OSError, ValueError, KeyError,
                EOFError, TypeError):
            ckpt.quarantine(ppath, counter="serve.journal.quarantined",
                            event="journal_quarantined")
            return None
        return a, ap, b, params

    # -- replay side -------------------------------------------------------

    def _read_segment(self, path: str) -> List[Dict[str, Any]]:
        """Sealed lines of one segment.  On the first unparseable or
        seal-failing line the valid prefix is kept, the damaged file is
        quarantined as ``.corrupt``, and the prefix is rewritten in its
        place so the next restart replays cleanly (the quarantined bytes
        stay as evidence, same contract as checkpoint quarantine)."""
        records: List[Dict[str, Any]] = []
        good_lines: List[str] = []
        damaged = False
        with open(path) as f:
            for line in f:
                stripped = line.strip()
                if not stripped:
                    continue
                try:
                    rec = json.loads(stripped)
                    seal = rec.pop("seal")
                    if seal != _seal(rec) or rec.get("op") not in _OPS:
                        raise ValueError("bad seal")
                except (json.JSONDecodeError, KeyError, ValueError,
                        AttributeError, TypeError):
                    damaged = True
                    break
                records.append(rec)
                good_lines.append(stripped)
        if damaged:
            ckpt.quarantine(path, counter="serve.journal.quarantined",
                            event="journal_quarantined")
            with open(path + ".tmp", "w") as f:
                for rec_line in good_lines:
                    f.write(rec_line + "\n")
                f.flush()
                os.fsync(f.fileno())
            os.replace(path + ".tmp", path)
        return records

    def replay(self) -> Replay:
        """Fold every segment's transitions into per-key histories.

        Duplicate transitions are idempotent folds (two ``done`` lines
        for one key — e.g. a retry that raced a death — count once); the
        admit ORDER is the original EDF submission order and is what
        recovery re-enqueues by."""
        entries: Dict[str, JournalEntry] = {}
        order: List[str] = []
        aux: Dict[str, List[Dict[str, Any]]] = {}
        quarantined_before = _corrupt_count(self.path)
        lines = 0
        for seg in self._segments():
            for rec in self._read_segment(seg):
                lines += 1
                idem = str(rec.get("idem"))
                if not valid_idem(idem):
                    # Journal lines only ever carry boundary-validated
                    # keys; an unsafe idem means a handcrafted file —
                    # skip it so replay never turns it into a path.
                    continue
                op = rec["op"]
                if op in ("cost", "decision"):
                    # Attribution, not state: collected for compact but
                    # never folded — a cost line alone must not
                    # synthesize a replayable entry.
                    aux.setdefault(idem, []).append(rec)
                    continue
                if op == "admitted":
                    if idem not in entries:
                        entries[idem] = JournalEntry(idem=idem, admit=rec)
                        order.append(idem)
                    continue
                ent = entries.get(idem)
                if ent is None:
                    # transition without an admit (its admit line was in
                    # a torn prefix): synthesize so done/poisoned dedupe
                    # still works; it can never be re-enqueued (no
                    # payload reference is trusted without an admit).
                    ent = JournalEntry(idem=idem, admit={},
                                       rejected="orphaned")
                    entries[idem] = ent
                if op == "dispatched":
                    ent.dispatched += 1
                elif op == "done":
                    ent.done = rec
                elif op == "rejected":
                    ent.rejected = str(rec.get("reason", "rejected"))
                elif op == "poisoned":
                    ent.poisoned = True
        with self._lock:
            for ent in entries.values():
                if ent.done is not None:
                    self._done.setdefault(ent.idem, None)  # lazy load
                if ent.poisoned:
                    self._poisoned.add(ent.idem)
        return Replay(entries=entries, order=order,
                      quarantined=_corrupt_count(self.path)
                      - quarantined_before,
                      lines=lines, aux=aux)

    def history(self, idem: str) -> List[Dict[str, Any]]:
        """Every sealed line for *idem* (all ops, including cost and
        decision attribution) in file order — `ia why`'s raw evidence
        from one journal."""
        out: List[Dict[str, Any]] = []
        for seg in self._segments():
            for rec in self._read_segment(seg):
                if str(rec.get("idem")) == idem:
                    out.append(rec)
        return out

    # -- tooling (`ia journal`) --------------------------------------------

    def inspect(self) -> Dict[str, Any]:
        """Read-only summary for ``ia journal inspect``."""
        rep = self.replay()
        states: Dict[str, int] = {}
        for ent in rep.entries.values():
            if ent.poisoned:
                st = "poisoned"
            elif ent.done is not None:
                st = "done"
            elif ent.rejected is not None:
                st = "rejected"
            elif ent.dispatched:
                st = "dispatched"
            else:
                st = "admitted"
            states[st] = states.get(st, 0) + 1
        return {
            "path": self.path,
            "segments": len(self._segments()),
            "corrupt_segments": _corrupt_count(self.path),
            "lines": rep.lines,
            "requests": len(rep.entries),
            "states": states,
            "incomplete": [e.idem for e in rep.incomplete],
            "poisoned": sorted(e.idem for e in rep.entries.values()
                               if e.poisoned),
        }

    def compact(self) -> Dict[str, Any]:
        """Rewrite the journal to its minimal equivalent: one fresh
        segment holding each key's FINAL state (admit lines only for
        still-incomplete work), dropping intermediate transitions and the
        input spills of finished requests.  Response spills are kept —
        they are what dedupe answers with.  ``.corrupt`` files are never
        touched.

        Refuses while the journal is active (``journal.lock`` held by a
        live pid): a live appender holds the newest segment open, so
        deleting it would send its fsync'd appends to an unlinked file
        and silently lose every transition after the compaction."""
        owner = self.active_pid()
        if owner is not None:
            raise RuntimeError(
                f"journal at {self.path} is active (pid {owner}); "
                "stop the server before compacting")
        rep = self.replay()
        before = {"segments": len(self._segments()), "lines": rep.lines}
        tmp = os.path.join(self.path, "compact.tmp")
        kept = 0
        with open(tmp, "w") as f:
            def put(rec: Dict[str, Any]) -> None:
                nonlocal kept
                f.write(json.dumps({"seal": _seal(rec), **rec},
                                   sort_keys=True,
                                   separators=(",", ":")) + "\n")
                kept += 1

            for idem in rep.order:
                ent = rep.entries[idem]
                if not ent.complete:
                    put(ent.admit)
                    for _ in range(ent.dispatched):
                        put({"op": "dispatched", "idem": idem})
                    # Keep attribution for still-open work so a post-
                    # compact `ia why` sees the partial chain; finished
                    # keys drop theirs with the other intermediates.
                    for rec in rep.aux.get(idem, ()):
                        put(rec)
            for idem, ent in sorted(rep.entries.items()):
                if ent.poisoned:
                    put({"op": "poisoned", "idem": idem})
                elif ent.done is not None:
                    put(ent.done)
            f.flush()
            os.fsync(f.fileno())
        segs = self._segments()
        last = int(os.path.basename(segs[-1])[8:-6]) if segs else 0
        os.replace(tmp, self._segment_path(last + 1))
        for seg in segs:
            os.remove(seg)
        for ent in rep.entries.values():
            if ent.complete:
                try:
                    os.remove(self.payload_path(ent.idem))
                except OSError:
                    pass
        return {**before, "after": {"segments": 1, "lines": kept},
                "dropped_lines": rep.lines - kept}

    def stats(self) -> Dict[str, int]:
        """Live journal counters (from the active obs registry) — what
        /healthz and the selftest summary surface."""
        snap = obs_metrics.snapshot() or {}
        counters = snap.get("counters", {})
        return {k.split("serve.journal.", 1)[1]: int(v)
                for k, v in counters.items()
                if k.startswith("serve.journal.")}

    def info(self) -> Dict[str, Any]:
        """Ownership facts for /healthz: which pid holds the advisory
        lock and which segment this incarnation appends to — what a
        router (or operator) checks before handing the directory to a
        replacement worker."""
        return {"lock_pid": self.active_pid(), "segment": self._segment}


def autocompact(path: str, min_segments: int = 2
                ) -> Optional[Dict[str, Any]]:
    """Offline compaction of a DEAD worker's journal dir, called by
    ``Fleet._replace`` between the corpse and the replacement's
    ``open()`` — the one window in a worker slot's life when nobody
    holds the directory, so multi-hour soaks don't grow segments
    unboundedly (live ``compact()`` refuses by design).

    A corpse with fewer than ``min_segments`` segments is already
    bounded and is SKIPPED without touching the directory — the gate
    is a bare listdir, so a first-kill handoff keeps its historic
    evidence intact: the stale foreign lock is still there for the
    replacement's ``open()`` to sweep, and segment numbering stays
    contiguous past the corpse's.

    Refusal-safe: if the journal turns out to be held by a live owner
    (or the rewrite hits an I/O error), the replacement simply
    inherits the uncompacted journal — recovery replay does not depend
    on compaction.  Returns the compaction summary, or None when
    skipped/refused; counters ``serve.journal.autocompact`` /
    ``.autocompact_skipped`` / ``.autocompact_refused`` make every
    outcome visible."""
    if not os.path.isdir(path):
        return None
    try:
        segments = [n for n in os.listdir(path)
                    if n.startswith("segment-") and n.endswith(".jsonl")]
    except OSError:
        return None
    if len(segments) < min_segments:
        obs_metrics.inc("serve.journal.autocompact_skipped")
        return None
    try:
        out = RequestJournal(path).compact()
    except (RuntimeError, OSError):
        obs_metrics.inc("serve.journal.autocompact_refused")
        return None
    obs_metrics.inc("serve.journal.autocompact")
    return out


class DecisionLog:
    """Sealed JSONL decision trail for verdicts rendered OUTSIDE any
    worker journal — the router/fleet control plane (spill off home,
    death, crash-loop gate, handoff re-chain).  Worker journals are
    single-writer per process, so cross-process verdicts land here
    instead, at the fleet journal root, and `ia why` merges both.

    Unlike :meth:`RequestJournal.record_decision` (persist-only, paired
    with obs/ledger.emit_decision by the caller), :meth:`record` is the
    whole funnel for its sites: counter + trace record + sealed line."""

    NAME = "decisions.jsonl"

    def __init__(self, path: str, fsync: bool = False):
        self.path = path
        self.fsync = fsync
        self._lock = threading.Lock()
        self._fh = None

    def record(self, idem: Optional[str], site: str, verdict: str,
               cause: Optional[str] = None, **extra: Any) -> None:
        rec: Dict[str, Any] = {"op": "decision", "site": site,
                               "verdict": verdict,
                               "ts": round(time.time(), 6)}
        if idem is not None:
            rec["idem"] = idem
        if cause is not None:
            rec["cause"] = cause
        if extra:
            rec.update(extra)
        line = json.dumps({"seal": _seal(rec), **rec},
                          sort_keys=True, separators=(",", ":"))
        with self._lock:
            if self._fh is None:
                os.makedirs(os.path.dirname(self.path) or ".",
                            exist_ok=True)
                self._fh = open(self.path, "a")
            self._fh.write(line + "\n")
            self._fh.flush()
            if self.fsync:
                os.fsync(self._fh.fileno())
        obs_metrics.inc(f"serve.decision.{verdict}")
        trace_rec = {"event": "serve_decision", "site": site,
                     "verdict": verdict}
        if cause is not None:
            trace_rec["cause"] = cause
        if idem is not None:
            trace_rec["idem"] = idem
        obs_trace.emit_record(trace_rec)

    def read(self, idem: Optional[str] = None) -> List[Dict[str, Any]]:
        """Sealed decision lines in file order; a torn tail or flipped
        bit drops that line only (evidence log, not replay state)."""
        out: List[Dict[str, Any]] = []
        try:
            with open(self.path) as f:
                lines = f.readlines()
        except OSError:
            return out
        for line in lines:
            stripped = line.strip()
            if not stripped:
                continue
            try:
                rec = json.loads(stripped)
                seal = rec.pop("seal")
                if seal != _seal(rec) or rec.get("op") != "decision":
                    raise ValueError("bad seal")
            except (json.JSONDecodeError, KeyError, ValueError,
                    AttributeError, TypeError):
                obs_metrics.inc("serve.decision_log.skipped")
                continue
            if idem is None or rec.get("idem") == idem:
                out.append(rec)
        return out

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                finally:
                    self._fh = None


# -- request forensics (`ia why`) ---------------------------------------------

def _journal_dirs(root: str) -> List[Tuple[str, str]]:
    """``(label, path)`` of every journal under *root*: either *root*
    itself (single-server layout, segments at top level) or each child
    directory holding segments (fleet layout, one subdir per worker)."""

    def has_segments(path: str) -> bool:
        try:
            return any(n.startswith("segment-") and n.endswith(".jsonl")
                       for n in os.listdir(path))
        except OSError:
            return False

    if has_segments(root):
        return [(os.path.basename(os.path.normpath(root)) or "journal",
                 root)]
    out: List[Tuple[str, str]] = []
    try:
        names = sorted(os.listdir(root))
    except OSError:
        return out
    for name in names:
        sub = os.path.join(root, name)
        if os.path.isdir(sub) and has_segments(sub):
            out.append((name, sub))
    return out


def _chain_step(e: Dict[str, Any]) -> str:
    op = e.get("op")
    if op == "admitted":
        return f"admitted[{e.get('worker', '?')}]"
    if op == "dispatched":
        return "dispatched"
    if op == "done":
        return "done"
    if op == "poisoned":
        return "poisoned"
    if op == "rejected":
        return f"rejected({e.get('reason', '?')})"
    if op == "cost":
        vec = e.get("vec") or {}
        q = float(vec.get("queue_ms") or 0.0)
        d = float(vec.get("dispatch_ms") or 0.0)
        step = f"queued {q:.0f}ms, ran {d:.0f}ms"
        lanes = int(vec.get("lanes") or 1)
        if lanes > 1:
            step += f" ({lanes} lanes)"
        retries = int(vec.get("retries") or 0)
        if retries:
            step += f", {retries} retries"
        return step
    if op == "decision":
        details = []
        if e.get("cause"):
            details.append(str(e["cause"]))
        for key in ("levels", "home", "to", "worker_id", "pid"):
            if e.get(key) is not None:
                details.append(f"{key}={e[key]}")
        verdict = e.get("verdict", "?")
        return f"{verdict}({', '.join(details)})" if details else verdict
    return str(op)


def reconstruct(idem: str, root: str) -> Dict[str, Any]:
    """Replay journal + ledger + decision evidence for one idempotency
    key into a single ordered causal chain — the `ia why` engine.

    *root* is either one journal directory (segments at top level) or a
    fleet journal root (per-worker subdirectories plus the router's
    ``decisions.jsonl``).  Events merge across sources ordered by their
    ``ts`` stamp (stable on ties; stamp-less legacy lines keep file
    order at the front)."""
    events: List[Dict[str, Any]] = []
    workers: List[str] = []
    for wid, jdir in _journal_dirs(root):
        jr = RequestJournal(jdir)
        hist = jr.history(idem)
        if hist:
            workers.append(wid)
        for rec in hist:
            events.append(dict(rec, worker=wid))
    dpath = os.path.join(root, DecisionLog.NAME)
    if os.path.exists(dpath):
        for rec in DecisionLog(dpath).read(idem):
            events.append(dict(rec, worker=str(rec.get("site",
                                                       "router"))))
    for i, e in enumerate(events):
        e["_seq"] = i
    events.sort(key=lambda e: (
        float(e["ts"]) if isinstance(e.get("ts"), (int, float))
        else float("-inf"), e["_seq"]))
    for e in events:
        e.pop("_seq", None)
    tenant = None
    traces = []
    for e in events:
        vec = e.get("vec") if e.get("op") == "cost" else None
        if tenant is None and isinstance(vec, dict) and vec.get("tenant"):
            tenant = vec["tenant"]
        for t in (e.get("trace"),
                  (vec or {}).get("trace") if isinstance(vec, dict)
                  else None):
            if t and t not in traces:
                traces.append(t)
    return {"idem": idem, "found": bool(events), "root": root,
            "workers": workers, "tenant": tenant, "traces": traces,
            "events": events,
            "chain": [_chain_step(e) for e in events]}


def render_why(doc: Dict[str, Any]) -> str:
    """Human-readable rendering of :func:`reconstruct`'s document."""
    idem = doc.get("idem", "?")
    if not doc.get("found"):
        return (f"ia why {idem}: no journal, ledger, or decision "
                f"records under {doc.get('root', '?')}\n")
    lines = [f"ia why {idem}"]
    if doc.get("tenant"):
        lines.append(f"  tenant: {doc['tenant']}")
    if doc.get("traces"):
        lines.append(f"  traces: {', '.join(doc['traces'])}")
    if doc.get("workers"):
        lines.append(f"  journals: {', '.join(doc['workers'])}")
    t0 = None
    for e in doc.get("events", []):
        ts = e.get("ts")
        if isinstance(ts, (int, float)):
            if t0 is None:
                t0 = ts
            stamp = f"+{ts - t0:8.3f}s"
        else:
            stamp = " " * 10
        lines.append(f"  {stamp} [{e.get('worker', '?'):>10}] "
                     f"{_chain_step(e)}")
    lines.append("  chain: " + " → ".join(doc.get("chain", [])))
    return "\n".join(lines) + "\n"


def _corrupt_count(path: str) -> int:
    try:
        names = os.listdir(path) + os.listdir(os.path.join(path,
                                                           "payloads"))
    except OSError:
        return 0
    return sum(1 for n in names if n.endswith(".corrupt"))


def emit_replay_record(event: str, **fields: Any) -> None:
    """Recovery instants for the serve trace track (`ia trace`)."""
    obs_trace.emit_record({"event": event, **fields})
