"""Core serving datatypes.

Host-side only: numpy planes in, numpy planes out.  The engine types
(`AnalogyParams`, `AnalogyResult`) are reused as-is so a served request
runs the exact code path a CLI run does — bit-identical outputs are an
acceptance criterion, not an aspiration.
"""

from __future__ import annotations

import dataclasses
import time
from concurrent.futures import Future
from typing import Any, Dict, Optional, Tuple

import numpy as np

from image_analogies_tpu.config import AnalogyParams
from image_analogies_tpu.serve.policy import ControlPolicy, QosPolicy


class Rejected(RuntimeError):
    """Admission control refused the request (no hang, no unbounded queue).

    ``reason`` is machine-readable: ``"queue_full"`` when the bounded queue
    is at depth, ``"shutting_down"`` once drain has begun,
    ``"breaker_open"`` when admission sheds because the dispatch circuit
    breaker is open (one hop before the queue — see serve/breaker.py),
    ``"circuit_open"`` when the breaker trips between an accepted
    request's admission and its dispatch,
    ``"worker_crash"`` when a crashed worker exhausted the requeue budget,
    ``"quota"`` when the tenant's per-style admission token bucket is
    empty (serve/policy.py — the viral style degrades itself, not the
    fleet; like ``"poison"`` this is a verdict about the REQUEST, so
    the router never spills it to another worker),
    ``"poison"`` when the request's idempotency key was previously marked
    poisoned in the write-ahead journal (it exhausted ``crash_requeues``
    once already — resubmission sheds instantly, before the breaker, so a
    known-poison key can neither re-crash the fleet nor trip the breaker).
    """

    def __init__(self, reason: str):
        super().__init__(f"request rejected: {reason}")
        self.reason = reason


class DeadlineExceeded(RuntimeError):
    """Deadline expired before dispatch; the request was cancelled, never
    sent to the device."""

    def __init__(self, request_id: int, late_s: float):
        super().__init__(
            f"request {request_id} deadline expired {late_s * 1e3:.1f}ms "
            "before dispatch")
        self.request_id = request_id
        self.late_s = late_s


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Scheduler knobs.  ``params`` is the default engine config; requests
    may carry their own (each distinct digest forms its own batch key)."""

    params: AnalogyParams
    queue_depth: int = 32          # admission bound; above it -> Rejected
    batch_window_ms: float = 4.0   # coalescing wait once a leader is held
    max_batch: int = 8             # requests per batched invocation
    workers: int = 2
    default_deadline_s: Optional[float] = None  # None -> no deadline
    degrade: bool = True           # False -> never degrade, only timeout
    request_retries: int = 1       # run_with_retry budget around dispatch
    warmup_sizes: Tuple[Tuple[int, int], ...] = ()  # (h, w) AOT precompile
    drain_timeout_s: float = 60.0
    # Deadline-aware batch pop: the leader is the earliest-deadline
    # request instead of the oldest, so tight-deadline traffic dispatches
    # first.  Undeadlined (or slack) requests are protected by the aging
    # bound: once the oldest waiter's queue age exceeds
    # ``ordering_age_bound_s`` it is promoted to leader regardless of
    # deadlines — EDF can reorder, never starve.
    deadline_ordering: bool = True
    ordering_age_bound_s: float = 5.0
    # Dispatch circuit breaker (serve/breaker.py): this many CONSECUTIVE
    # batch-dispatch failures trip it open (0 disables); while open,
    # requests fail fast with Rejected("circuit_open") instead of burning
    # workers, and one probe per cooldown tests recovery.
    breaker_threshold: int = 5
    breaker_cooldown_s: float = 1.0
    # Persist the learned cost-model rate into the tune store on shutdown
    # so the NEXT server seeds its degrade estimates from it
    # (provenance "store").  Off by default: tests and embedders should
    # not write store files unless asked; `ia serve` enables it.
    cost_persist: bool = False
    # A crashed worker thread (an escape below the per-request handler)
    # requeues its batch's unresolved requests up to this many times each
    # before failing them with Rejected("worker_crash") — no request is
    # ever silently lost, and a poison request can't requeue forever.
    crash_requeues: int = 1
    # SLO over deadline outcomes (obs/slo.py): target fraction of
    # deadlined requests that must meet their deadline, with fast
    # (paging) and slow (ticket) burn-rate windows.  Exported as gauges
    # and in /healthz; undeadlined traffic is not counted.
    slo_target: float = 0.99
    slo_fast_window_s: float = 60.0
    slo_slow_window_s: float = 600.0
    # Durability (serve/journal.py): when set, every request is recorded
    # in a write-ahead journal under this directory at admit time and on
    # each state transition; Server.recover() replays it on startup
    # (done-dedupe, re-enqueue, poison shed).  None (default) disables
    # the journal entirely — the request path never touches the module.
    journal_dir: Optional[str] = None
    # fsync each journal append (the durability guarantee).  Tests and
    # throughput-over-durability embedders may turn it off.
    journal_fsync: bool = True
    # Batched B-axis engine (batch/engine.py): a compatible same-key
    # batch of >= 2 TPU-backend requests dispatches as ONE engine call
    # (one compiled program, k lanes) with per-member fault isolation.
    # Incompatible batches fall back to the sequential per-member loop
    # with the reason on batch.fallback_sequential.<reason>.  Outputs
    # are bit-identical either way (the loadgen selftest gates it).
    batch_engine: bool = True
    # Tenant metering plane (obs/ledger.py): arm the per-request cost
    # ledger + space-saving heavy-hitter tracker for the server's
    # lifetime.  One style (= batcher exemplar sha1) is one tenant;
    # /tenants and `ia top --tenants` read the resulting document.
    # Disarming makes the cost path one bool check (zero-alloc,
    # tracemalloc-locked in tests) — what bench.py's
    # ledger_overhead_pct measures.
    ledger: bool = True
    ledger_capacity: int = 512     # bounded in-memory cost vectors
    tenant_k: int = 16             # heavy-hitter slots (O(K) memory)
    # Per-tenant QoS (serve/policy.py): admission token buckets fed by
    # the tenants sketch's observed cost shares + weighted-fair batch
    # pop across tenants.  None (default) disables QoS entirely — the
    # admission and pop paths are byte-identical to the pre-QoS server.
    # Round-trips through config_to_json/config_from_json for the
    # subprocess transport (serve/transport.py re-hydrates the dict).
    qos: Optional[QosPolicy] = None

    def __post_init__(self):
        if self.ledger_capacity < 1:
            raise ValueError("ledger_capacity must be >= 1")
        if self.tenant_k < 1:
            raise ValueError("tenant_k must be >= 1")
        if self.queue_depth < 1:
            raise ValueError("queue_depth must be >= 1")
        if self.max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        if self.workers < 1:
            raise ValueError("workers must be >= 1")
        if self.breaker_threshold < 0 or self.crash_requeues < 0:
            raise ValueError("breaker_threshold/crash_requeues must be >= 0")
        if self.ordering_age_bound_s < 0:
            raise ValueError("ordering_age_bound_s must be >= 0")
        if not 0.0 < self.slo_target < 1.0:
            raise ValueError("slo_target must be in (0, 1)")
        if (self.slo_fast_window_s <= 0
                or self.slo_slow_window_s < self.slo_fast_window_s):
            raise ValueError(
                "slo windows must satisfy 0 < fast <= slow")


@dataclasses.dataclass(frozen=True)
class FleetConfig:
    """Router + worker-fleet knobs (serve/fleet.py, serve/router.py).

    ``serve`` is the per-worker template; each worker gets a copy with
    ``journal_dir`` pointed at ``<journal_root>/<wid>`` (when
    ``journal_root`` is set) so a dead worker's journal directory can be
    handed, whole, to its replacement."""

    serve: ServeConfig
    size: int = 2                  # number of in-process Server workers
    journal_root: Optional[str] = None
    vnodes: int = 32               # virtual nodes per worker on the ring
    # Worker transport (serve/transport.py): "inproc" keeps today's
    # in-process Server workers; "subprocess" spawns each worker as a
    # `python -m image_analogies_tpu.serve.worker_main` child on its own
    # loopback HTTP port — same wire frames, same journal handoff, but
    # kill/replace is a real SIGKILL + re-spawn on the same journal dir.
    transport: str = "inproc"
    # Subprocess readiness handshake deadline: the child must report
    # {pid, port} over its startup pipe within this many seconds
    # (jax import + warmup + journal replay all happen before ready).
    spawn_timeout_s: float = 120.0
    # Crash-loop supervisor (transport.CrashLoopSupervisor): a worker
    # death within ``crash_loop_window_s`` of its own spawn counts as
    # RAPID; respawns after rapid deaths back off (capped jittered,
    # utils.failure.backoff_delay over backoff_s/backoff_cap_s below),
    # and ``crash_loop_threshold`` consecutive rapid deaths gate the
    # worker ("crash_loop") instead of respawning forever.  0 disables
    # the gate (respawn always).
    crash_loop_window_s: float = 1.0
    crash_loop_threshold: int = 3
    # Router<->worker hop encoding: "auto"/"binary" negotiate the IAF2
    # frame (serve/wire.py) when the worker advertises it, "json" forces
    # the list transport (the fallback both sides always speak).
    wire: str = "auto"
    health_interval_s: float = 0.25  # health-gate poll cadence
    death_checks: int = 2          # consecutive failed polls -> dead
    # Gate a worker (spill its keys to the next ring successor) when its
    # queue depth reaches this fraction of queue_depth, or any breaker
    # reports "open".
    spill_queue_frac: float = 0.8
    spill_retries: int = 3         # extra route attempts after the first
    backoff_s: float = 0.05        # utils.failure.backoff_delay base
    backoff_cap_s: float = 1.0
    # Elastic-fleet control plane (serve/control.py): when set, the
    # fleet starts at ``policy.min_workers`` (``size`` is ignored) and
    # the health daemon's reconcile pass scales it between min and max
    # under the declarative targets.  None (default) keeps the fixed
    # ``size`` fleet with no autoscaling — only the gate/death verdicts
    # (now rendered by the control plane) remain.
    policy: Optional[ControlPolicy] = None

    def __post_init__(self):
        if self.size < 1:
            raise ValueError("size must be >= 1")
        if self.vnodes < 1:
            raise ValueError("vnodes must be >= 1")
        if self.wire not in ("auto", "binary", "json"):
            raise ValueError("wire must be auto|binary|json")
        if self.transport not in ("inproc", "subprocess"):
            raise ValueError("transport must be inproc|subprocess")
        if self.spawn_timeout_s <= 0:
            raise ValueError("spawn_timeout_s must be > 0")
        if self.crash_loop_window_s < 0 or self.crash_loop_threshold < 0:
            raise ValueError(
                "crash_loop_window_s/crash_loop_threshold must be >= 0")
        if self.health_interval_s <= 0:
            raise ValueError("health_interval_s must be > 0")
        if self.death_checks < 1:
            raise ValueError("death_checks must be >= 1")
        if not 0.0 < self.spill_queue_frac <= 1.0:
            raise ValueError("spill_queue_frac must be in (0, 1]")
        if self.spill_retries < 0:
            raise ValueError("spill_retries must be >= 0")
        if self.backoff_s <= 0 or self.backoff_cap_s < self.backoff_s:
            raise ValueError(
                "backoff must satisfy 0 < backoff_s <= backoff_cap_s")


@dataclasses.dataclass
class Request:
    """One enqueued synthesis job.  ``deadline`` is absolute
    ``time.monotonic()`` seconds (None = unbounded)."""

    request_id: int
    a: np.ndarray
    ap: np.ndarray
    b: np.ndarray
    params: AnalogyParams
    key: Tuple[Any, ...]
    future: "Future[Response]"
    deadline: Optional[float] = None
    t_submit: float = dataclasses.field(default_factory=time.monotonic)
    t_dequeue: Optional[float] = None
    requeues: int = 0  # crash-containment requeue count (bounded)
    # Write-ahead-journal identity (None when the journal is disabled).
    # ``replayed`` marks a request reconstructed by Server.recover() —
    # its dispatch transitions continue the pre-restart history.
    idem: Optional[str] = None
    replayed: bool = False
    # Cross-hop trace context (obs/trace.py TRACE_KEYS): captured from
    # the submitting thread, adopted by the worker thread that runs the
    # request — worker threads are NOT the submit thread, so the trace
    # must travel in the request, not in a thread-local.
    trace: Optional[Dict[str, str]] = None
    # Encoded request size as it crossed the HTTP boundary (0 for
    # in-process submissions) — part of the cost vector (obs/ledger.py).
    wire_bytes: int = 0
    # Priority class weight (serve/policy.py PRIORITY_*): the tenant's
    # stride-scheduling share in the weighted-fair queue pop.  Carried
    # per request (X-IA-Priority over HTTP); inert unless the queue
    # runs with a QosPolicy that arms weighted_fair.
    priority: int = 2

    def __post_init__(self):
        if self.priority < 1:
            raise ValueError("priority must be >= 1")

    def remaining(self, now: Optional[float] = None) -> Optional[float]:
        if self.deadline is None:
            return None
        return self.deadline - (time.monotonic() if now is None else now)


@dataclasses.dataclass
class Response:
    """Completed request.  ``degraded`` is None for a full-fidelity run,
    else the substitutions made to meet the deadline (e.g.
    ``{"levels": 1, "patch_size": 3}``) — degraded responses are valid
    outputs, just flagged."""

    request_id: int
    bp: np.ndarray
    bp_y: np.ndarray
    stats: Dict[str, Any]
    batch_size: int
    queue_ms: float
    dispatch_ms: float
    total_ms: float
    degraded: Optional[Dict[str, Any]] = None

    @property
    def status(self) -> str:
        return "degraded" if self.degraded else "ok"
