"""Core serving datatypes.

Host-side only: numpy planes in, numpy planes out.  The engine types
(`AnalogyParams`, `AnalogyResult`) are reused as-is so a served request
runs the exact code path a CLI run does — bit-identical outputs are an
acceptance criterion, not an aspiration.
"""

from __future__ import annotations

import dataclasses
import time
from concurrent.futures import Future
from typing import Any, Dict, Optional, Tuple

import numpy as np

from image_analogies_tpu.config import AnalogyParams


class Rejected(RuntimeError):
    """Admission control refused the request (no hang, no unbounded queue).

    ``reason`` is machine-readable: ``"queue_full"`` when the bounded queue
    is at depth, ``"shutting_down"`` once drain has begun.
    """

    def __init__(self, reason: str):
        super().__init__(f"request rejected: {reason}")
        self.reason = reason


class DeadlineExceeded(RuntimeError):
    """Deadline expired before dispatch; the request was cancelled, never
    sent to the device."""

    def __init__(self, request_id: int, late_s: float):
        super().__init__(
            f"request {request_id} deadline expired {late_s * 1e3:.1f}ms "
            "before dispatch")
        self.request_id = request_id
        self.late_s = late_s


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Scheduler knobs.  ``params`` is the default engine config; requests
    may carry their own (each distinct digest forms its own batch key)."""

    params: AnalogyParams
    queue_depth: int = 32          # admission bound; above it -> Rejected
    batch_window_ms: float = 4.0   # coalescing wait once a leader is held
    max_batch: int = 8             # requests per batched invocation
    workers: int = 2
    default_deadline_s: Optional[float] = None  # None -> no deadline
    degrade: bool = True           # False -> never degrade, only timeout
    request_retries: int = 1       # run_with_retry budget around dispatch
    warmup_sizes: Tuple[Tuple[int, int], ...] = ()  # (h, w) AOT precompile
    drain_timeout_s: float = 60.0

    def __post_init__(self):
        if self.queue_depth < 1:
            raise ValueError("queue_depth must be >= 1")
        if self.max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        if self.workers < 1:
            raise ValueError("workers must be >= 1")


@dataclasses.dataclass
class Request:
    """One enqueued synthesis job.  ``deadline`` is absolute
    ``time.monotonic()`` seconds (None = unbounded)."""

    request_id: int
    a: np.ndarray
    ap: np.ndarray
    b: np.ndarray
    params: AnalogyParams
    key: Tuple[Any, ...]
    future: "Future[Response]"
    deadline: Optional[float] = None
    t_submit: float = dataclasses.field(default_factory=time.monotonic)
    t_dequeue: Optional[float] = None

    def remaining(self, now: Optional[float] = None) -> Optional[float]:
        if self.deadline is None:
            return None
        return self.deadline - (time.monotonic() if now is None else now)


@dataclasses.dataclass
class Response:
    """Completed request.  ``degraded`` is None for a full-fidelity run,
    else the substitutions made to meet the deadline (e.g.
    ``{"levels": 1, "patch_size": 3}``) — degraded responses are valid
    outputs, just flagged."""

    request_id: int
    bp: np.ndarray
    bp_y: np.ndarray
    stats: Dict[str, Any]
    batch_size: int
    queue_ms: float
    dispatch_ms: float
    total_ms: float
    degraded: Optional[Dict[str, Any]] = None

    @property
    def status(self) -> str:
        return "degraded" if self.degraded else "ok"
