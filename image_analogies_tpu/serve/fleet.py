"""Worker fleet: N workers behind one consistent-hash Router.

The management half of ROADMAP direction 1.  Each worker is a full
:class:`serve.server.Server` (own queue, batcher, breaker, journal
directory) reached through a :class:`serve.transport.Transport` — in
the same process by default, or as a real child process
(``transport="subprocess"``) on its own loopback HTTP port.  Either
way the worker has a STABLE identity ``w0..w{size-1}``: the wid owns
the ring slot and the journal directory, so a replacement worker
inherits both — affinity for untouched keys is preserved trivially and
the dead worker's write-ahead journal is recovered by whoever takes
the wid next (the handoff the PR 7 roadmap note promised).

Health gate loop (daemon thread, ``health_interval_s`` cadence):

- ``handle.health()`` raising, or reporting not-accepting / zero alive
  worker threads, counts a MISS; ``death_checks`` consecutive misses
  declare the worker dead and trigger :meth:`_replace` — kill the old
  incarnation (SIGKILL for a subprocess: the journal lock is left on
  disk holding a real foreign pid, swept by the replacement's open()),
  start a replacement on the SAME journal dir (``Server.start`` runs
  ``recover()`` before traffic: done-dedupe, admit-order replay,
  poison preserved), then hand the router every stranded in-flight
  future to re-answer by idempotency key.
- A worker that is ALIVE but replaying its journal reports
  ``recovering: true`` — liveness without readiness.  The death
  verdict is gated on liveness only: a long recovery must not look
  like a corpse and trigger a spurious second handoff.
- An open breaker or a queue at ``spill_queue_frac`` of depth GATES the
  worker: the router spills its keys to the next ring successor until
  the gate clears.  Gating is advisory and reversible; death is not.
- Every death consults the :class:`transport.CrashLoopSupervisor`:
  rapid deaths (within ``crash_loop_window_s`` of their own spawn)
  back off before respawn, and ``crash_loop_threshold`` consecutive
  rapid deaths park the slot (gate ``"crash_loop"``,
  ``router.crash_loops``) instead of burning spawns forever — an
  operator ``ungate_worker`` re-arms it.

Wire negotiation (satellite of the IAF2 work in serve/wire.py): every
router->worker hop round-trips the three request planes (and the
response planes) through the negotiated codec — IAF2 binary frames by
default, JSON lists on fallback.  In-process that rehearses the exact
encode/decode path; over the subprocess transport the same frames
actually cross the process boundary as HTTP bodies.

Host-side only: no jax imports, no jit (serve grep-lock scans this
file).  Device work happens inside each worker's engine.
"""

from __future__ import annotations

import contextlib
import dataclasses
import hashlib
import os
import threading
import time
from typing import Any, Dict, List, Optional

from image_analogies_tpu.obs import fleet as obs_fleet
from image_analogies_tpu.obs import archive as obs_archive
from image_analogies_tpu.obs import ceilings as obs_ceilings
from image_analogies_tpu.obs import ledger as obs_ledger
from image_analogies_tpu.obs import live as obs_live
from image_analogies_tpu.obs import metrics as obs_metrics
from image_analogies_tpu.obs import tenants as obs_tenants
from image_analogies_tpu.obs import timeline as obs_timeline
from image_analogies_tpu.obs import trace as obs_trace
from image_analogies_tpu.serve import journal as serve_journal
from image_analogies_tpu.serve import transport as serve_transport
# Re-exported for embedders/tests that import the handle machinery from
# its historical home (the seam moved it to serve/transport.py).
from image_analogies_tpu.serve.transport import (  # noqa: F401
    CrashLoopSupervisor, WorkerHandle, _roundtrip_iaf2, _roundtrip_json,
    _wrap_response)
from image_analogies_tpu.serve.control import ControlPlane
from image_analogies_tpu.serve.router import Router
from image_analogies_tpu.serve.types import FleetConfig, Rejected, Response


class Fleet:
    """Owns the workers, the health-gate loop, and the Router."""

    def __init__(self, cfg: FleetConfig):
        self.cfg = cfg
        self.workers: Dict[str, Any] = {}
        self.transport = serve_transport.make_transport(cfg.transport)
        self.supervisor = serve_transport.CrashLoopSupervisor(
            cfg.crash_loop_window_s, cfg.crash_loop_threshold,
            cfg.backoff_s, cfg.backoff_cap_s)
        # Router/fleet verdicts persist in a sealed DecisionLog at the
        # fleet journal root (they can't land in any worker journal —
        # single-writer, often another process); `ia why` merges it
        # with the per-worker journals into one causal chain.
        self.decisions = (serve_journal.DecisionLog(
            os.path.join(cfg.journal_root, serve_journal.DecisionLog.NAME))
            if cfg.journal_root else None)
        self.router = Router(self, vnodes=cfg.vnodes,
                             spill_retries=cfg.spill_retries,
                             backoff_s=cfg.backoff_s,
                             backoff_cap_s=cfg.backoff_cap_s,
                             decision_log=self.decisions)
        # Control plane (serve/control.py): owns the per-worker gate
        # verdict always, and the autoscaling reconcile pass when a
        # declarative policy is attached.
        self.control = ControlPlane(self, cfg.policy)
        self.handoffs: List[Dict[str, Any]] = []
        self._gates: Dict[str, str] = {}   # wid -> reason
        self._misses: Dict[str, int] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._health_thread: Optional[threading.Thread] = None
        self._started = False
        # Fleet-level obs scope (parent of every in-process worker
        # scope) + the health loop's scrape cache:
        # wid -> {scope, t, snapshot}.
        self._scope: Optional[obs_metrics.ObsScope] = None
        self._scope_exit = contextlib.ExitStack()
        self._scrapes: Dict[str, Dict[str, Any]] = {}

    # ------------------------------------------------------------------
    # lifecycle

    def _worker_cfg(self, wid: str):
        if self.cfg.journal_root:
            return dataclasses.replace(
                self.cfg.serve,
                journal_dir=os.path.join(self.cfg.journal_root, wid))
        return self.cfg.serve

    def _negotiate(self, advertised) -> str:
        if self.cfg.wire in ("auto", "binary") and "iaf2" in advertised:
            return "iaf2"
        return "json"

    def _spawn(self, wid: str, generation: int):
        codec = self._negotiate(self.transport.handle_cls.wire_formats)
        handle = self.transport.spawn(
            wid, generation, self._worker_cfg(wid), codec,
            scope_parent=self._scope,
            spawn_timeout_s=self.cfg.spawn_timeout_s)
        with self._lock:
            self.workers[wid] = handle
            self._misses[wid] = 0
            self._scrape_locked(wid, handle)
        obs_metrics.inc("router.wire.{}".format(codec), 0)
        return handle

    def start(self) -> "Fleet":
        if self._started:
            return self
        self._started = True
        # The fleet's own run scope (joins an ambient drill/test run
        # reentrantly): router counters written from caller threads
        # resolve here, and every worker scope chains into it.
        # With an autoscaling policy the fleet breathes: start at the
        # policy floor and let the control plane grow it under load.
        initial = (self.cfg.policy.min_workers if self.cfg.policy
                   else self.cfg.size)
        self._scope_exit.enter_context(obs_trace.run_scope(
            self.cfg.serve.params.replace(metrics=True),
            manifest_extra={"fleet": {"size": initial,
                                      "wire": self.cfg.wire,
                                      "vnodes": self.cfg.vnodes,
                                      "transport": self.cfg.transport,
                                      "autoscale": bool(self.cfg.policy)}}))
        self._scope = obs_metrics.current_scope()
        # Temporal plane: the health loop below is the fleet's sampling
        # cadence — arm the process timeline for the fleet's lifetime so
        # each poll lands worker-labeled windowed series in it.
        obs_timeline.arm()
        # Witness plane: with an archive root configured (env
        # IA_ARCHIVE_DIR — the fleet-operator path, like the catalog's
        # IA_CATALOG_DIR), the health loop also persists closed
        # timeline/tenants documents to sealed disk segments, and the
        # ceilings watchdog trends RSS / journal / archive growth.
        archive_root = os.environ.get("IA_ARCHIVE_DIR")
        self._archive_armed = bool(archive_root)
        if archive_root:
            obs_archive.arm(root=archive_root)
        obs_ceilings.arm(decision_log=self.decisions)
        for i in range(initial):
            wid = "w{}".format(i)
            self._spawn(wid, generation=0)
            self.router.ring.add(wid)
        # Catalog prefetch (ROADMAP item 4): with a catalog root
        # configured (env IA_CATALOG_DIR — the fleet-operator path),
        # pre-stage each style's sealed entries into host RAM now that
        # the ring knows every style's home worker, so the first request
        # for a cataloged style finds warm tiers instead of paying the
        # disk load (or the full build) inside the request path.
        from image_analogies_tpu.catalog import tiers as catalog_tiers

        if catalog_tiers.active():
            catalog_tiers.warm_for_fleet(self.router)
        self._health_thread = threading.Thread(
            target=self._health_loop, name="fleet-health", daemon=True)
        self._health_thread.start()
        return self

    def shutdown(self) -> None:
        if not self._started:
            return
        # Stop the health loop FIRST so a draining worker is not
        # mistaken for a dead one and "replaced" mid-shutdown.
        self._stop.set()
        if self._health_thread is not None:
            self._health_thread.join(5.0)
        for handle in list(self.workers.values()):
            handle.shutdown()
        if self.decisions is not None:
            self.decisions.close()
        obs_ceilings.disarm()
        if getattr(self, "_archive_armed", False):
            obs_archive.disarm()
            self._archive_armed = False
        obs_timeline.disarm()
        self._scope_exit.close()
        self._started = False

    def __enter__(self) -> "Fleet":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.shutdown()

    # ------------------------------------------------------------------
    # router-facing surface

    def default_params(self):
        return self.cfg.serve.params

    def gated(self, wid: str) -> bool:
        with self._lock:
            return wid in self._gates

    def gate_worker(self, wid: str, reason: str) -> None:
        """Ops/test hook: force-gate a worker (router spills its keys)."""
        with self._lock:
            self._gates[wid] = reason

    def ungate_worker(self, wid: str) -> None:
        with self._lock:
            self._gates.pop(wid, None)
        self.supervisor.reset(wid)

    def forward(self, wid: str, a, ap, b, params,
                deadline_s: Optional[float], idem: Optional[str],
                priority: int = 2) -> "Future[Response]":
        """One router->worker hop through the transport handle: request
        planes AND the trace context through the negotiated codec,
        submit, response planes back through the codec."""
        return self.workers[wid].forward(a, ap, b, params, deadline_s,
                                         idem, priority=priority)

    def submit(self, a, ap, b, params=None, deadline_s=None,
               idempotency_key=None,
               wire_bytes: int = 0, priority: int = 2
               ) -> "Future[Response]":
        """Client entry point — delegates to the router.  ``wire_bytes``
        (the fleet HTTP front end's body size) is accepted for submit_fn
        signature parity; the router->worker hop measures its own frame
        and that is what the worker-side cost vector records."""
        del wire_bytes
        return self.router.submit(a, ap, b, params=params,
                                  deadline_s=deadline_s,
                                  idempotency_key=idempotency_key,
                                  priority=priority)

    # ------------------------------------------------------------------
    # health gate loop

    def _judge(self, handle) -> Optional[str]:
        """None = healthy; "dead" = missed; else a gate reason.  The
        judgement itself moved to the control plane
        (ControlPlane.gate_verdict); this shim fetches the health doc
        and keeps the historical handle-facing surface."""
        try:
            h = handle.health()
        except Exception:  # noqa: BLE001 - unresponsive counts as dead
            h = None
        control = getattr(self, "control", None) or ControlPlane(self)
        return control.gate_verdict(h)

    def _scrape_locked(self, wid: str, handle) -> None:
        """Cache a metrics snapshot of the worker's registry (lock held).

        The health loop is the fleet's scrape cadence: each pass stores
        the worker's isolated registry snapshot plus when it was taken,
        so /healthz can report scrape freshness per worker and a merged
        view is available even for a worker that dies mid-interval.
        In-process that reads the chained scope registry; over the
        subprocess transport it is a /metrics.json fetch (None while
        the child is unreachable — keep the last good scrape).
        """
        snap = handle.snapshot()
        if snap is None:
            return
        self._scrapes[wid] = {
            "scope": handle.scope_id,
            "t": time.monotonic(),
            "snapshot": snap,
        }
        # Feed the temporal plane: the worker's isolated registry
        # becomes worker-labeled windowed series (counter deltas /
        # gauge last-values / windowed histograms) in the timeline —
        # delta logic there treats a replacement's reset counters as a
        # fresh generation, so wN keeps one continuous series across
        # incarnations.
        obs_timeline.sample_snapshot(snap, worker=wid)

    def _journal_bytes(self) -> Optional[float]:
        """Total on-disk bytes under the fleet journal root (segments,
        decision log, worker subdirs) — the ceilings watchdog's
        journal-growth series.  None (series skipped) without a root."""
        root = self.cfg.journal_root
        if not root:
            return None
        total = 0
        try:
            for dirpath, _dirs, files in os.walk(root):
                for name in files:
                    try:
                        total += os.path.getsize(
                            os.path.join(dirpath, name))
                    except OSError:
                        pass
        except OSError:
            return None
        return float(total)

    @staticmethod
    def _poll_phase(wid: str) -> float:
        """Deterministic per-worker fraction of the poll interval.

        N workers polled back-to-back at a fixed cadence scrape (and,
        over the subprocess transport, hit /healthz) in lockstep — a
        thundering herd that grows with the fleet.  Hashing the wid
        spreads the polls across the interval, stably per worker, with
        no shared state and no RNG."""
        digest = hashlib.sha256(wid.encode()).digest()
        return int.from_bytes(digest[:4], "big") / 2.0 ** 32

    def _health_loop(self) -> None:
        interval = self.cfg.health_interval_s
        if self._stop.wait(interval):
            return
        while True:
            if self._scope is not None:
                # Fleet-level series (router.* live only here) sampled
                # unlabeled, alongside the worker-labeled ones below.
                obs_timeline.sample_snapshot(self._scope.registry.snapshot())
            # Tenant metering plane: mirror the local ledger's tracked
            # tenants into tenant:<sha1[:8]>-labeled timeline series at
            # the same cadence (no-op when the plane is disarmed — e.g.
            # subprocess transport, where children sample their own).
            obs_ledger.sample_timeline()
            # Witness + watchdog planes (both no-ops when disarmed):
            # persist the current timeline/tenants documents to the
            # archive, and trend the resource-ceiling series.
            obs_archive.sample()
            obs_ceilings.sample(extra={
                "journal.bytes": self._journal_bytes()})
            # Jittered per-worker polls: visit workers in phase order,
            # sleeping the phase gap between them, so one pass still
            # takes ~interval but no two workers scrape in lockstep.
            healths: Dict[str, Optional[Dict[str, Any]]] = {}
            elapsed = 0.0
            for wid in sorted(list(self.workers), key=self._poll_phase):
                gap = self._poll_phase(wid) * interval - elapsed
                if gap > 0:
                    if self._stop.wait(gap):
                        return
                    elapsed += gap
                if self._stop.is_set():
                    return
                handle = self.workers.get(wid)
                if handle is None:
                    continue
                with self._lock:
                    if self._gates.get(wid) == "crash_loop":
                        # Parked by the supervisor: no polls, no
                        # respawns, until an operator ungates.
                        continue
                    self._scrape_locked(wid, handle)
                try:
                    h = handle.health()
                except Exception:  # noqa: BLE001 - unresponsive = dead
                    h = None
                healths[wid] = h
                verdict = self.control.gate_verdict(h)
                if verdict == "dead":
                    with self._lock:
                        self._misses[wid] = self._misses.get(wid, 0) + 1
                        misses = self._misses[wid]
                    if misses >= self.cfg.death_checks:
                        try:
                            self._replace(wid)
                        except Exception:  # noqa: BLE001 - keep looping
                            obs_metrics.inc("router.replace_errors")
                    continue
                with self._lock:
                    self._misses[wid] = 0
                    if verdict is None:
                        self._gates.pop(wid, None)
                    else:
                        self._gates[wid] = verdict
            # Autoscaling pass (no-op without a policy): the control
            # plane compares this pass's observed signals against the
            # declarative targets and spawns/retires through the
            # fleet's own primitives.
            if self.control.policy is not None:
                try:
                    self.control.reconcile(healths)
                except Exception:  # noqa: BLE001 - keep the loop alive
                    obs_metrics.inc("control.reconcile_errors")
            if self._stop.wait(max(0.0, interval - elapsed)):
                return

    # ------------------------------------------------------------------
    # death + journal handoff

    def _replace(self, wid: str):
        """Declare ``wid`` dead, hand its journal dir to a replacement,
        and let the router re-answer stranded futures.  Returns the
        replacement handle, or None when the crash-loop supervisor
        parked the slot instead."""
        old = self.workers[wid]
        uptime_s = time.monotonic() - getattr(old, "spawned_at", 0.0)
        with self._lock:
            self._gates[wid] = "dead"
        obs_metrics.inc("router.deaths")
        obs_trace.emit_record({"event": "router_death", "worker": wid,
                               "generation": old.generation})
        # Fleet verdicts are worker-scope (no idem): they feed counters,
        # `ia report`, and the decisions journal, but never a per-idem
        # chain — those steps come from the router's spill/rechain sites.
        if self.decisions is not None:
            self.decisions.record(None, "fleet", "death", "health_misses",
                                  worker_id=wid, generation=old.generation)
        # kill() releases the journal lock (in-process) or abandons it
        # on disk (subprocess SIGKILL — a real foreign stale lock); the
        # replacement's open() sweeps it, starts a fresh segment, and
        # recover() replays what's left.
        old.kill()
        verdict = self.supervisor.on_death(wid, uptime_s)
        if verdict["rapid"]:
            obs_metrics.inc("router.crash_loop_rapid")
        if verdict["gate"]:
            # Crash loop: park the slot instead of respawning forever.
            # Stranded futures get a terminal verdict — with no
            # replacement coming, hanging them would strand clients.
            obs_metrics.inc("router.crash_loops")
            obs_trace.emit_record({"event": "router_crash_loop",
                                   "worker": wid,
                                   "rapid": verdict["rapid"]})
            if self.decisions is not None:
                self.decisions.record(None, "fleet", "crash_loop",
                                      "rapid_deaths", worker_id=wid)
            with self._lock:
                self._gates[wid] = "crash_loop"
                self._misses[wid] = 0
            self.router.fail_pending(wid, Rejected("crash_loop"))
            return None
        if verdict["delay_s"]:
            obs_trace.emit_record({"event": "router_respawn_backoff",
                                   "worker": wid,
                                   "delay_s": verdict["delay_s"]})
            if self.decisions is not None:
                self.decisions.record(None, "fleet", "respawn_backoff",
                                      "recent_death", worker_id=wid,
                                      delay_s=verdict["delay_s"])
            if self._stop.wait(verdict["delay_s"]):
                return None  # fleet shutting down mid-backoff
        # Offline-compact the corpse's journal before the replacement
        # opens it: the dir is guaranteed writer-free in this window, so
        # a long-lived fleet's per-worker journals stay bounded by live
        # state instead of growing a segment per incarnation.  A
        # single-segment corpse (first kill) is skipped untouched —
        # the replacement keeps its historic handoff evidence (stale
        # lock sweep, contiguous segment numbering).  Refusal is safe —
        # the replacement just inherits the uncompacted history.
        if self.cfg.journal_root:
            serve_journal.autocompact(
                os.path.join(self.cfg.journal_root, wid))
        handle = self._spawn(wid, generation=old.generation + 1)
        recovered = handle.recovery_stats()
        obs_metrics.inc("router.handoffs")
        obs_trace.emit_record({"event": "router_handoff", "worker": wid,
                               "generation": handle.generation,
                               "recovered": recovered})
        if self.decisions is not None:
            self.decisions.record(None, "fleet", "handoff",
                                  "journal_inherited", worker_id=wid,
                                  generation=handle.generation)
        self.handoffs.append({"worker": wid,
                              "generation": handle.generation,
                              "recovered": recovered})
        with self._lock:
            self._gates.pop(wid, None)
            self._misses[wid] = 0
        self.router.on_worker_replaced(wid, handle)
        return handle

    # ------------------------------------------------------------------
    # observability

    def _worker_obs(self, wid: str, handle) -> Dict[str, Any]:
        """Obs identity for /healthz: which scope serves this wid and how
        stale the health loop's last scrape of it is."""
        with self._lock:
            scrape = self._scrapes.get(wid)
        obs: Dict[str, Any] = {
            "scope": handle.scope_id,
        }
        if scrape is not None:
            obs["last_scrape_age_s"] = round(
                time.monotonic() - scrape["t"], 3)
            if scrape["scope"] != obs["scope"]:
                obs["stale_scope"] = scrape["scope"]
        return obs

    def metrics_snapshots(self) -> Dict[str, Dict[str, Any]]:
        """Fresh per-worker registry snapshots keyed by wid (the
        federation input: each is the worker's ISOLATED view — chained
        scope registry in-process, /metrics.json over the subprocess
        transport)."""
        out: Dict[str, Dict[str, Any]] = {}
        for wid, handle in sorted(self.workers.items()):
            snap = handle.snapshot()
            if snap is not None:
                out[wid] = snap
        return out

    def tenants_doc(self) -> Dict[str, Any]:
        """Fleet-level ``/tenants``: the local ledger (in-process
        transport shares one module plane, so this is the whole fleet)
        merged with whatever each handle can scrape (subprocess children
        serve their own ``/tenants``).  Mergeable space-saving keeps the
        federated top-K an honest interval."""
        local = obs_ledger.tenants_doc()
        docs = [local]
        for _wid, handle in sorted(self.workers.items()):
            doc = handle.tenants()
            if doc is not None:
                docs.append(doc)
        merged = obs_tenants.merge_docs(docs)
        merged["armed"] = any(d.get("armed") for d in docs)
        merged["recorded"] = sum(int(d.get("recorded") or 0)
                                 for d in docs)
        uptime = max((float(d.get("uptime_s") or 0.0) for d in docs),
                     default=0.0)
        if uptime:
            merged["uptime_s"] = uptime
            for row in merged["tenants"]:
                row["qps"] = round(row.get("requests", 0) / uptime, 4)
        return merged

    def metrics_text(self, worker: Optional[str] = None) -> Optional[str]:
        """Prometheus exposition: merged fleet view with ``worker=<wid>``
        labeled series, or one worker's isolated view (``worker=``
        selector).  Returns None for an unknown (or unreachable) wid."""
        if worker is not None:
            handle = self.workers.get(worker)
            if handle is None:
                return None
            snap = handle.snapshot()
            if snap is None:
                return None
            return obs_live.render_prometheus(snap)
        extra = None
        if self._scope is not None:
            # Fleet-scope families the workers do not chain into
            # (router.*) ride along labeled worker="fleet"; worker-
            # chained families are filtered inside render_fleet so
            # nothing is double counted.
            extra = ("fleet", self._scope.registry.snapshot())
        return obs_fleet.render_fleet(self.metrics_snapshots(), extra=extra)

    def health(self) -> Dict[str, Any]:
        """Fleet /healthz view: per-worker liveness + readiness + ring
        membership."""
        workers: Dict[str, Any] = {}
        for wid, handle in sorted(self.workers.items()):
            try:
                h = handle.health()
                workers[wid] = {
                    "ok": h.get("ok", False),
                    "ready": bool(h.get("ready", h.get("ok", False))),
                    "recovering": bool(h.get("recovering", False)),
                    "generation": handle.generation,
                    "pid": handle.pid,
                    "codec": handle.codec,
                    "queue_depth": h.get("queue_depth", 0),
                    "breakers": h.get("breakers", {}),
                    "journal": h.get("journal"),
                    "gate": self._gates.get(wid),
                    "obs": self._worker_obs(wid, handle),
                }
            except Exception as exc:  # noqa: BLE001 - report, not raise
                workers[wid] = {"ok": False, "ready": False,
                                "error": str(exc),
                                "generation": handle.generation,
                                "pid": handle.pid,
                                "gate": self._gates.get(wid),
                                "obs": self._worker_obs(wid, handle)}
        return {
            "ok": all(w.get("ok") for w in workers.values()),
            # Live size: with an autoscaling policy the fleet breathes,
            # so /healthz reports what exists, not what was configured.
            "size": len(self.workers),
            "configured_size": self.cfg.size,
            "wire": self.cfg.wire,
            "transport": self.cfg.transport,
            "ring": {"members": self.router.ring.members(),
                     "vnodes": self.cfg.vnodes},
            "pending": self.router.pending_count(),
            "handoffs": len(self.handoffs),
            "control": self.control.status(),
            "workers": workers,
        }
