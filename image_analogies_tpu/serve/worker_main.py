"""Subprocess worker entry: ``python -m image_analogies_tpu.serve.worker_main``.

One fleet slot as a real OS process (spawned by
:class:`serve.transport.SubprocessTransport`).  The contract:

- Config arrives as ONE JSON document on stdin
  (``{"serve": <ServeConfig>, "wid", "generation", "port"}`` — see
  :func:`serve.transport.config_from_json`); nothing else is read.
- The worker opens its journal dir (the advisory lock now holds a REAL
  foreign pid from the fleet's point of view), replays recovery, binds
  a loopback-only HTTP socket (``port`` 0 = ephemeral), and only THEN
  reports ``{"pid", "port", "wid"}`` on the ``--ready-fd`` pipe —
  readiness means "answering", not "forked".
- Serves the standard surface: ``GET /healthz`` (liveness + readiness),
  ``GET /metrics`` (Prometheus) and ``/metrics.json`` (the registry
  snapshot the fleet federates), ``GET /tenants`` (the per-style cost
  document the fleet merges), ``POST /v1/analogy`` (IAF2 or JSON,
  ``X-IA-Trace`` adopted per hop).
- SIGTERM drains and exits 0 (graceful replace); SIGKILL is the death
  the fleet drills — journal lock left on disk, swept by the
  replacement.

Host-side only at module scope: no jax imports, no jit (the serve
grep-lock scans this file).  The engine loads inside Server.start().
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading
from http.server import ThreadingHTTPServer
from typing import Optional

from image_analogies_tpu.obs import ceilings as obs_ceilings
from image_analogies_tpu.obs import metrics as obs_metrics
from image_analogies_tpu.obs import timeline as obs_timeline
from image_analogies_tpu.obs import trace as obs_trace
from image_analogies_tpu.serve import http as serve_http
from image_analogies_tpu.serve import transport as serve_transport
from image_analogies_tpu.serve.server import Server


def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="worker_main",
        description="fleet subprocess worker (config on stdin)")
    ap.add_argument("--ready-fd", type=int, default=None,
                    help="fd to write the {pid, port} ready line to")
    args = ap.parse_args(argv)

    doc = json.loads(sys.stdin.read() or "{}")
    cfg = serve_transport.config_from_json(doc["serve"])
    wid = str(doc.get("wid", "w?"))
    generation = int(doc.get("generation", 0))
    port = int(doc.get("port", 0))

    # The child's ambient run scope IS its isolated worker registry —
    # per-process isolation replaces the in-process ObsScope chaining;
    # the fleet federates via /metrics.json instead of a parent scope.
    with obs_trace.run_scope(
            cfg.params.replace(metrics=True),
            manifest_extra={"worker": {"wid": wid,
                                       "generation": generation,
                                       "pid": os.getpid()}}):
        server = Server(cfg).start()

        # Per-process temporal plane: the child samples its own registry
        # (the fleet cannot reach across the process boundary to do it)
        # so GET /timeline answers live windows, and the ceilings
        # watchdog trends this worker's own RSS — a leaking child emits
        # its own obs.ceiling.* alarms and decision records.
        tl = obs_timeline.arm()
        obs_ceilings.arm()
        tl.start_sampler(interval_s=1.0)

        def _snapshot():
            return obs_metrics.snapshot() or {}

        handler = serve_http._make_handler_from(
            server.health, server.submit, server.refresh_gauges,
            snapshot_fn=_snapshot, tenants_fn=server.tenants_doc)
        httpd = ThreadingHTTPServer(("127.0.0.1", port), handler)
        bound_port = httpd.server_address[1]

        stop = threading.Event()

        def _on_term(signum, frame):  # noqa: ARG001 - signal API
            stop.set()

        signal.signal(signal.SIGTERM, _on_term)
        signal.signal(signal.SIGINT, _on_term)

        http_thread = threading.Thread(
            target=httpd.serve_forever,
            name="{}-http".format(wid), daemon=True)
        http_thread.start()

        if args.ready_fd is not None:
            line = json.dumps({"pid": os.getpid(), "port": bound_port,
                               "wid": wid, "generation": generation})
            os.write(args.ready_fd, (line + "\n").encode())
            os.close(args.ready_fd)

        stop.wait()
        httpd.shutdown()
        obs_ceilings.disarm()
        obs_timeline.disarm()
        server.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
