"""Dispatch circuit breaker: fail fast when the engine is failing hard.

A wedged or broken backend turns every dispatch into a slow failure —
each one burns a worker for the full retry budget while the queue backs
up behind it.  The breaker converts that into fast, honest rejection:

- **closed** (normal): dispatches flow; each failure bumps a consecutive
  counter, any success resets it.
- **open**: after ``threshold`` consecutive failures the breaker trips.
  Requests fail immediately with ``Rejected("circuit_open")`` — no
  dispatch, no retry burn — for ``cooldown_s`` seconds.
- **half_open**: after the cooldown, exactly ONE probe dispatch is let
  through.  Success closes the breaker; failure re-opens it for another
  cooldown.

The admission layer consults :meth:`admission_open` — a non-claiming
read that is True only while the breaker is open with the cooldown
unelapsed — so ``submit()`` can shed with ``Rejected("breaker_open")``
one hop before the queue without stealing the half-open probe slot.
State is exported live as the gauge ``serve.breaker.state.<backend>``
(closed=0, half_open=1, open=2) for the /metrics exposition.

``threshold=0`` disables the breaker entirely (every ``allow()`` is
True, ``admission_open()`` is False, nothing is counted).  The clock is
injectable so tests drive the state machine without sleeping.
"""

from __future__ import annotations

import threading
import time
from typing import Callable

from image_analogies_tpu.obs import metrics as obs_metrics
from image_analogies_tpu.obs import recorder as obs_recorder
from image_analogies_tpu.obs import trace as obs_trace


_STATE_CODE = {"closed": 0, "half_open": 1, "open": 2}


class CircuitBreaker:
    def __init__(self, threshold: int, cooldown_s: float,
                 clock: Callable[[], float] = time.monotonic,
                 backend: str = "default"):
        self._threshold = int(threshold)
        self._cooldown_s = float(cooldown_s)
        self._clock = clock
        self.backend = str(backend)
        self._lock = threading.Lock()
        self._state = "closed"
        self._consecutive = 0
        self._opened_at = 0.0
        self._probing = False  # half_open: one probe slot, taken or not

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def export_state(self) -> None:
        """Publish the per-backend state gauge (closed=0, half_open=1,
        open=2).  Called on every transition and once at pool start so
        the gauge exists from the first scrape."""
        with self._lock:
            self._export_locked()

    def _export_locked(self) -> None:
        obs_metrics.set_gauge(f"serve.breaker.state.{self.backend}",
                              _STATE_CODE[self._state])

    def admission_open(self) -> bool:
        """Non-claiming read for the admission layer: True only while the
        breaker is open AND the cooldown has not elapsed.  Once the
        cooldown expires this returns False even before a probe runs, so
        the half-open probe request can flow through ``submit()``."""
        if self._threshold <= 0:
            return False
        with self._lock:
            return (self._state == "open"
                    and self._clock() - self._opened_at < self._cooldown_s)

    def allow(self) -> bool:
        """May a dispatch proceed right now?  In half_open this CLAIMS the
        single probe slot, so exactly one caller gets True per cooldown —
        the caller must follow up with record_success/record_failure."""
        if self._threshold <= 0:
            return True
        with self._lock:
            if self._state == "closed":
                return True
            if self._state == "open":
                if self._clock() - self._opened_at < self._cooldown_s:
                    obs_metrics.inc("serve.breaker.fast_fails")
                    return False
                self._state = "half_open"
                self._probing = False
                self._export_locked()
                obs_trace.emit_record({"event": "breaker_half_open"})
            # half_open: hand out the one probe slot
            if self._probing:
                obs_metrics.inc("serve.breaker.fast_fails")
                return False
            self._probing = True
            obs_metrics.inc("serve.breaker.probes")
            return True

    def record_success(self) -> None:
        if self._threshold <= 0:
            return
        with self._lock:
            if self._state != "closed":
                obs_trace.emit_record({"event": "breaker_closed"})
            self._state = "closed"
            self._consecutive = 0
            self._probing = False
            self._export_locked()

    def record_failure(self) -> None:
        if self._threshold <= 0:
            return
        with self._lock:
            if self._state == "half_open":
                # probe failed: straight back to open, fresh cooldown
                self._trip()
                return
            self._consecutive += 1
            if self._state == "closed" and self._consecutive >= self._threshold:
                self._trip()

    def _trip(self) -> None:
        # lock held by callers
        self._state = "open"
        self._opened_at = self._clock()
        self._consecutive = 0
        self._probing = False
        self._export_locked()
        obs_metrics.inc("serve.breaker.trips")
        obs_trace.emit_record({"event": "breaker_open",
                               "cooldown_s": self._cooldown_s})
        # A trip means the last `threshold` dispatches all failed — dump
        # the flight ring while the evidence is still in it (no-op when
        # the current scope has no dump dir; never raises).
        obs_recorder.dump_current("breaker_open",
                                  extra={"backend": self.backend,
                                         "cooldown_s": self._cooldown_s})
